"""Sampling utilities: growing reservoir sampling and hypergeometric splits.

Equivalents of the reference's ReservoirSamplingGrow
(reference: thrill/common/reservoir_sampling.hpp:174, used by api/sort.hpp:303
to collect splitter candidates) and hypergeometric_distribution
(reference: thrill/common/hypergeometric_distribution.hpp, used by
api/sample.hpp:235 to split a global sample budget across workers).
"""

from __future__ import annotations

import math
from typing import Generic, List, TypeVar

import numpy as np

T = TypeVar("T")


class ReservoirSamplingGrow(Generic[T]):
    """Reservoir sampling whose reservoir grows with the stream.

    Maintains a uniform sample of size ~ ``desired_imbalance**-2 * log2(n)``
    style growth: the reference grows the reservoir so relative splitter
    error stays bounded as more items arrive. We implement the same
    behavior with a simpler growth rule: size = max(min_size,
    ceil(growth_factor * sqrt(n))) capped at max_size.
    """

    def __init__(self, rng: np.random.Generator, min_size: int = 128,
                 max_size: int = 1 << 16, growth_factor: float = 4.0) -> None:
        self.rng = rng
        self.min_size = min_size
        self.max_size = max_size
        self.growth_factor = growth_factor
        self.count = 0
        self.samples: List[T] = []

    def desired_size(self) -> int:
        if self.count <= 0:
            return self.min_size
        want = int(math.ceil(self.growth_factor * math.sqrt(self.count)))
        return max(self.min_size, min(self.max_size, want))

    def add(self, item: T) -> None:
        self.count += 1
        size = self.desired_size()
        if self.count <= size:
            # stream shorter than reservoir: keep everything
            self.samples.append(item)
            return
        # admit with probability size/count even when the reservoir has
        # just grown (len < size); unconditional append here would bias
        # the sample toward items at growth boundaries
        j = int(self.rng.integers(0, self.count))
        if j < size:
            if len(self.samples) < size:
                self.samples.append(item)
            else:  # len == size here, so j indexes in range
                self.samples[j] = item

    def add_batch(self, items) -> None:
        for it in items:
            self.add(it)

    def add_batch_indexed(self, start: int, items) -> None:
        """Vectorized batch add of ``(start + i, items[i])`` pairs.

        Same admission distribution as per-item :meth:`add` (each item
        draws j ~ U[0, its running count) and is admitted iff
        j < desired_size at that count) with ONE vectorized draw per
        batch; pair tuples are only constructed for admitted items.
        The EM sort's spill loop calls this per run chunk — per-item
        Python sampling was a profiled hotspot there."""
        m = len(items)
        if m == 0:
            return
        i = 0
        # fill phase (stream shorter than the growing reservoir):
        # bounded by max(min_size, ...) early counts — rare past startup
        while i < m:
            self.count += 1
            if self.count > self.desired_size():
                self.count -= 1
                break
            self.samples.append((start + i, items[i]))
            i += 1
        if i == m:
            return
        counts = np.arange(self.count + 1, self.count + (m - i) + 1)
        sizes = np.clip(
            np.ceil(self.growth_factor * np.sqrt(counts)),
            self.min_size, self.max_size).astype(np.int64)
        draws = self.rng.integers(0, counts)
        self.count += m - i
        for k in np.flatnonzero(draws < sizes):
            item = (start + i + int(k), items[i + int(k)])
            j = int(draws[k])
            if len(self.samples) < int(sizes[k]):
                self.samples.append(item)
            else:
                self.samples[j] = item

    def sample_rate(self) -> float:
        if self.count == 0:
            return 1.0
        return len(self.samples) / self.count


def hypergeometric_split(rng: np.random.Generator, total_samples: int,
                         counts: np.ndarray) -> np.ndarray:
    """Split a global sample budget over partitions w/o communication bias.

    Given per-worker item counts, returns per-worker sample counts whose sum
    is ``total_samples``, distributed according to the multivariate
    hypergeometric distribution — i.e. exactly as if sampling
    ``total_samples`` items without replacement from the concatenation.
    Reference: thrill/api/sample.hpp:235 uses sequential hypergeometric
    draws the same way.
    """
    counts = np.asarray(counts, dtype=np.int64)
    n = int(counts.sum())
    k = min(int(total_samples), n)
    out = np.zeros(len(counts), dtype=np.int64)
    remaining_pop = n
    remaining_k = k
    for i, c in enumerate(counts):
        if remaining_k <= 0:
            break
        c = int(c)
        if remaining_pop <= c:
            out[i] = remaining_k
            remaining_k = 0
            break
        draw = int(rng.hypergeometric(c, remaining_pop - c, remaining_k))
        out[i] = draw
        remaining_k -= draw
        remaining_pop -= c
    return out
