"""Tracing spine: correlated spans across every layer.

The JsonLogger (common/logger.py) records flat event lines; this module
adds the CORRELATION the grown system needs: lightweight spans
(``trace_id``/``span_id``/``parent``) with a category lane per
subsystem, tagged with rank, generation (PR-8 failure domains), tenant
and job name (PR-9 service plane) — so a Perfetto timeline can show
*which dispatch, in which exchange, of which job, on which rank* was on
the critical path. Instrumented at the natural choke points the earlier
refactors created:

* ``parallel/mesh.py::_CountedJit.__call__`` — every device dispatch
  (cat ``dispatch``), including the whole-loop fori program;
* ``api/fusion.py::FusionPlan.execute`` — stitched segments (``fusion``);
* ``data/exchange.py`` — phase A / chunked phase B / optimistic-vs-
  synced verdicts / capacity-miss heals (``exchange``);
* ``data/multiplexer.py`` — host frames + async sends (``host``);
* ``net/group.py`` — collectives, generation heals (``net``);
* ``mem/pressure.py`` — escalation-ladder rungs (``mem``);
* ``api/loop.py`` — capture/replay/fori iterations (``loop``);
* ``service/scheduler.py`` — queue-wait and run per job (``service``).

Spans emit through the existing JsonLogger as ``event=span`` lines
(json2profile ignores unknown events, so the HTML report keeps
working) and ``tools/trace2perfetto.py`` exports Chrome-trace-event
JSON — one pid lane per rank, one tid lane per subsystem — that loads
directly in Perfetto / chrome://tracing.

Two always-on companions make this production-shaped:

* **Flight recorder**: every finished span/instant also lands in a
  bounded in-memory ring (``THRILL_TPU_TRACE_RING`` records, default
  512 — a deque append, near-zero cost when file logging is off). The
  moment a pipeline aborts (PipelineError/ClusterAbort/unrecoverable
  verdict, api/context.py hooks) the ring dumps to a timestamped file
  under ``THRILL_TPU_FLIGHT_DIR`` — a self-contained post-mortem whose
  final spans name the failing site and generation. The dump header
  records the THRILL_TPU_FAULTS arming, so chaos-sweep archives carry
  the seed that produced each failure.
* **Live metrics**: common/metrics.py serves ``overall_stats`` +
  service gauges in Prometheus text format from a daemon thread
  (``THRILL_TPU_METRICS_PORT``).

Overhead contract: ``THRILL_TPU_TRACE=0`` is a pinned no-op fast path
— the dispatch choke point pays ONE attribute read plus one predicate
check and allocates no span objects (tests/common/test_trace.py pins
this via the module's ``SPANS_CREATED`` counter).
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, Optional

#: total Span objects ever allocated in this process — the pin the
#: THRILL_TPU_TRACE=0 no-op test asserts stays flat across dispatches
SPANS_CREATED = 0

#: shared do-nothing context manager for the disabled path (stateless,
#: so one instance serves every call site)
_NULL = contextlib.nullcontext()

_FLIGHT_SEQ = itertools.count()


def trace_enabled() -> bool:
    """THRILL_TPU_TRACE=0 disables span creation everywhere (read once
    per Tracer, at Context construction)."""
    from .config import _env_flag
    return _env_flag("THRILL_TPU_TRACE", True)


def _env_int_clamped(name: str, default: int, lo: int) -> int:
    from .config import _env_int
    try:
        return max(_env_int(name, default), lo)
    except ValueError:
        return default


def ring_capacity() -> int:
    """THRILL_TPU_TRACE_RING: flight-recorder ring size in records
    (default 512; 0 disables the ring and with it the flight dumps)."""
    return _env_int_clamped("THRILL_TPU_TRACE_RING", 512, 0)


def flight_dir() -> Optional[str]:
    """Directory flight-recorder dumps land in. Default: a per-USER
    stable path under the system temp dir (the recorder is always on;
    a shared fixed path would be owned by whichever user ran first and
    silently unwritable for everyone else);
    ``THRILL_TPU_FLIGHT_DIR=0|off|none`` disables dumps entirely."""
    v = os.environ.get("THRILL_TPU_FLIGHT_DIR")
    if v in ("0", "off", "none"):
        return None
    if v:
        return v
    import tempfile
    uid = getattr(os, "getuid", lambda: "u")()
    return os.path.join(tempfile.gettempdir(),
                        f"thrill_tpu_flight-{uid}")


def _flight_keep() -> int:
    """Newest-N dump files kept per directory (THRILL_TPU_FLIGHT_KEEP,
    default 40) — an abort-heavy chaos sweep must not fill the disk."""
    return _env_int_clamped("THRILL_TPU_FLIGHT_KEEP", 40, 1)


class Span:
    """One timed region. Context-manager: exceptions escaping the block
    are recorded as an ``error`` attribute before the span finishes —
    the flight recorder's final spans name the failing site this way."""

    __slots__ = ("tracer", "span_id", "parent", "cat", "name", "ts_us",
                 "t0", "t1", "attrs", "generation", "tenant", "job")

    def __init__(self, tracer: "Tracer", span_id: int,
                 parent: Optional[int], cat: str, name: str,
                 attrs: Dict[str, Any]) -> None:
        self.tracer = tracer
        self.span_id = span_id
        self.parent = parent
        self.cat = cat
        self.name = name
        self.attrs = attrs
        self.ts_us = tracer._now_us()
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.generation = tracer.gen_fn() if tracer.gen_fn is not None \
            else None
        self.tenant = tracer.tenant_fn() if tracer.tenant_fn is not None \
            else None
        self.job = tracer.current_job

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, et, ev, tb) -> None:
        if ev is not None:
            self.attrs["error"] = repr(ev)[:200]
        self.tracer.end(self)

    def rec(self) -> dict:
        r = {"event": "span", "cat": self.cat, "name": self.name,
             "trace": self.tracer.trace_id, "span": self.span_id,
             "rank": self.tracer.rank, "ts": self.ts_us,
             "dur_us": int(((self.t1 if self.t1 is not None
                             else time.perf_counter()) - self.t0) * 1e6)}
        if self.parent is not None:
            r["parent"] = self.parent
        if self.generation is not None:
            r["generation"] = self.generation
        if self.tenant is not None:
            r["tenant"] = self.tenant
        if self.job is not None:
            r["job"] = self.job
        r.update(self.attrs)
        return r


class Tracer:
    """Per-Context span factory + flight-recorder ring.

    Attached as ``mesh_exec.tracer`` / ``net.group.tracer`` /
    ``ctx.tracer`` so every choke point reaches it in one attribute
    read; ``enabled`` False (THRILL_TPU_TRACE=0) makes every guarded
    site skip span allocation entirely. Propagation is EXPLICIT: a
    per-thread span stack supplies the parent id; cross-thread workers
    (the async host sender) pass ``parent=`` captured on the
    submitting thread."""

    def __init__(self, rank: int = 0, logger=None,
                 ring: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        self.enabled = trace_enabled() if enabled is None else enabled
        self.rank = rank
        self.logger = logger
        cap = ring_capacity() if ring is None else ring
        self.ring: Optional[collections.deque] = \
            collections.deque(maxlen=cap) if cap > 0 else None
        self.trace_id = f"{os.getpid():x}.{rank}"
        self._ids = itertools.count(1)
        self._tls = threading.local()
        # context binders (Context sets them): generation / tenant of
        # the moment a span STARTS; the scheduler sets current_job
        # around each served job so nested spans carry the job name
        self.gen_fn = None
        self.tenant_fn = None
        self.current_job: Optional[str] = None
        # finished spans per category lane (bench.py trace lane counts)
        self.lane_counts: Dict[str, int] = {}
        if logger is not None and hasattr(logger, "now_us"):
            self._now_us = logger.now_us
        else:
            wall0, perf0 = time.time(), time.perf_counter()
            self._now_us = lambda: int(
                (wall0 + time.perf_counter() - perf0) * 1e6)

    # -- span lifecycle -------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_id(self) -> Optional[int]:
        """The calling thread's innermost open span id (for explicit
        cross-thread parenting)."""
        st = getattr(self._tls, "stack", None)
        return st[-1].span_id if st else None

    def span(self, cat: str, name: str, parent: Optional[int] = None,
             **attrs: Any) -> Span:
        """Open a span (use as a context manager). ``parent`` defaults
        to the calling thread's innermost open span."""
        return self.begin(cat, name, parent=parent, **attrs)

    def begin(self, cat: str, name: str, parent: Optional[int] = None,
              **attrs: Any) -> Span:
        """Open a span without the context-manager protocol (callers
        with early-exit control flow pair it with ``end`` in a
        try/finally)."""
        global SPANS_CREATED
        SPANS_CREATED += 1
        st = self._stack()
        if parent is None and st:
            parent = st[-1].span_id
        sp = Span(self, next(self._ids), parent, cat, name, attrs)
        st.append(sp)
        return sp

    def end(self, sp: Span, **attrs: Any) -> None:
        sp.t1 = time.perf_counter()
        if attrs:
            sp.attrs.update({k: v for k, v in attrs.items()
                             if v is not None})
        st = getattr(self._tls, "stack", None)
        if st:
            # pop the span plus anything leaked above it (an exception
            # that skipped a child's end must not corrupt parenting)
            for i in range(len(st) - 1, -1, -1):
                if st[i] is sp:
                    del st[i:]
                    break
        self.lane_counts[sp.cat] = self.lane_counts.get(sp.cat, 0) + 1
        self._record(sp.rec())

    def emit_span(self, cat: str, name: str, start_s: float,
                  end_s: float, parent: Optional[int] = None,
                  **attrs: Any) -> None:
        """Record an already-elapsed region measured with
        ``time.perf_counter()`` (the scheduler's queue-wait bar: the
        wait happened before the span could be opened)."""
        if not self.enabled:
            return
        now_us = self._now_us()
        elapsed_us = int(max(time.perf_counter() - start_s, 0.0) * 1e6)
        rec = {"event": "span", "cat": cat, "name": name,
               "trace": self.trace_id, "span": next(self._ids),
               "rank": self.rank, "ts": now_us - elapsed_us,
               "dur_us": int(max(end_s - start_s, 0.0) * 1e6)}
        if parent is not None:
            rec["parent"] = parent
        if self.gen_fn is not None:
            rec["generation"] = self.gen_fn()
        rec.update({k: v for k, v in attrs.items() if v is not None})
        self.lane_counts[cat] = self.lane_counts.get(cat, 0) + 1
        self._record(rec)

    def instant(self, cat: str, name: str, **attrs: Any) -> None:
        """Zero-duration marker (ladder rungs, exchange verdicts)."""
        if not self.enabled:
            return
        rec = {"event": "span", "kind": "instant", "cat": cat,
               "name": name, "trace": self.trace_id,
               "span": next(self._ids), "rank": self.rank,
               "ts": self._now_us(), "dur_us": 0}
        pid = self.current_id()
        if pid is not None:
            rec["parent"] = pid
        if self.gen_fn is not None:
            rec["generation"] = self.gen_fn()
        if self.tenant_fn is not None:
            t = self.tenant_fn()
            if t is not None:
                rec["tenant"] = t
        if self.current_job is not None:
            rec["job"] = self.current_job
        rec.update({k: v for k, v in attrs.items() if v is not None})
        # instants count toward the lane totals too: the mem lane is
        # emitted EXCLUSIVELY as instants (ladder rungs) and must show
        # up in bench trace_spans / the trace_spans metric
        self.lane_counts[cat] = self.lane_counts.get(cat, 0) + 1
        self._record(rec)

    def _record(self, rec: dict) -> None:
        if self.ring is not None:
            self.ring.append(rec)
        log = self.logger
        if log is not None and log.enabled:
            log.line(**rec)

    # -- flight recorder ------------------------------------------------
    def dump_flight(self, reason: Any, generation: Optional[int] = None
                    ) -> Optional[str]:
        """Write the ring's records to a timestamped post-mortem file.
        Best-effort by contract: returns the path, or None when the
        recorder is disabled (tracing off / no ring /
        THRILL_TPU_FLIGHT_DIR=0), the ring is empty (a header-only
        dump would only churn the keep-N rotation — the TRACE=0 abort
        path writes nothing), or the write fails — a failing dump must
        never mask the abort being recorded."""
        if not self.enabled or not self.ring:
            return None
        d = flight_dir()
        if d is None:
            return None
        recs = list(self.ring)
        from . import faults
        header = {"event": "flight_header",
                  "reason": str(reason)[:300],
                  "generation": generation, "rank": self.rank,
                  "trace": self.trace_id, "ts": self._now_us(),
                  "records": len(recs),
                  "faults": os.environ.get(faults.ENV_VAR) or None}
        name = (f"flight-{int(time.time() * 1e3)}-p{os.getpid()}"
                f"-r{self.rank}-{next(_FLIGHT_SEQ)}.json")
        path = os.path.join(d, name)
        try:
            os.makedirs(d, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps(header, default=str) + "\n")
                for r in recs:
                    f.write(json.dumps(r, default=str) + "\n")
        except OSError:
            return None
        try:
            _prune(d, _flight_keep())
        except OSError:
            pass
        return path


def _prune(d: str, keep: int) -> None:
    """Drop all but the newest ``keep`` flight dumps in ``d`` — along
    with each pruned dump's decision-ledger sibling
    (``decisions-*.json``, common/decisions.py), which would otherwise
    accumulate unboundedly under an abort-heavy chaos sweep."""
    files = [os.path.join(d, f) for f in os.listdir(d)
             if f.startswith("flight-") and f.endswith(".json")]
    if len(files) <= keep:
        return
    files.sort(key=lambda p: os.path.getmtime(p), reverse=True)
    for p in files[keep:]:
        for victim in (p, os.path.join(
                os.path.dirname(p), "decisions-"
                + os.path.basename(p)[len("flight-"):])):
            try:
                os.unlink(victim)
            except OSError:
                pass


def span_of(tracer: Optional[Tracer], cat: str, name: str,
            **attrs: Any):
    """``tracer.span(...)`` when tracing is live, the shared null
    context otherwise — the one-liner guard for call sites where a
    with-block reads best."""
    if tracer is not None and tracer.enabled:
        return tracer.span(cat, name, **attrs)
    return _NULL


def instant_of(tracer: Optional[Tracer], cat: str, name: str,
               **attrs: Any) -> None:
    """Guarded instant: the one-liner the marker sites (ladder rungs,
    reconnects, fusion degradations) share instead of each carrying
    the None/enabled check."""
    if tracer is not None and tracer.enabled:
        tracer.instant(cat, name, **attrs)
