"""Hash functions for partitioning and sketches.

Equivalent of the reference's hash utilities
(reference: thrill/common/hash.hpp — CRC32-based tabulation hashing used
by the reduce tables and HyperLogLog). On the device path we use a
splitmix64-style finalizer over packed 64-bit key words — multiplicative
mixing maps well onto the TPU VPU, unlike table lookups.
"""

from __future__ import annotations

import numpy as np


def _require_jnp():
    import jax.numpy as jnp
    return jnp


# splitmix64 finalizer constants
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def mix64(x):
    """splitmix64 finalizer on a uint64 array (jnp or np)."""
    jnp = _require_jnp()
    x = x.astype(jnp.uint64)
    if x.dtype != jnp.uint64:  # x64 disabled would silently truncate
        raise RuntimeError("thrill_tpu requires JAX x64 mode for 64-bit hashing")
    x = x ^ (x >> np.uint64(30))
    x = x * _C1
    x = x ^ (x >> np.uint64(27))
    x = x * _C2
    x = x ^ (x >> np.uint64(31))
    return x


def hash_combine64(h, x):
    """Combine a new uint64 word into a running hash (boost-style)."""
    jnp = _require_jnp()
    h = h.astype(jnp.uint64)
    return mix64(h ^ (x.astype(jnp.uint64) + _GOLDEN + (h << np.uint64(6)) + (h >> np.uint64(2))))


def hash_key_words(words) -> "object":
    """Hash a list of equally-shaped uint64 arrays into one uint64 array."""
    jnp = _require_jnp()
    assert len(words) >= 1
    h = mix64(words[0].astype(jnp.uint64) + _GOLDEN)
    for w in words[1:]:
        h = hash_combine64(h, w)
    return h


def np_mix64(x: np.ndarray) -> np.ndarray:
    """NumPy version of mix64 (host path)."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64)
        x = x ^ (x >> np.uint64(30))
        x = x * _C1
        x = x ^ (x >> np.uint64(27))
        x = x * _C2
        x = x ^ (x >> np.uint64(31))
    return x


def stable_host_hash(obj) -> int:
    """Deterministic 64-bit hash of a Python object (host path).

    Strings/bytes hash by content (FNV-1a); ints by splitmix64; tuples
    combine recursively. Unlike builtin ``hash``, not salted per process,
    so multi-host partitioning is consistent.
    """
    if isinstance(obj, bytes):
        return _fnv1a(obj)
    if isinstance(obj, str):
        return _fnv1a(obj.encode("utf-8"))
    # numeric tower: values that compare equal must hash equal
    # (True == 1, 5.0 == 5, -0.0 == 0.0), like Python's own hash contract
    if isinstance(obj, (bool, np.bool_)):
        obj = int(obj)
    if isinstance(obj, (float, np.floating)):
        f = float(obj) + 0.0          # normalizes -0.0 -> +0.0
        if f.is_integer():
            obj = int(f)              # int path below wraps mod 2^64,
            # keeping hash(2.0**64) == hash(2**64) like Python equality
        else:
            return int(np_mix64(np.float64(f).view(np.uint64)))
    if isinstance(obj, (int, np.integer)):
        return int(np_mix64(np.uint64(int(obj) & 0xFFFFFFFFFFFFFFFF)))
    if isinstance(obj, tuple):
        h = np.uint64(0x9E3779B97F4A7C15)
        for el in obj:
            with np.errstate(over="ignore"):
                h = np_mix64(h ^ np.uint64(stable_host_hash(el)))
        return int(h)
    # Fallback: repr bytes (slow but total).
    return _fnv1a(repr(obj).encode("utf-8"))


def _fnv1a(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h
