"""Deterministic, seeded fault injection.

The reference framework's only failure story is die-with-parent
process hygiene (reference: thrill/api/context.cpp:849-878) — its
recovery paths are untestable because nothing can *provoke* a fault on
demand. This registry makes every failure mode in this framework a
named, seeded, reproducible event:

* Code declares **sites** at import time (``declare("net.tcp.send",
  kind="transient")``) and calls ``check("net.tcp.send")`` at the
  matching operation. With no injection configured the check is a dict
  lookup — effectively free.
* Operators/tests arm sites via ``THRILL_TPU_FAULTS`` (or the
  :func:`inject` context manager). Spec grammar, semicolon-separated::

      THRILL_TPU_FAULTS="net.tcp.send:p=0.5:n=2:seed=7;vfs.*:n=1"

  - site name or ``fnmatch`` pattern (``net.*``)
  - ``p=<float>``  per-hit fire probability (default 1.0)
  - ``n=<int>``    max fires for this entry (default 1; ``n=0`` =
    unbounded)
  - ``seed=<int>`` RNG seed; the stream is derived from
    ``(seed, site)`` so two sites armed by one pattern fire
    independently but reproducibly (default 0)
  - ``after=<int>`` skip the first k eligible hits (default 0)
  - ``delay=<dur>`` LATENCY mode: a fire SLEEPS for ``<dur>``
    (``50ms``, ``2s`` or plain seconds) at the site instead of
    raising — the deterministic way to CREATE a slow rank or a slow
    disk, so straggler attribution (common/doctor.py) is testable
    without real contention. Delayed fires are counted separately
    (``faults_delayed``) and logged with ``kind=delay``.
* Every trigger is recorded in :data:`REGISTRY` and logged as a JSON
  ``event=fault_injected`` line (visible to tools/json2profile.py)
  when a logger is attached (api/context.py attaches the Context's).

A fired check raises :class:`InjectedConnectionError` /
:class:`InjectedIOError` / :class:`InjectedFault` per the site's
declared exception class, so the *real* error-handling paths — the
retry policy in common/retry.py, the poison-abort protocol in
net/group.py — are what the injection exercises; nothing is mocked.
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
from typing import Any, Callable, Dict, List, Optional

ENV_VAR = "THRILL_TPU_FAULTS"

TRANSIENT = "transient"
PERMANENT = "permanent"


class InjectedFault(Exception):
    """Base class of every injected error; ``site`` names the origin."""

    def __init__(self, site: str, kind: str = TRANSIENT) -> None:
        super().__init__(f"injected fault at site '{site}' ({kind})")
        self.site = site
        self.kind = kind


class InjectedConnectionError(InjectedFault, ConnectionError):
    """Injected transport fault (dropped socket, failed frame)."""


class InjectedIOError(InjectedFault, IOError):
    """Injected storage fault (flaky object-store read, spill I/O)."""


class Site:
    """A declared injection point."""

    def __init__(self, name: str, kind: str, exc: type) -> None:
        self.name = name
        self.kind = kind            # failure class the site simulates
        self.exc = exc
        self.hits = 0               # check() calls while armed
        self.fires = 0              # faults actually raised


class _Arm:
    """One armed spec entry (pattern, probability, budget, RNG)."""

    def __init__(self, pattern: str, p: float, n: int, seed: int,
                 after: int, delay: Optional[float] = None) -> None:
        self.pattern = pattern
        self.p = p
        self.n = n                  # 0 = unbounded
        self.seed = seed
        self.after = after
        self.delay = delay          # seconds to sleep instead of raise
        self._rngs: Dict[str, random.Random] = {}
        self._fired: Dict[str, int] = {}
        self._seen: Dict[str, int] = {}

    def matches(self, site: str) -> bool:
        return site == self.pattern or fnmatch.fnmatchcase(site,
                                                           self.pattern)

    def fire(self, site: str) -> bool:
        """Deterministic per-(entry, site) decision stream."""
        seen = self._seen.get(site, 0)
        self._seen[site] = seen + 1
        if seen < self.after:
            return False
        if self.n and self._fired.get(site, 0) >= self.n:
            return False
        rng = self._rngs.get(site)
        if rng is None:
            rng = self._rngs[site] = random.Random(f"{self.seed}:{site}")
        if self.p < 1.0 and rng.random() >= self.p:
            return False
        self._fired[site] = self._fired.get(site, 0) + 1
        return True


class FaultRegistry:
    """Site table + armed spec, re-parsed when the env string changes."""

    def __init__(self) -> None:
        self.sites: Dict[str, Site] = {}
        self.events: List[dict] = []      # recent fault_injected records
        self.injected = 0                 # total faults raised
        self.delayed = 0                  # latency-mode fires (slept)
        self.retries = 0                  # retry-policy sleeps taken
        self.recoveries = 0               # successful recovery events
        self.aborts = 0                   # poison frames broadcast
        self._arms: List[_Arm] = []
        self._spec: Optional[str] = None
        self._log: Optional[Callable[..., None]] = None
        self._lock = threading.Lock()

    # -- declaration ---------------------------------------------------
    def declare(self, name: str, kind: str = TRANSIENT,
                exc: type = InjectedIOError) -> str:
        site = self.sites.get(name)
        if site is None:
            self.sites[name] = Site(name, kind, exc)
        return name

    # -- arming --------------------------------------------------------
    def _sync(self) -> None:
        spec = os.environ.get(ENV_VAR, "")
        if spec == self._spec:
            return
        self._spec = spec
        self._arms = parse_spec(spec)

    def armed(self, site: str) -> bool:
        with self._lock:
            self._sync()
            return any(a.matches(site) for a in self._arms)

    def active(self) -> bool:
        """Cheap lock-free predicate: is ANY injection possibly armed?
        Hot call sites (per-frame, per-dispatch) gate their policy
        wrapping on it so the disarmed steady state pays one env read."""
        return bool(os.environ.get(ENV_VAR)) or bool(self._arms)

    # -- the hot check -------------------------------------------------
    def check(self, name: str, **detail: Any) -> None:
        """Raise the site's exception when an armed entry fires.

        ``detail`` fields ride into the log record (peer rank, path...).
        Disarmed fast path is lock-free: one env read + two attribute
        reads (benign race — a spec change mid-read just takes the
        locked slow path on the next call).
        """
        spec = os.environ.get(ENV_VAR, "")
        if spec == self._spec and not self._arms:
            return
        with self._lock:
            self._sync()
            if not self._arms:
                return
            site = self.sites.get(name)
            if site is None:
                site = self.sites[name] = Site(name, TRANSIENT,
                                               InjectedIOError)
            fired_arm = None
            for arm in self._arms:
                if arm.matches(name):
                    site.hits += 1
                    if arm.fire(name):
                        fired_arm = arm
                        break
            if fired_arm is None:
                return
            delay_s = fired_arm.delay
            if delay_s is not None:
                # latency mode: the fire SLEEPS at the site instead of
                # raising — a deterministic straggler, not an error
                self.delayed += 1
                rec = {"event": "fault_injected", "site": name,
                       "kind": "delay", "delay_s": delay_s}
            else:
                site.fires += 1
                self.injected += 1
                rec = {"event": "fault_injected", "site": name,
                       "kind": site.kind, "fire": site.fires}
            rec.update(detail)
            self.events.append(rec)
            if len(self.events) > 1024:
                del self.events[:512]
            log = self._log
        self._emit(log, rec)
        if delay_s is not None:
            # sleep OUTSIDE the registry lock: a delayed rank must not
            # serialize every other thread's disarmed fast path
            import time
            time.sleep(delay_s)
            return
        raise site.exc(name, site.kind)

    # -- observability -------------------------------------------------
    def note(self, event: str, _quiet: bool = False,
             **detail: Any) -> None:
        """Record a recovery-layer event (retry / recovery / abort)
        into the same JSON stream the injections use. ``_quiet`` bumps
        the counter WITHOUT an event record — high-frequency callers
        (bootstrap dials) log sparsely but must never under-count."""
        with self._lock:
            if event == "retry":
                self.retries += 1
            elif event == "recovery":
                self.recoveries += 1
            elif event == "abort":
                self.aborts += 1
            if _quiet:
                return
            rec = {"event": event}
            rec.update(detail)
            self.events.append(rec)
            if len(self.events) > 1024:
                del self.events[:512]
            log = self._log
        self._emit(log, rec)

    def log_line(self, event: str, **detail: Any) -> None:
        """Emit one JSON line through the attached logger WITHOUT
        recording it in the bounded events ring — for I/O-lane summary
        events (prefetch/writeback) whose volume would evict the fault
        records the ring exists to keep."""
        with self._lock:
            log = self._log
        rec = {"event": event}
        rec.update(detail)
        self._emit(log, rec)

    @staticmethod
    def _emit(log: Optional[Callable[..., None]], rec: dict) -> None:
        if log is None:
            return
        try:
            log(**rec)
        except Exception:
            pass                  # logging must never mask the fault

    def set_logger(self, line: Optional[Callable[..., None]]) -> None:
        """``line(**fields)`` sink for JSON events (JsonLogger.line)."""
        with self._lock:
            self._log = line

    def stats(self) -> dict:
        with self._lock:
            return {"faults_injected": self.injected,
                    "faults_delayed": self.delayed,
                    "retries": self.retries,
                    "recoveries": self.recoveries,
                    "aborts": self.aborts}

    def reset(self) -> None:
        """Forget armed state + counters (tests)."""
        with self._lock:
            self._spec = None
            self._arms = []
            self.events = []
            self.injected = self.retries = self.delayed = 0
            self.recoveries = self.aborts = 0
            for s in self.sites.values():
                s.hits = s.fires = 0


def parse_duration_s(v: str) -> float:
    """``50ms`` / ``2s`` / plain seconds -> non-negative seconds."""
    v = v.strip()
    if v.endswith("ms"):
        out = float(v[:-2]) / 1e3
    elif v.endswith("s"):
        out = float(v[:-1])
    else:
        out = float(v)
    if out < 0:
        raise ValueError(v)
    return out


def parse_spec(spec: str) -> List[_Arm]:
    """Parse a THRILL_TPU_FAULTS value; malformed entries are skipped
    loudly (a typo must not silently disable the whole chaos run)."""
    arms: List[_Arm] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        pattern, p, n, seed, after = parts[0].strip(), 1.0, 1, 0, 0
        delay: Optional[float] = None
        ok = bool(pattern)
        for kv in parts[1:]:
            k, _, v = kv.partition("=")
            try:
                if k == "p":
                    p = float(v)
                elif k == "n":
                    n = int(v)
                elif k == "seed":
                    seed = int(v)
                elif k == "after":
                    after = int(v)
                elif k == "delay":
                    delay = parse_duration_s(v)
                else:
                    raise ValueError(k)
            except ValueError:
                ok = False
        if ok:
            arms.append(_Arm(pattern, p, n, seed, after, delay))
        else:
            import sys
            print(f"thrill_tpu.faults: malformed {ENV_VAR} entry "
                  f"{entry!r} ignored", file=sys.stderr)
    return arms


#: process-wide registry: sites declare here, Context attaches its
#: JsonLogger here, overall_stats() reads the counters here
REGISTRY = FaultRegistry()

declare = REGISTRY.declare
check = REGISTRY.check
note = REGISTRY.note
armed = REGISTRY.armed


class inject:
    """Context manager arming sites programmatically (tests)::

        with faults.inject("api.mesh.dispatch", n=1, seed=3):
            ...

    Composes with an existing env spec by appending; restores the
    previous value on exit.
    """

    def __init__(self, pattern: str, p: float = 1.0, n: int = 1,
                 seed: int = 0, after: int = 0,
                 delay: Optional[float] = None) -> None:
        self.entry = f"{pattern}:p={p}:n={n}:seed={seed}:after={after}"
        if delay is not None:
            self.entry += f":delay={delay}"
        self._prev: Optional[str] = None

    def __enter__(self) -> "inject":
        self._prev = os.environ.get(ENV_VAR)
        merged = (f"{self._prev};{self.entry}" if self._prev
                  else self.entry)
        os.environ[ENV_VAR] = merged
        return self

    def __exit__(self, *exc) -> None:
        if self._prev is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = self._prev
