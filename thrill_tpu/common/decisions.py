"""Plan observatory: the framework-wide decision ledger.

The port now makes ~ten data-driven plan choices deep inside the stack
— fusion split points and barrier reasons (api/fusion.py,
api/dia_base.py), bulk/chunked/1-factor exchange strategy, chunk count
K, narrow specs and the optimistic-vs-synced verdict
(data/exchange.py), pre-shuffle prune verdicts (core/preshuffle.py),
HBM admission estimates (mem/pressure.py + parallel/mesh.py), plan-
store seed consumption and skips (service/plan_store.py,
api/context.py). Each used to decide silently, auditable only by
reading code. This module makes every one of them a first-class
record:

* :class:`DecisionRecord` — site key, kind, inputs, predicted value,
  chosen alternative, rejected alternatives with their estimated
  costs, and (once truth arrives) the joined actual with a
  ``log2(predicted/actual)`` error.
* :class:`DecisionLedger` — one per Context, attached as
  ``mesh_exec.decisions`` so every choke point reaches it in one
  attribute read. Records land in a bounded ring
  (``THRILL_TPU_DECISIONS_RING``, default 4096), as ``event=decision``
  JSON log lines, and as instants on the tracing spine's ``plan`` lane
  (common/trace.py) — Perfetto shows *why* alongside *when*.
* Joins happen at the points where truth arrives: the optimistic
  exchange's deferred capacity check, the dispatch choke point's
  measured output bytes, observed prune fractions (record_prune).
  Per-kind ``|log2(pred/actual)|`` aggregates feed the accuracy
  ledger in ``ctx.overall_stats()`` (``decision_accuracy``), the
  ``cost_model_mae`` bench lane, and ``PlanStore.save_ledger`` — the
  on-disk audit trail next to plans.json.
* :func:`render_plan` — the shared explain() renderer: an annotated
  physical-plan tree (ops, fused segments, exchange strategy per
  edge, every decision with its reason and audit verdict). Consumed
  live by ``ctx.explain()`` / ``DIA.explain()`` and offline by
  ``tools/plan_report.py`` over JSON logs.

Overhead contract: ``THRILL_TPU_DECISIONS=0`` is a pinned no-op — the
dispatch choke point pays one attribute read plus one predicate and
allocates no record objects (tests/common/test_decisions.py pins this
via :data:`RECORDS_CREATED`, the SPANS_CREATED pattern). Decisions are
observability, never load-bearing: a dropped or ring-evicted record
changes no plan.

This ledger is the direct prerequisite for the ROADMAP's cost-based
adaptive planner: a cost model you can audit is one you can let
choose.
"""

from __future__ import annotations

import collections
import itertools
import json
import math
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from .stats import Aggregate

#: total DecisionRecord objects ever allocated in this process — the
#: THRILL_TPU_DECISIONS=0 no-op test asserts this stays flat across
#: dispatches (the SPANS_CREATED pattern, common/trace.py)
RECORDS_CREATED = 0

#: audit-verdict error threshold: |log2(pred/actual)| <= 1 (within 2x)
#: reads "ok", anything past it "off" — coarse by design; the MAE
#: aggregates carry the real number
_OK_LOG2 = 1.0


def decisions_enabled() -> bool:
    """THRILL_TPU_DECISIONS=0 disables the whole ledger (read once per
    ledger, at Context construction)."""
    from .config import _env_flag
    return _env_flag("THRILL_TPU_DECISIONS", True)


def ring_capacity() -> int:
    """THRILL_TPU_DECISIONS_RING: in-memory record ring size (default
    4096; explain() sees at most this many recent records — the
    per-kind counters and accuracy aggregates never drop)."""
    from .config import _env_int
    try:
        return max(_env_int("THRILL_TPU_DECISIONS_RING", 4096), 0)
    except ValueError:
        return 4096


class DecisionRecord:
    """One plan choice: what was decided, from which inputs, what the
    model predicted, what else was on the table — and, once truth
    arrives, how wrong the prediction was."""

    __slots__ = ("seq", "kind", "site", "chosen", "predicted",
                 "rejected", "reason", "inputs", "dia", "node",
                 "actual", "err_log2", "verdict")

    def __init__(self, seq: int, kind: str, site: str, chosen: str,
                 predicted: Optional[float], rejected, reason,
                 inputs: Dict[str, Any], dia: Optional[int],
                 node: Optional[str]) -> None:
        self.seq = seq
        self.kind = kind
        self.site = site
        self.chosen = chosen
        self.predicted = predicted
        self.rejected = rejected     # [(alternative, est_cost), ...]
        self.reason = reason
        self.inputs = inputs
        self.dia = dia
        self.node = node
        self.actual: Optional[float] = None
        self.err_log2: Optional[float] = None
        self.verdict: Optional[str] = None

    def rec(self) -> dict:
        """JSON-log form (the ``event=decision`` line; also what
        tools/plan_report.py reconstructs records from)."""
        r: Dict[str, Any] = {"event": "decision", "seq": self.seq,
                             "kind": self.kind, "site": self.site,
                             "chosen": self.chosen}
        if self.predicted is not None:
            r["predicted"] = self.predicted
        if self.rejected:
            r["rejected"] = [[a, c] for a, c in self.rejected]
        if self.reason:
            r["reason"] = self.reason
        if self.inputs:
            r["inputs"] = self.inputs
        if self.dia is not None:
            r["dia_id"] = self.dia
        if self.node is not None:
            r["node"] = self.node
        return r

    def audit_rec(self) -> dict:
        r: Dict[str, Any] = {"event": "decision_audit", "seq": self.seq,
                             "kind": self.kind, "site": self.site,
                             "verdict": self.verdict}
        if self.actual is not None:
            r["actual"] = self.actual
        if self.err_log2 is not None:
            r["err_log2"] = round(self.err_log2, 4)
        return r


class DecisionLedger:
    """Per-Context decision store + predicted-vs-actual accuracy
    aggregates. Attached as ``mesh_exec.decisions`` (one attribute
    read per choke point); ``enabled`` False makes every guarded site
    allocate nothing."""

    def __init__(self, logger=None, tracer=None,
                 ring: Optional[int] = None,
                 enabled: Optional[bool] = None) -> None:
        self.enabled = decisions_enabled() if enabled is None \
            else enabled
        self.logger = logger
        self.tracer = tracer
        # audit subscriber (api/planner.py Planner.on_audit): called
        # with every record whose actual just joined, so the adaptive
        # planner can act on predictions that turned out to be lies.
        # None (no planner / THRILL_TPU_PLANNER=0) = pure observatory.
        self.audit_hook = None
        cap = ring_capacity() if ring is None else ring
        self.records: collections.deque = collections.deque(
            maxlen=cap if cap > 0 else 1)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        # never-evicted aggregates: per-kind record counts, per-kind
        # joined counts + |log2 err| stats, per-(kind, site) audit
        # means (the worst-sites table)
        self.kind_counts: Dict[str, int] = {}
        self.joined_counts: Dict[str, int] = {}
        self._acc: Dict[str, Aggregate] = {}
        self._site_err: Dict[Tuple[str, str], List[float]] = {}
        # open records awaiting a resolve_site() join from a different
        # scope (prune verdicts: recorded at plan time, audited when
        # record_prune observes the fraction)
        self._open: Dict[Tuple[str, str], DecisionRecord] = {}
        # current DIA node (thread-local stack; dia_base.materialize
        # binds it around compute so decisions recorded inside land on
        # the right node in explain())
        self._tls = threading.local()

    # -- node binding ---------------------------------------------------
    def push_node(self, dia_id: int, label: str) -> None:
        st = getattr(self._tls, "nodes", None)
        if st is None:
            st = self._tls.nodes = []
        st.append((dia_id, label))

    def pop_node(self) -> None:
        st = getattr(self._tls, "nodes", None)
        if st:
            st.pop()

    def _current_node(self) -> Tuple[Optional[int], Optional[str]]:
        st = getattr(self._tls, "nodes", None)
        return st[-1] if st else (None, None)

    # -- recording ------------------------------------------------------
    def record(self, kind: str, site: str, chosen: str,
               predicted: Optional[float] = None,
               rejected=None, reason: Optional[str] = None,
               join: bool = False, dia: Optional[int] = None,
               node: Optional[str] = None,
               **inputs: Any) -> DecisionRecord:
        """Record one plan choice. ``join=True`` keeps the record open
        under (kind, site) for a later :meth:`resolve_site`; callers
        holding the record in scope pass it to :meth:`resolve`
        directly. ``dia``/``node`` override the thread-local current
        node (fusion-barrier records are ABOUT a node, not recorded
        inside its compute)."""
        global RECORDS_CREATED
        RECORDS_CREATED += 1
        if dia is None:
            dia, node = self._current_node()
        rec = DecisionRecord(next(self._ids), kind, site, chosen,
                             _num(predicted), rejected, reason,
                             inputs, dia, node)
        with self._lock:
            self.records.append(rec)
            self.kind_counts[kind] = self.kind_counts.get(kind, 0) + 1
            if join:
                self._open[(kind, site)] = rec
        log = self.logger
        if log is not None and log.enabled:
            log.line(**rec.rec())
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant("plan", kind, site=site, chosen=chosen,
                       predicted=rec.predicted, reason=reason)
        return rec

    # -- joining actuals ------------------------------------------------
    def resolve(self, rec: Optional[DecisionRecord], actual,
                verdict: Optional[str] = None) -> None:
        """Join the measured truth back onto a decision: computes the
        ``log2(predicted/actual)`` error when both sides are positive
        numbers, folds it into the per-kind accuracy aggregates, and
        emits the ``event=decision_audit`` line + trace instant."""
        if rec is None:
            return
        actual = _num(actual)
        rec.actual = actual
        pred = rec.predicted
        if pred is not None and actual is not None \
                and pred > 0 and actual > 0:
            rec.err_log2 = math.log2(pred / actual)
            rec.verdict = verdict or (
                "ok" if abs(rec.err_log2) <= _OK_LOG2 else "off")
            with self._lock:
                self.joined_counts[rec.kind] = \
                    self.joined_counts.get(rec.kind, 0) + 1
                self._acc.setdefault(rec.kind, Aggregate()).add(
                    abs(rec.err_log2))
                se = self._site_err.setdefault((rec.kind, rec.site),
                                               [0, 0.0])
                se[0] += 1
                se[1] += abs(rec.err_log2)
        else:
            rec.verdict = verdict or "unmeasured"
            with self._lock:
                self.joined_counts[rec.kind] = \
                    self.joined_counts.get(rec.kind, 0) + 1
        log = self.logger
        if log is not None and log.enabled:
            log.line(**rec.audit_rec())
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant("plan", rec.kind + "_audit", site=rec.site,
                       verdict=rec.verdict,
                       err_log2=(round(rec.err_log2, 3)
                                 if rec.err_log2 is not None else None))
        hook = self.audit_hook
        if hook is not None:
            # the planner's re-optimization trigger; a raising hook
            # must not break the audit join it rides on (planning is
            # perf, the join is observability — neither may take down
            # the pipeline that produced the actual)
            try:
                hook(rec)
            except Exception:
                pass

    def resolve_site(self, kind: str, site: str, actual,
                     verdict: Optional[str] = None) -> bool:
        """Join by (kind, site) for scopes that no longer hold the
        record (record_prune). Returns False when no open record
        matches — joins are best-effort by contract."""
        with self._lock:
            rec = self._open.pop((kind, site), None)
        if rec is None:
            return False
        self.resolve(rec, actual, verdict=verdict)
        return True

    # -- aggregates -----------------------------------------------------
    def accuracy(self) -> Dict[str, dict]:
        """Per-kind accuracy ledger: records, joined actuals, mean and
        stdev of |log2(predicted/actual)|."""
        with self._lock:
            out = {}
            for kind, n in sorted(self.kind_counts.items()):
                agg = self._acc.get(kind)
                out[kind] = {
                    "n": n,
                    "joined": self.joined_counts.get(kind, 0),
                    "mae_log2": round(agg.mean, 4) if agg is not None
                    and agg.count else None,
                    "stdev_log2": round(agg.stdev, 4)
                    if agg is not None and agg.count else None,
                }
            return out

    def worst_sites(self, k: int = 5) -> List[dict]:
        """Top-k sites by mean |log2 err| — where the cost model lies
        the most (json2profile's decisions lane, plan_report)."""
        with self._lock:
            rows = [{"kind": kind, "site": site, "n": n,
                     "mae_log2": round(tot / n, 4)}
                    for (kind, site), (n, tot) in self._site_err.items()
                    if n]
        rows.sort(key=lambda r: -r["mae_log2"])
        return rows[:k]

    def snapshot(self) -> List[dict]:
        """Record dicts (audit fields merged) for rendering — a copy,
        so the service dispatcher may keep recording mid-render."""
        with self._lock:
            recs = list(self.records)
        out = []
        for r in recs:
            d = r.rec()
            if r.verdict is not None:
                d["verdict"] = r.verdict
            if r.actual is not None:
                d["actual"] = r.actual
            if r.err_log2 is not None:
                d["err_log2"] = round(r.err_log2, 4)
            out.append(d)
        return out

    def summary(self) -> dict:
        """The persisted accuracy ledger (PlanStore.save_ledger)."""
        return {"version": 1,
                "decisions": sum(self.kind_counts.values()),
                "accuracy": self.accuracy(),
                "worst_sites": self.worst_sites()}

    def dump_beside(self, flight_path: Optional[str]) -> Optional[str]:
        """Archive the ledger next to a flight-recorder dump (the
        chaos sweep keeps both): ``flight-*.json`` gains a sibling
        ``decisions-*.json`` with the summary plus the ring's records.
        Best-effort like the flight dump itself."""
        if flight_path is None or not self.enabled:
            return None
        recs = self.snapshot()
        if not recs:
            return None
        d, name = os.path.split(flight_path)
        if not name.startswith("flight-"):
            return None
        path = os.path.join(d, "decisions-" + name[len("flight-"):])
        try:
            with open(path, "w") as f:
                f.write(json.dumps(self.summary(), default=str) + "\n")
                for r in recs:
                    f.write(json.dumps(r, default=str) + "\n")
        except OSError:
            return None
        return path


def _num(v) -> Optional[float]:
    """Coerce to a plain float (np scalars repr badly in JSON);
    None/NaN stay None."""
    if v is None or isinstance(v, bool):
        return None
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


# ----------------------------------------------------------------------
# guarded one-liners for the choke points (the span_of pattern)
# ----------------------------------------------------------------------

def ledger_of(mex) -> Optional[DecisionLedger]:
    """The mesh's ledger when recording is live, else None — ONE
    attribute read plus one predicate on the disabled path (the pinned
    THRILL_TPU_DECISIONS=0 contract)."""
    led = getattr(mex, "decisions", None)
    if led is not None and led.enabled:
        return led
    return None


def record_of(mex, kind: str, site: str, chosen: str,
              **kw) -> Optional[DecisionRecord]:
    led = ledger_of(mex)
    if led is None:
        return None
    return led.record(kind, site, chosen, **kw)


def resolve_of(mex, rec: Optional[DecisionRecord], actual,
               verdict: Optional[str] = None) -> None:
    if rec is None:
        return
    led = getattr(mex, "decisions", None)
    if led is not None:
        led.resolve(rec, actual, verdict=verdict)


def resolve_io_prefetch(mex, rec: Optional[DecisionRecord],
                        io_delta: dict) -> None:
    """THE audit-join formula for ``io_prefetch`` decisions, shared by
    every readahead site (em_sort merge, checkpoint/hbm restore):
    joined actual = the measured hit rate over the window's consumed
    readahead, clamped away from zero so an all-miss run resolves as a
    loud finite error; a window that never consumed readahead at all
    stays unmeasured. One definition — the planner's learned per-site
    depth grows from this signal, and the sites must not drift apart
    in what they feed it."""
    if rec is None:
        return
    from .iostats import hit_rate
    consumed = io_delta.get("prefetch_hits", 0) \
        + io_delta.get("prefetch_misses", 0)
    resolve_of(mex, rec,
               max(hit_rate(io_delta), 1e-3) if consumed else None)


# ----------------------------------------------------------------------
# the shared explain() renderer
# ----------------------------------------------------------------------

def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n:.1f}{unit}")
        n /= 1024
    return f"{n:.1f}GiB"


def _fmt_decision(d: dict) -> str:
    """One decision as an annotated line: kind, chosen-vs-rejected
    with estimated costs, the reason, and the audit verdict."""
    parts = [f"{d['kind']}: chose {d['chosen']}"]
    # prune predictions are fractions, capacity predictions row counts;
    # everything else predicts bytes
    unit = (d.get("inputs") or {}).get("unit") or "bytes"
    fmt = (lambda v: f"{float(v):.3g}") if unit != "bytes" else _fmt_bytes
    rej = d.get("rejected") or []
    if rej:
        alts = ", ".join(f"{a} est {_fmt_bytes(c)}" if _num(c)
                         is not None else str(a) for a, c in rej)
        parts.append(f"over {alts}")
    if d.get("predicted") is not None:
        parts.append(f"pred {fmt(d['predicted'])}")
    if d.get("reason"):
        parts.append(f"({d['reason']})")
    if d.get("actual") is not None:
        err = d.get("err_log2")
        audit = f"actual {fmt(d['actual'])}"
        if err is not None:
            audit += f", err x{2 ** abs(err):.2f} [{d.get('verdict')}]"
        elif d.get("verdict"):
            audit += f" [{d['verdict']}]"
        parts.append("-> " + audit)
    elif d.get("verdict"):
        parts.append(f"-> [{d['verdict']}]")
    return " ".join(parts)


def render_plan(nodes: List[dict], decisions: List[dict],
                W: Optional[int] = None, title: str = "") -> str:
    """Render the physical plan as an annotated tree.

    ``nodes``: [{"id", "label", "state", "parents": [ids]}, ...] —
    from live DIA nodes (ctx.explain / DIA.explain) or reconstructed
    from ``node_execute_start``/``node_fused`` log events
    (tools/plan_report.py). ``decisions``: record dicts as produced by
    :meth:`DecisionLedger.snapshot` (audits merged).

    Sinks render first (consumer at top, parents indented below — the
    pull direction); shared parents render once and are referenced by
    id afterwards. Decisions attach to the node whose compute recorded
    them (``dia_id``); site-less ones land in a trailing "plan-wide"
    section. Nodes in state FUSED are annotated with the stitched
    program that consumed them (the ``fusion`` decision naming their
    dia id)."""
    by_id = {n["id"]: n for n in nodes}
    ids = set(by_id)
    referenced = {p for n in nodes for p in n.get("parents", ())
                  if p in ids}
    sinks = [n for n in nodes if n["id"] not in referenced]
    # decisions by node
    per_node: Dict[int, List[dict]] = {}
    rest: List[dict] = []
    fused_names: Dict[int, str] = {}
    for d in decisions:
        if d.get("kind") == "fusion":
            for nid in (d.get("inputs") or {}).get("dia_ids") or ():
                if nid is not None:
                    fused_names.setdefault(int(nid),
                                           (d.get("inputs")
                                            or {}).get("ops", ""))
        nid = d.get("dia_id")
        if nid is not None:
            if nid in ids:
                per_node.setdefault(nid, []).append(d)
            # else: bound to a node OUTSIDE this plan (an earlier
            # pipeline on a reused Context, or outside this DIA's
            # subgraph) — dropping it keeps explain() about THIS plan
        else:
            rest.append(d)
    lines: List[str] = []
    head = title or "physical plan"
    if W:
        head += f" (W={W})"
    lines.append(head)
    seen: set = set()

    def walk(root: int) -> None:
        # explicit stack, not recursion: a long chained pipeline can
        # nest deeper than the interpreter's recursion limit
        stack: List[Tuple[int, int]] = [(root, 0)]
        while stack:
            nid, depth = stack.pop()
            pad = "  " * depth
            n = by_id.get(nid)
            if n is None:
                lines.append(f"{pad}- #{nid} (outside this plan)")
                continue
            state = n.get("state") or "?"
            tag = f"{pad}- {n.get('label', '?')}#{nid} [{state}]"
            if state == "FUSED" and nid in fused_names:
                tag += f"  ~ fused into [{fused_names[nid]}]"
            if nid in seen:
                lines.append(tag + "  (see above)")
                continue
            seen.add(nid)
            lines.append(tag)
            for d in per_node.get(nid, ()):
                lines.append(f"{pad}    . {_fmt_decision(d)}")
            for p in reversed(n.get("parents", ())):
                stack.append((p, depth + 1))

    for s in sorted(sinks, key=lambda n: n["id"], reverse=True):
        walk(s["id"])
    if rest:
        lines.append("plan-wide decisions:")
        # collapse repeats (loop iterations re-record the same site):
        # show each (kind, site, chosen) once with a xN count and the
        # LAST audit (latest truth wins)
        grouped: Dict[Tuple, List[dict]] = {}
        for d in rest:
            grouped.setdefault((d.get("kind"), d.get("site"),
                                d.get("chosen")), []).append(d)
        for key, ds in grouped.items():
            last = ds[-1]
            cnt = f"  x{len(ds)}" if len(ds) > 1 else ""
            lines.append(f"  . {_fmt_decision(last)}{cnt}")
    return "\n".join(lines)


def render_accuracy(accuracy: Dict[str, dict],
                    worst: List[dict]) -> str:
    """The audited-accuracy table (plan_report, run scripts)."""
    lines = ["decision accuracy (|log2 predicted/actual|):",
             f"  {'kind':<16} {'n':>5} {'joined':>7} {'mae':>7} "
             f"{'stdev':>7}"]
    for kind, row in sorted(accuracy.items()):
        mae = row.get("mae_log2")
        sd = row.get("stdev_log2")
        lines.append(
            f"  {kind:<16} {row.get('n', 0):>5} "
            f"{row.get('joined', 0):>7} "
            f"{mae if mae is not None else '-':>7} "
            f"{sd if sd is not None else '-':>7}")
    if worst:
        lines.append("worst-audited sites:")
        for r in worst:
            lines.append(f"  {r['kind']}@{r['site']}: "
                         f"mae {r['mae_log2']} over {r['n']} joins")
    return "\n".join(lines)
