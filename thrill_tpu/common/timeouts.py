"""Load-aware distress deadlines.

The net layer's "peer gone?" timeouts (bootstrap accept loops, isend
flush) exist to turn a dead peer into a clean error instead of a hang.
The reference has no such caps at these points — MPI_Waitall and its
tcp Connect loops block until the runtime kills the job — so ours must
never fire MERELY because the machine is oversubscribed: on a loaded
host a healthy peer can legitimately spend minutes between progress
points (XLA compiles, EM spills), and a fixed cap converts that into a
spurious child death (observed: the 2-process MPI wordcount child
dying at a fixed 60 s flush deadline under a synthetic full-core load).

``scaled(base)`` stretches a base deadline by the PER-CORE 1-minute
loadavg (capped at 6x, floor 1x) — idle or merely-busy multi-core
machines keep the tight diagnostic deadline; only real
oversubscription (runnable tasks exceeding cores) stretches it.
tests/net/portalloc.load_scaled delegates here: one copy of the
policy for parent-side drain budgets and child-side deadlines alike.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

# loadavg is kernel-updated every ~5 s; poll loops re-evaluate budgets
# as often as every 50 us, so the read is cadence-limited to ~1 s
# (benign data race: tuple swap is atomic)
_LOAD_CACHE = (-10.0, 1.0)


def _per_core_load() -> float:
    global _LOAD_CACHE
    now = time.monotonic()
    ts, val = _LOAD_CACHE
    if now - ts > 1.0:
        try:
            val = os.getloadavg()[0] / (os.cpu_count() or 1)
        except (OSError, AttributeError):
            val = 0.0
        _LOAD_CACHE = (now, val)
    return val


def scaled(base_s: float) -> float:
    return base_s * max(1.0, min(_per_core_load(), 6.0))


def budget_fn(override: Optional[float],
              base_s: float) -> Callable[[], float]:
    """The one policy for distress-deadline dispatch: an explicit
    override is a FIXED budget (tests rely on determinism); otherwise
    the load-scaled base, re-evaluated on every call so a load spike
    arriving mid-wait stretches an already-started deadline. The
    stretch is a RATCHET: once granted, a budget never contracts —
    otherwise a wait started under load would spuriously expire the
    moment the 1-minute loadavg decays (elapsed > newly-shrunk budget)
    even though the now-unloaded peer is about to complete."""
    if override is not None:
        fixed = float(override)
        return lambda: fixed
    best = scaled(base_s)

    def ratchet() -> float:
        nonlocal best
        best = max(best, scaled(base_s))
        return best

    return ratchet
