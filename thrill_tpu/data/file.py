"""File: an ordered spillable sequence of item Blocks.

Equivalent of the reference's data::File + BlockWriter/BlockReader
(reference: thrill/data/file.hpp:56, block_writer.hpp:53,
block_reader.hpp:42): items are appended through a writer that fills
fixed-budget blocks, bytes live in the BlockPool (C++ store with LRU
disk spill), and keep/consume readers stream them back. Blocks are
shared ref-counted views (data/block.py), so ``slice`` and ``scatter``
carve item ranges ZERO-COPY — the reference's Stream::Scatter primitive
(thrill/data/stream.hpp:77-210) that re-slices blocks without
deserializing fixed-size items. Random access ``get_item_at`` mirrors
File::GetItemAt via a cumulative-count bisect + single-row decode.
"""

from __future__ import annotations

import bisect
from typing import Any, Iterator, List, Optional, Sequence

from .block import Block
from .block_pool import BlockPool
from .serializer import serialize_batch

DEFAULT_BLOCK_ITEMS = 4096


class File:
    def __init__(self, pool: Optional[BlockPool] = None,
                 block_items: int = DEFAULT_BLOCK_ITEMS) -> None:
        self.pool = pool or BlockPool()
        self._owns_pool = pool is None
        self.block_items = block_items
        self.blocks: List[Block] = []

    # legacy views (tests/introspection)
    @property
    def block_ids(self) -> List[int]:
        return [b.bid for b in self.blocks]

    @property
    def block_counts(self) -> List[int]:
        return [b.num_items for b in self.blocks]

    # -- writing --------------------------------------------------------
    def writer(self) -> "BlockWriter":
        return BlockWriter(self)

    def append_block(self, block: Block) -> None:
        """Adopt a Block view (takes ownership of one reference)."""
        if block.num_items:
            self.blocks.append(block)
        else:
            block.release()       # empty view: give the reference back

    @property
    def num_items(self) -> int:
        return sum(b.num_items for b in self.blocks)

    # -- reading --------------------------------------------------------
    # All readers decode blocks at consumption (Block.iter_items):
    # columnar batches decode zero-copy column views with no pickle
    # parse, and ``project`` reads only one tuple element's columns —
    # the k-way merge's item feeds skip the pos columns entirely
    # (ISSUE 15).
    def keep_reader(self, project=None) -> Iterator[Any]:
        """Stream items without consuming the file
        (reference: KeepFileBlockSource, file.hpp:349)."""
        for b in self.blocks:
            yield from b.iter_items(project)

    def consume_reader(self, project=None) -> Iterator[Any]:
        """Stream items, dropping each block after it is read
        (reference: ConsumeFileBlockSource, file.hpp:414)."""
        while self.blocks:
            b = self.blocks.pop(0)
            yield from b.iter_items(project)
            b.release()

    def prefetch_reader(self, consume: bool = False,
                        submit=None, project=None) -> Iterator[Any]:
        """Keep/consume reader with ONE block read ahead on a shared
        readahead pool — the k-way merge's per-run prefetch slot
        (reference: BlockPool prefetch, thrill/data/block_pool.hpp:177):
        while this run's current block decodes and drains, its next
        block's bytes are already being fetched from the spill store,
        so the merge winner's successor block is resident when the
        tournament needs it.

        ``submit`` is a readahead executor's submit (data/writeback.py
        ``make_readahead``); None degrades to the plain reader. A
        background fetch failure falls back to a demand read on the
        consumer thread — never wrong data. With ``consume``, a
        generator abandoned mid-stream may strand its <= 2 in-flight
        blocks until ``pool.close()`` (callers already clear files and
        close the pool in their cleanup)."""
        if submit is None:
            return self.consume_reader(project) if consume \
                else self.keep_reader(project)
        return self._prefetch_iter(consume, submit, project)

    def _prefetch_iter(self, consume: bool, submit,
                       project=None) -> Iterator[Any]:
        from .serializer import deserialize_iter
        from .writeback import readahead_get, readahead_job
        pool = self.pool
        idx = 0

        def next_block():
            nonlocal idx
            if consume:
                return self.blocks.pop(0) if self.blocks else None
            if idx < len(self.blocks):
                idx += 1
                return self.blocks[idx - 1]
            return None

        def start(b):
            # surgical readahead: a RAM-resident block's get is a
            # memcpy — backgrounding it buys queue overhead, not
            # latency. Only blocks a demand read would fault in from
            # disk ride the pool.
            if pool.resident(b.bid):
                return None
            return submit(readahead_job(
                lambda: pool.get(b.bid), "file.prefetch"))

        b = next_block()
        fut = start(b) if b is not None else None
        while b is not None:
            nb = next_block()
            nfut = start(nb) if nb is not None else None
            raw = readahead_get(fut, lambda blk=b: pool.get(blk.bid),
                                "file.prefetch")
            if b.hi > b.lo:
                yield from deserialize_iter(raw, b.lo, b.hi, project)
            if consume:
                b.release()
            b, fut = nb, nfut

    def _cumulative(self) -> List[int]:
        out = [0]
        for b in self.blocks:
            out.append(out[-1] + b.num_items)
        return out

    def get_item_at(self, index: int) -> Any:
        """Random access (reference: File::GetItemAt) — bisect over
        cumulative counts, decode exactly one row for fixed-size
        batches."""
        cum = self._cumulative()
        if not 0 <= index < cum[-1]:
            raise IndexError(index)
        k = bisect.bisect_right(cum, index) - 1
        return self.blocks[k].item_at(index - cum[k])

    # -- zero-copy carving ---------------------------------------------
    def slice(self, start: int, end: int) -> "File":
        """New File over items [start, end), sharing every byte block
        (reference: Block slicing, block.hpp:52)."""
        cum = self._cumulative()
        if not 0 <= start <= end <= cum[-1]:
            raise IndexError((start, end, cum[-1]))
        out = File(pool=self.pool, block_items=self.block_items)
        if start == end:
            return out
        k = bisect.bisect_right(cum, start) - 1
        pos = start
        while pos < end:
            b = self.blocks[k]
            lo = pos - cum[k]
            hi = min(end - cum[k], b.num_items)
            out.append_block(b.slice(lo, hi))
            pos = cum[k] + hi
            k += 1
        return out

    def scatter(self, offsets: Sequence[int]) -> List["File"]:
        """Split into len(offsets)-1 Files at the given item offsets —
        the Stream::Scatter primitive (thrill/data/stream.hpp:77-210):
        block-granular sharing, only edge blocks are sliced, no item is
        deserialized."""
        return [self.slice(offsets[i], offsets[i + 1])
                for i in range(len(offsets) - 1)]

    def clear(self) -> None:
        for b in self.blocks:
            b.release()
        self.blocks.clear()

    def close(self) -> None:
        self.clear()
        if self._owns_pool:
            self.pool.close()


class BlockWriter:
    def __init__(self, file: File) -> None:
        self.file = file
        self._buf: List[Any] = []

    def put(self, item: Any) -> None:
        self._buf.append(item)
        if len(self._buf) >= self.file.block_items:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        payload = serialize_batch(self._buf)
        bid = self.file.pool.put(payload)
        self.file.blocks.append(Block(self.file.pool, bid, 0,
                                      len(self._buf)))
        self._buf = []

    def close(self) -> None:
        self.flush()

    def __enter__(self) -> "BlockWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
