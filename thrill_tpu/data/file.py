"""File: an ordered spillable sequence of item blocks.

Equivalent of the reference's data::File + BlockWriter/BlockReader
(reference: thrill/data/file.hpp:56, block_writer.hpp:53,
block_reader.hpp:42): items are appended through a writer that fills
fixed-budget blocks, blocks live in the BlockPool (C++ store with LRU
disk spill), and keep/consume readers stream them back. Random access
``get_item_at`` mirrors File::GetItemAt.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional

from .block_pool import BlockPool
from .serializer import deserialize_batch, serialize_batch

DEFAULT_BLOCK_ITEMS = 4096


class File:
    def __init__(self, pool: Optional[BlockPool] = None,
                 block_items: int = DEFAULT_BLOCK_ITEMS) -> None:
        self.pool = pool or BlockPool()
        self._owns_pool = pool is None
        self.block_items = block_items
        self.block_ids: List[int] = []
        self.block_counts: List[int] = []

    # -- writing --------------------------------------------------------
    def writer(self) -> "BlockWriter":
        return BlockWriter(self)

    @property
    def num_items(self) -> int:
        return sum(self.block_counts)

    # -- reading --------------------------------------------------------
    def keep_reader(self) -> Iterator[Any]:
        """Stream items without consuming the file
        (reference: KeepFileBlockSource, file.hpp:349)."""
        for bid in self.block_ids:
            for it in deserialize_batch(self.pool.get(bid)):
                yield it

    def consume_reader(self) -> Iterator[Any]:
        """Stream items, dropping each block after it is read
        (reference: ConsumeFileBlockSource, file.hpp:414)."""
        while self.block_ids:
            bid = self.block_ids.pop(0)
            self.block_counts.pop(0)
            for it in deserialize_batch(self.pool.get(bid)):
                yield it
            self.pool.drop(bid)

    def get_item_at(self, index: int) -> Any:
        """Random access (reference: File::GetItemAt)."""
        for bid, cnt in zip(self.block_ids, self.block_counts):
            if index < cnt:
                return deserialize_batch(self.pool.get(bid))[index]
            index -= cnt
        raise IndexError(index)

    def clear(self) -> None:
        for bid in self.block_ids:
            self.pool.drop(bid)
        self.block_ids.clear()
        self.block_counts.clear()

    def close(self) -> None:
        self.clear()
        if self._owns_pool:
            self.pool.close()


class BlockWriter:
    def __init__(self, file: File) -> None:
        self.file = file
        self._buf: List[Any] = []

    def put(self, item: Any) -> None:
        self._buf.append(item)
        if len(self._buf) >= self.file.block_items:
            self.flush()

    def flush(self) -> None:
        if not self._buf:
            return
        payload = serialize_batch(self._buf)
        bid = self.file.pool.put(payload)
        self.file.block_ids.append(bid)
        self.file.block_counts.append(len(self._buf))
        self._buf = []

    def close(self) -> None:
        self.flush()

    def __enter__(self) -> "BlockWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
