"""All-to-all item exchange: the TPU-native shuffle data plane.

The reference moves items between workers through serialized Block
streams multiplexed over TCP/MPI connections (reference:
thrill/data/multiplexer.hpp:67, cat_stream.hpp:155, mix_stream.hpp:126,
stream.hpp:77-210 ``Scatter``). The TPU-native equivalent is a
bulk-synchronous exchange of columnar shards over the ICI mesh:

  Phase A (jit): compute each item's destination worker, stably sort
      items by destination, count per-destination sends
      -> the analog of the reference's per-destination BlockWriters.
  Host step: agree on padded block capacity from the [W, W] send-count
      matrix (tiny transfer; shapes must be static for XLA). Capacities
      round up to powers of two so recompilation is rare.
  Phase B (jit): scatter into [W, M] padded per-destination blocks,
      ``lax.all_to_all`` over the mesh, compact received blocks into a
      fresh [out_cap] shard -> the analog of Multiplexer block transit +
      receive-side BlockQueues.

On real TPU pods `lax.ragged_all_to_all` can skip the padding (config
``exchange='ragged'``); XLA:CPU lacks that op, so the dense padded path
is the portable default.

Destination builders cover every DOp shuffle pattern:
  hash partition (ReduceByKey/GroupBy/Join), range partition by splitter
  search (Sort/Merge), index ranges (ReduceToIndex/Zip/Concat/Rebalance)
  and explicit per-item targets.

Overlapped data plane (the MixStream-analog dispatch discipline):

* Phase B is CHUNKED — the per-destination slot space [0, M_pad) splits
  into K row ranges (``common/partition.py`` bounds) and each range is
  its own jitted dispatch scattering into a shared output accumulator.
  Every output row is written by exactly one chunk at the exact position
  the bulk program would use, so results are bit-identical for any K;
  jax's async dispatch keeps chunk i's ``all_to_all`` + compaction in
  flight while chunk i+1 is scattered, and the consumer's next program
  can be enqueued before the last chunks land. ``THRILL_TPU_XCHG_CHUNKS``
  forces K; the auto policy chunks only volumes worth pipelining.
* The mid-shuffle host sync on the [W, W] send matrix is ELIDED in
  steady state: per-(plan-key, site) padded capacities learned by
  ``_sticky_caps`` double as a capacity-plan cache, phase B dispatches
  optimistically on the cached plan straight off the DEVICE send matrix
  (counts come back as a device output), and a device-computed overflow
  flag rides a deferred check (the hinted-join pattern): on a miss the
  exchange transparently re-runs from the retained phase-A output under
  the synced plan. ``THRILL_TPU_XCHG_CAP_CACHE=0`` disables the
  optimistic path; ``THRILL_TPU_OVERLAP=0`` restores the bulk-
  synchronous exchange (single dispatch + host sync) bit-identically.
"""

from __future__ import annotations

import os
import time
import weakref
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common import decisions as _decisions
from ..common import faults
from ..common import trace as _trace
from ..common.config import (cap_cache_enabled, overlap_enabled,
                             round_up_pow2, xchg_narrow_enabled)
from ..common.partition import dense_range_bounds
from ..common.retry import default_policy
from ..parallel.mesh import AXIS, MeshExec
from .shards import DeviceShards

# per-chunk injection at the chunked phase-B dispatch loop: fires
# BEFORE the chunk program launches (nothing dispatched yet), so a
# transient retry is safe — mirrors the fused per-op site discipline
_F_CHUNK = faults.declare("data.exchange.chunk")
# row-narrowing injection: fires before a learned narrow spec is
# applied to a phase-B dispatch; an armed fire DEGRADES that exchange
# to full-width rows (narrowing is a pure byte optimization — shipping
# wide is always correct), never a wrong result
_F_PACK = faults.declare("data.exchange.pack")


# ----------------------------------------------------------------------
# plan-state persistence (service/plan_store.py)
# ----------------------------------------------------------------------
# The learned per-site plan state — sticky capacities, plan kinds,
# narrow ranges — is keyed by in-memory identity tuples (call-site
# ident + cap + treedef + dtypes). For persistence the tuples digest to
# stable strings: every component reprs deterministically for a fixed
# program (ints, strings, dtypes, shape tuples, PyTreeDefs), so a warm
# restart of the SAME pipeline recomputes the same digests, and a
# changed pipeline simply misses and re-learns. Values are correctness-
# neutral (a lying capacity/range is healed by the in-trace overflow
# flag), which is what makes importing them safe at all.


def _canon(x) -> str:
    """Address-free canonical repr for digesting. Call-site idents
    embed user FUNCTIONS (key extractors, reduce lambdas) whose repr
    carries a memory address; canonicalize them to module.qualname
    plus a bytecode hash — stable across processes for the same
    source, distinct for distinct lambdas sharing a qualname. Other
    objects whose default repr is address-bearing degrade to their
    class identity: a collision can only MERGE plan state of
    same-class sites, which is correctness-neutral (capacities
    ratchet, ranges/kinds are healed by the in-trace guards)."""
    if isinstance(x, tuple):
        return "(" + ",".join(_canon(e) for e in x) + ")"
    if callable(x) and not isinstance(x, type):
        qn = getattr(x, "__qualname__", None)
        if qn:
            code = getattr(x, "__code__", None)
            if code is not None:
                import hashlib
                # bytecode + constants: `lambda x: x % 7` and
                # `lambda x: x % 11` share co_code (the constant lives
                # in co_consts, referenced by index) — hashing both
                # keeps "edit the constant -> warm restart misses and
                # re-learns". Nested code objects in co_consts hash by
                # their own bytecode (their repr carries an address).
                consts = tuple(
                    c.co_code.hex() if hasattr(c, "co_code")
                    else repr(c) for c in code.co_consts)
                # closure cells too: factory-made lambdas
                # (make(7) vs make(1000)) share code AND consts — the
                # captured value is what distinguishes them
                try:
                    cells = tuple(_canon(c.cell_contents)
                                  for c in (x.__closure__ or ()))
                except Exception:
                    cells = ("<?>",)
                h = hashlib.sha1(repr((consts, cells)).encode()
                                 + b"|" + code.co_code).hexdigest()[:8]
                return f"<fn {getattr(x, '__module__', '?')}.{qn}:{h}>"
            return f"<fn {getattr(x, '__module__', '?')}.{qn}>"
    r = repr(x)
    if " at 0x" in r:
        return f"<{type(x).__module__}.{type(x).__qualname__}>"
    return r


def _ident_digest(ident: Tuple) -> str:
    import hashlib
    return hashlib.sha1(_canon(ident).encode()).hexdigest()


def plan_seed(mex: MeshExec, kind: str, ident: Tuple):
    """Consume the imported plan-store seed for ``ident`` (None when
    no store was attached or the key is unknown). Consumed ONCE: the
    live per-mesh dicts take over from the first lookup, so the seed
    table never shadows fresher in-process learning. Shared with
    core/preshuffle.py for its verdict/fraction kinds."""
    seeds = getattr(mex, "_plan_seed", None)
    if not seeds:
        return None
    m = seeds.get(kind)
    if not m:
        return None
    dg = _ident_digest(ident)
    v = m.pop(dg, None)
    if v is not None:
        mex.stats_plan_store_hits = getattr(
            mex, "stats_plan_store_hits", 0) + 1
        # decision ledger: a warm-start seed was consumed INSTEAD of a
        # data-driven plan build — explain() shows where the plan
        # store actually paid off (common/decisions.py)
        led = _decisions.ledger_of(mex)
        if led is not None:
            led.record("store_seed", site="xchg:" + dg[:10],
                       chosen=kind, reason="warm-start seed consumed")
    return v


def count_plan_build(mex: MeshExec) -> None:
    """One data-driven host plan construction (synced exchange plan /
    pre-shuffle verdict evaluation) — the events a warm plan-store
    restart runs ZERO of."""
    mex.stats_plan_builds = getattr(mex, "stats_plan_builds", 0) + 1


def merge_unconsumed_seeds(mex, out: dict) -> dict:
    """Ride imported-but-unconsumed seeds along an export, so learned
    state for pipelines NOT re-run this session survives the save
    (forgetting this silently drops their plans). Shared by every
    plan-state exporter (here and core/preshuffle.py)."""
    seeds = getattr(mex, "_plan_seed", None) or {}
    for kind in out:
        for dg, v in (seeds.get(kind) or {}).items():
            out[kind].setdefault(dg, v)
    return out


def install_plan_seeds(mex, state: dict, kinds, *,
                       symmetric: bool = False) -> int:
    """Merge digest maps for ``kinds`` into the shared lazy seed table
    (``mex._plan_seed``); returns how many entries arrived. Shared by
    every plan-state importer.

    ``symmetric`` is the caller's attestation that every rank of a
    multi-controller mesh installs these EXACT entries (the rank-0
    broadcast path, api/context.py). A non-attested install — e.g. a
    per-rank store read — flips ``mex._plan_seed_symmetric`` off, and
    with it the optimistic exchange gate (``_optimistic_ok``): seeds
    of unknown provenance could differ across ranks, and per-process
    optimism over divergent plans desyncs the collective schedule.
    IN-PROCESS learned state needs no attestation: it derives from the
    replicated send matrix under the lockstep submission contract, so
    it is symmetric by construction (the flag's default)."""
    seeds = getattr(mex, "_plan_seed", None)
    if seeds is None:
        seeds = mex._plan_seed = {}
    n = 0
    for kind in kinds:
        m = state.get(kind)
        if isinstance(m, dict) and m:
            seeds.setdefault(kind, {}).update(m)
            n += len(m)
    if n and not symmetric:
        mex._plan_seed_symmetric = False
    return n


#: MeshExec attributes owned by this module whose VALUES are shaped by
#: the worker count W (per-worker capacity vectors, W-specific plan
#: kinds and narrow ranges, unconsumed store seeds keyed under the
#: current W). An elastic resize (parallel/mesh.py MeshExec.resize)
#: archives them per W instead of letting a W' pipeline consume a
#: W-shaped capacity — a lying cap is healed by the overflow flag, but
#: a WRONG-LENGTH cap vector would be garbage, not a lie.
W_STATE_ATTRS = ("_sticky_caps", "_sticky_ranges", "_xchg_plan",
                 "_xchg_plan_uses", "_plan_seed")


def export_plan_state(mex: MeshExec) -> dict:
    """This mesh's exchange plan state as JSON-serializable digest
    maps (the plan store's on-disk form)."""
    return merge_unconsumed_seeds(mex, {
        "caps": {_ident_digest(k): [int(x) for x in v]
                 for k, v in getattr(mex, "_sticky_caps", {}).items()},
        "plan": {_ident_digest(k): str(v)
                 for k, v in getattr(mex, "_xchg_plan", {}).items()},
        "ranges": {_ident_digest(k):
                   [list(map(int, r)) if r is not None else None
                    for r in v]
                   for k, v in getattr(mex, "_sticky_ranges",
                                       {}).items()},
    })


def import_plan_state(mex: MeshExec, state: dict, *,
                      symmetric: bool = False) -> int:
    """Install exchange plan-state seeds (digest maps, as produced by
    :func:`export_plan_state`); returns how many entries arrived."""
    return install_plan_seeds(mex, state, ("caps", "plan", "ranges"),
                              symmetric=symmetric)


def _seeded_caps(mex: MeshExec, ident: Tuple) -> Optional[Tuple[int, ...]]:
    v = plan_seed(mex, "caps", ident)
    if not v:
        return None
    try:
        return tuple(int(x) for x in v)
    except (TypeError, ValueError):
        return None


def _sticky_range_get(mex: MeshExec, cap_ident: Tuple):
    """The remembered per-leaf range union for a site, seeding the
    live store from an attached plan store on first miss."""
    store = getattr(mex, "_sticky_ranges", None)
    if store is None:
        store = mex._sticky_ranges = {}
    prev = store.get(cap_ident)
    if prev is None:
        v = plan_seed(mex, "ranges", cap_ident)
        if v is not None:
            try:
                prev = tuple(tuple(int(x) for x in r)
                             if r is not None else None for r in v)
            except (TypeError, ValueError):
                prev = None
            if prev is not None:
                store[cap_ident] = prev
    return prev


# ----------------------------------------------------------------------
# phase-B row narrowing (dtype/range analysis)
# ----------------------------------------------------------------------
# Integer leaves whose observed [min, max] fits a narrower dtype cross
# the fabric as that dtype: phase A all-reduces per-leaf ranges on
# device (no extra sync — the synced plan step reads them alongside the
# send matrix, and the optimistic path trusts the spec LEARNED from
# past synced runs, guarded by an in-trace range check riding the
# existing deferred overflow flag). Narrow specs, like capacities, only
# ever WIDEN for a site, so steady-state executables are reused.


def _narrowable_leaves(leaves) -> Tuple[int, ...]:
    """Leaf indices eligible for range analysis: integer dtypes wider
    than one byte (floats never narrow — NaN/rounding would break bit
    parity; sub-byte ints have nothing to gain)."""
    return tuple(i for i, l in enumerate(leaves)
                 if np.dtype(l.dtype).kind in "iu"
                 and np.dtype(l.dtype).itemsize >= 2)


def _spec_from_ranges(mex: MeshExec, cap_ident: Tuple, leaves,
                      nidx: Tuple[int, ...],
                      ranges: Optional[np.ndarray]):
    """Sticky (widen-only) narrow spec for this site: merge the fetched
    per-leaf ranges into the remembered union and derive the narrow
    dtype per leaf. Returns a tuple of dtype-str-or-None per LEAF (not
    per narrowable leaf), or None when nothing narrows."""
    if ranges is None or not nidx:
        return None
    prev = _sticky_range_get(mex, cap_ident)
    store = mex._sticky_ranges
    merged = []
    for j, li in enumerate(nidx):
        lo, hi = int(ranges[j, 0]), int(ranges[j, 1])
        dt = np.dtype(leaves[li].dtype)
        if dt.kind == "u" and (lo < 0 or hi < 0):
            # u64 value past int64.max wrapped negative in the range
            # output: unrepresentable — poison the leaf's range so it
            # never narrows
            lo, hi = np.iinfo(np.int64).min, np.iinfo(np.int64).max
        if lo > hi:                       # empty shard: no information
            if prev is not None and prev[j] is not None:
                lo, hi = prev[j]
            else:
                merged.append(None)
                continue
        elif prev is not None and prev[j] is not None:
            lo, hi = min(lo, prev[j][0]), max(hi, prev[j][1])
        merged.append((lo, hi))
    store[cap_ident] = tuple(merged)
    from ..net.wire import narrow_dtype
    spec: list = [None] * len(leaves)
    any_narrow = False
    for j, li in enumerate(nidx):
        if merged[j] is None:
            continue
        nd = narrow_dtype(merged[j][0], merged[j][1],
                          np.dtype(leaves[li].dtype).itemsize)
        if nd is not None:
            spec[li] = nd.str
            any_narrow = True
    return tuple(spec) if any_narrow else None


def _sticky_spec(mex: MeshExec, cap_ident: Tuple, leaves):
    """Narrow spec for an OPTIMISTIC dispatch: derived purely from the
    site's remembered range union (no fetch). The in-trace guard in
    chunk 0 catches data that outgrew the learned ranges and routes
    the exchange to the synced heal, which re-learns them."""
    prev = _sticky_range_get(mex, cap_ident)
    if prev is None:
        return None
    nidx = _narrowable_leaves(leaves)
    from ..net.wire import narrow_dtype
    spec: list = [None] * len(leaves)
    any_narrow = False
    for j, li in enumerate(nidx):
        if j >= len(prev) or prev[j] is None:
            continue
        nd = narrow_dtype(prev[j][0], prev[j][1],
                          np.dtype(leaves[li].dtype).itemsize)
        if nd is not None:
            spec[li] = nd.str
            any_narrow = True
    return tuple(spec) if any_narrow else None


def _pack_degraded(spec):
    """data.exchange.pack injection gate: an armed fire drops the
    narrow spec for THIS exchange (full-width rows — always correct),
    mirroring the degrade-never-wrong discipline of mem.estimate."""
    if spec is None or not faults.REGISTRY.active():
        return spec
    try:
        faults.check(_F_PACK)
    except faults.InjectedFault:
        faults.note("recovery", what="xchg.pack_degrade")
        return None
    return spec


def _narrow_item_bytes(leaves, spec) -> int:
    """Per-item fabric bytes under a narrow spec (None = full width)."""
    total = 0
    for i, l in enumerate(leaves):
        isz = (np.dtype(spec[i]).itemsize
               if spec is not None and spec[i] is not None
               else np.dtype(l.dtype).itemsize)
        total += isz * int(np.prod(l.shape[2:], dtype=np.int64))
    return total


def leaf_ranges_traced(xs, mask):
    """Traced helper (inside shard_map): all-reduced [len(xs), 2] int64
    ``[min, max]`` of each leaf's valid items — the range analysis the
    phase-B narrowing feeds on. Shared by phase A and by the presorted
    classify programs (Sort/Merge phase 2), so every phase-B flavor
    learns from the same math. u64 values past int64.max are clamped
    BEFORE the int64 cast: they saturate at int64.max, which correctly
    reads as "cannot narrow" without poisoning the leaf's sticky range
    when a shard merely happened to be empty."""
    i64max = np.iinfo(np.int64).max
    rows = []
    for x in xs:
        info = jnp.iinfo(x.dtype)
        smax = info.max
        if x.dtype == jnp.uint64:
            x = jnp.minimum(x, jnp.uint64(i64max))
            smax = i64max
        m = mask.reshape((-1,) + (1,) * (x.ndim - 1))
        lo = lax.pmin(jnp.min(jnp.where(m, x, smax))
                      .astype(jnp.int64), AXIS)
        hi = lax.pmax(jnp.max(jnp.where(m, x, info.min))
                      .astype(jnp.int64), AXIS)
        rows.append(jnp.stack([lo, hi]))
    return jnp.stack(rows)


def presorted_range_leaves(mex: MeshExec, cap: int, leaves) -> Tuple[int, ...]:
    """Narrowable leaf indices when a presorted classify program should
    bolt on the range analysis — the same worth-it policy as phase A
    (volume gate, W > 1, knob on, no capture in flight)."""
    W = mex.num_workers
    if not (W > 1 and xchg_narrow_enabled()
            and mex.loop_recorder is None
            and W * cap * leaf_item_bytes(leaves) >= _NARROW_MIN_BYTES):
        return ()
    return _narrowable_leaves(leaves)


def _ex_cumsum(x):
    return jnp.cumsum(x) - x


def _planner_of(mex):
    """The mesh's adaptive planner (api/planner.py) when live, else
    None — attribute reads only (no api import: the planner object is
    attached by the Context, exactly like the decision ledger)."""
    pl = getattr(mex, "planner", None)
    if pl is not None and pl.enabled:
        return pl
    return None


def resolve_mode(mex: MeshExec) -> str:
    """Exchange mode precedence: env THRILL_TPU_EXCHANGE, then the
    mesh's configured mode, then dense. Single source of truth for
    every caller that gates on the exchange plan (the Sort fused path
    must agree with the plan the generic exchange would pick).

    The env override is captured ONCE at mesh construction
    (``MeshExec._env_exchange``) — this used to be an ``os.environ``
    read on every plan step of every exchange. Set the variable before
    building the mesh; mid-process toggles still work through
    ``mex.exchange_mode`` (which Context sets from Config)."""
    if hasattr(mex, "_env_exchange"):
        env = mex._env_exchange
    else:                                  # bare stubs in tests
        env = os.environ.get("THRILL_TPU_EXCHANGE")
    return env or getattr(mex, "exchange_mode", "dense")


def send_slot_index(dest, S_row, W: int, M_pad: int, cap: int):
    """Traced helper: flat [W*M_pad] send-buffer position per item
    (dump row W*M_pad for invalid), given dest-sorted destinations and
    this worker's send-count row."""
    off = _ex_cumsum(S_row)
    dc = jnp.clip(dest, 0, W - 1)
    slot = jnp.arange(cap) - jnp.take(off, dc)
    return jnp.where(dest < W, dc * M_pad + slot, W * M_pad)


def ship_blocks(x, send_idx, W: int, M_pad: int):
    """Traced helper: scatter one leaf into [W, M_pad] padded
    per-destination blocks and all_to_all them; returns the received
    [W*M_pad, ...] rank-ordered runs (run w = source w's items)."""
    trail = x.shape[1:]
    buf = jnp.zeros((W * M_pad + 1,) + trail, x.dtype)
    buf = buf.at[send_idx].set(x)
    blocks = buf[:W * M_pad].reshape((W, M_pad) + trail)
    recv = lax.all_to_all(blocks, AXIS, split_axis=0,
                          concat_axis=0, tiled=True)
    return recv.reshape((W * M_pad,) + trail)


def send_counts(dest: jnp.ndarray, W: int) -> jnp.ndarray:
    """Traced helper (inside shard_map): per-destination send histogram,
    all-gathered into the replicated [W, W] matrix every worker needs
    for the host planning step. ``dest`` uses W for invalid items."""
    from ..core.pallas_kernels import partition_histogram
    send = partition_histogram(dest, W)
    return lax.all_gather(send, AXIS)


def exchange_presorted(mex: MeshExec, treedef, sorted_dest, sorted_leaves,
                       S: np.ndarray, min_cap: int = 1,
                       ident: Tuple = (),
                       ranges: Optional[np.ndarray] = None
                       ) -> DeviceShards:
    """Ship items that are ALREADY grouped by destination.

    Public entry for operators whose upstream order makes destinations
    monotone (Sort: items are key-sorted, so splitter rank never
    decreases) — they skip the generic phase-A destination sort
    entirely. Contract: ``sorted_dest`` is [W, cap] int32 with each
    worker's valid items contiguous per destination in rank order
    (monotone suffices) and W marking invalid slots; ``sorted_leaves``
    are [W, cap, ...] in that same order; ``S[w, d]`` counts w's items
    bound for d (as produced by ``send_counts``). ``ranges`` ([L, 2]
    int64 over the narrowable leaves, see
    :func:`presorted_range_leaves`) opts the call into phase-B row
    narrowing — presorted callers compute it inside their own phase-A
    program, where the data is already resident.
    """
    return _exchange_planned(mex, treedef, sorted_dest, sorted_leaves, S,
                             min_cap=min_cap, ident=ident, ranges=ranges)


def _phase_a(shards: DeviceShards, dest_builder: Callable,
             cache_key: Tuple, want_ranges: bool = True):
    """Phase A: destination, local dest-sort, send counts. Returns
    (treedef, sorted_dest, sorted_leaves, send_mat, range_mat) with the
    [W, W] send matrix REPLICATED ON DEVICE — whether the planner syncs
    it to the host (classic path) or dispatches phase B straight off it
    (optimistic capacity-cache path) is the caller's decision.

    ``range_mat`` ([L, 2] int64, replicated; None when no leaf is
    narrowable or narrowing is off) carries the all-reduced [min, max]
    of every integer leaf's valid items — the dtype/range analysis the
    phase-B row narrowing feeds on. Computing it here costs two
    reductions per leaf inside a program that already sorts the shard;
    whether anything READS it (the synced plan step, or an optimistic
    miss heal) is again the caller's decision. Callers whose phase B
    never narrows (the streamed rounds) pass ``want_ranges=False`` and
    skip the analysis entirely."""
    mex = shards.mesh_exec
    # an upstream optimistic exchange may still owe its overflow check:
    # heal it before this program bakes the (possibly truncated)
    # columns into a new shuffle
    shards.validate_pending()
    W = mex.num_workers
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    # Narrowing pays on VOLUME: W=1 exchanges move nothing, and a
    # kilobyte shuffle saves less than the range analysis adds to its
    # phase-A compile — the same worth-it policy as phase-B chunking.
    # The gate is deterministic across processes (cap/W/dtypes are
    # globally agreed shapes).
    narrow_worth = (want_ranges and W > 1 and xchg_narrow_enabled()
                    and W * cap * leaf_item_bytes(leaves)
                    >= _NARROW_MIN_BYTES)
    nidx = _narrowable_leaves(leaves) if narrow_worth else ()
    key_a = ("xchg_a", cache_key, cap, treedef, nidx,
             tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build_a():
        def fa(counts_dev, *ls):
            count = counts_dev[0, 0]
            mask = jnp.arange(cap) < count
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            widx = lax.axis_index(AXIS)
            dest = dest_builder(tree, mask, widx).astype(jnp.int32)
            dest = jnp.where(mask, jnp.clip(dest, 0, W - 1), W)
            from ..core.device_sort import argsort_words
            from ..core.rowmove import take_rows_multi
            perm = argsort_words([dest.astype(jnp.uint64)])
            sorted_dest = jnp.take(dest, perm)
            sorted_ls = take_rows_multi([l[0] for l in ls], perm)
            # replicate the [W, W] send-count matrix: every process can
            # then fetch it locally (multi-controller safe host step)
            all_send = send_counts(sorted_dest, W)
            outs = (sorted_dest[None], all_send,
                    *[sl[None] for sl in sorted_ls])
            if nidx:
                outs = outs + (leaf_ranges_traced(
                    [ls[li][0] for li in nidx], mask),)
            return outs

        from jax.sharding import PartitionSpec as P
        out_specs = (P(AXIS), P()) + (P(AXIS),) * len(leaves)
        if nidx:
            out_specs = out_specs + (P(),)
        return mex.smap(fa, 1 + len(leaves), out_specs=out_specs)

    fa = mex.cached(key_a, build_a)
    with _trace.span_of(getattr(mex, "tracer", None), "exchange",
                        "phase_a", rows=W * cap):
        out_a = fa(shards.counts_device(), *leaves)
    sorted_dest, send_mat = out_a[0], out_a[1]
    if nidx:
        sorted_leaves = list(out_a[2:-1])
        range_mat = out_a[-1]
    else:
        sorted_leaves = list(out_a[2:])
        range_mat = None
    return treedef, sorted_dest, sorted_leaves, send_mat, range_mat


def exchange(shards: DeviceShards, dest_builder: Callable, cache_key: Tuple,
             min_cap: int = 1) -> DeviceShards:
    """Move every valid item to the worker computed by ``dest_builder``.

    ``dest_builder(tree, valid_mask, worker_index) -> int32 [cap]`` is
    traced inside the phase-A program; ``cache_key`` must identify it
    (plus its static parameters) for executable caching.

    Steady state pays NO mid-shuffle host sync: once this call site's
    padded capacities are cached (the first, synced run), phase B
    dispatches optimistically on the cached plan with the send matrix
    staying device-resident; a capacity miss is detected by a deferred
    device flag and healed by re-running the synced plan from the
    retained phase-A output (lineage-level, never wrong data).
    """
    mex = shards.mesh_exec
    # a loop capture is recording: leaf ranges are VALUES of loop data
    # (carry-dependent), so reading them would taint the tape —
    # captured exchanges ship full-width rows, and the capture-time
    # phase A skips the analysis entirely so the replayed tape carries
    # no dead per-iteration range reductions
    treedef, sorted_dest, sorted_leaves, send_mat, range_mat = _phase_a(
        shards, dest_builder, cache_key,
        want_ranges=mex.loop_recorder is None)
    if mex.num_workers > 1:
        cap = sorted_leaves[0].shape[1] if sorted_leaves else 0
        cap_ident = _dense_cap_ident(cache_key, cap, treedef,
                                     sorted_leaves)
        caps = _optimistic_ok(mex, cap_ident, min_cap, ident=cache_key,
                              counts=shards._counts_host)
        if caps is not None:
            return _exchange_optimistic(
                mex, treedef, sorted_dest, sorted_leaves, send_mat,
                caps, ident=cache_key, min_cap=min_cap,
                range_mat=range_mat)
    # the exchange barrier: the host plan sync blocks until phase A's
    # send matrix lands — wait attribution (common/doctor.py) charges
    # the blocked window to the "exchange" lane
    doc = getattr(mex, "doctor", None)
    t0 = time.perf_counter() if doc is not None else 0.0
    S = mex.fetch(send_mat)                       # [W, W] S[w, d]
    if doc is not None:
        doc.record_wait("xchg.plan_sync", None,
                        time.perf_counter() - t0, lane="exchange")
    # the tiny [L, 2] range matrix rides the SAME host-sync window as
    # the send matrix (raw transfer: one logical plan sync, not a
    # second counted mid-pipeline fetch)
    ranges = None if range_mat is None else mex._fetch_raw(range_mat)
    return _exchange_planned(mex, treedef, sorted_dest, sorted_leaves, S,
                             min_cap=min_cap, ident=cache_key,
                             smat_dev=send_mat, ranges=ranges)


def exchange_stream(shards: DeviceShards, dest_builder: Callable,
                    cache_key: Tuple):
    """MixStream analog: yield received blocks round by round, in
    arbitrary (schedule) order, instead of one compacted shard.

    The reference's MixStream (thrill/data/mix_stream.hpp:126) delivers
    blocks as they arrive so the consumer overlaps processing with the
    shuffle. The TPU-native equivalent: each 1-factor round is its own
    small jitted program whose result the consumer folds while jax's
    async dispatch keeps later rounds' collectives in flight — no
    global receive buffer, no compaction scatter, no rank-order
    guarantee. Yields one DeviceShards per source (identity round
    first, then the 1-factor schedule — tier-pure on sliced meshes).
    """
    mex = shards.mesh_exec
    W = mex.num_workers
    # streamed rounds ship full-width by design — skip range analysis
    treedef, sorted_dest, sorted_leaves, send_mat, _ = _phase_a(
        shards, dest_builder, cache_key, want_ranges=False)
    # per-round caps genuinely need the host S — the same exchange
    # barrier as the planned path, charged to the same wait lane
    doc = getattr(mex, "doctor", None)
    t0 = time.perf_counter() if doc is not None else 0.0
    S = mex.fetch(send_mat)
    if doc is not None:
        doc.record_wait("xchg.plan_sync", None,
                        time.perf_counter() - t0, lane="exchange")
    account_traffic(mex, S, leaf_item_bytes(sorted_leaves),
                    site="xchg:" + _ident_digest(cache_key)[:10])
    cap = sorted_leaves[0].shape[1] if sorted_leaves else 0
    if W > 1:
        count_plan_build(mex)
        led = _decisions.ledger_of(mex)
        if led is not None:
            rec = led.record(
                "xchg_strategy", "xchg:" + _ident_digest(cache_key)[:10],
                "stream", reason="MixStream delivery requested",
                items=int(S.sum()))
            led.resolve(rec, (int(S.sum()) - int(np.trace(S)))
                        * leaf_item_bytes(sorted_leaves))

    if W == 1:
        yield DeviceShards(mex, jax.tree.unflatten(treedef, sorted_leaves),
                           np.diag(S).astype(np.int64).copy())
        return

    rounds = one_factor_rounds(mex)
    cap_ident = ("xchg_stream_caps", cache_key, cap, treedef,
                 tuple((l.dtype, l.shape[2:]) for l in sorted_leaves))
    needed = (max(int(np.diag(S).max()), 1),) + tuple(
        max(int(S[np.arange(W), to].max()), 1) for to in rounds)
    caps = _sticky_caps(mex, cap_ident, needed)
    mex.stats_padded_rows += sum(caps)
    # identity round is a local scatter; rounds 1.. cross the fabric
    # (streamed rounds ship full-width: no narrowing on this path)
    stream_bytes = W * sum(caps[1:]) * leaf_item_bytes(sorted_leaves)
    mex.stats_bytes_wire_device += stream_bytes
    mex.stats_bytes_wire_device_raw += stream_bytes

    srow = mex.put_small(S.astype(np.int32))

    def round_program(r: int, to, M_r: int):
        key = ("xchg_stream_round", cap, M_r, W,
               None if to is None else tuple(int(x) for x in to),
               treedef,
               tuple((l.dtype, l.shape[2:]) for l in sorted_leaves))

        def build():
            def f(sdest, srow_a, *ls):
                d = sdest[0]
                off = _ex_cumsum(srow_a[0])
                i = jnp.arange(cap)
                widx = lax.axis_index(AXIS)
                d_r = widx if to is None else jnp.take(
                    jnp.asarray(to), widx)
                sel = d == d_r
                slot = i - jnp.take(off, d_r)
                send_idx = jnp.where(sel, slot, M_r)
                outs = []
                for l in ls:
                    x = l[0]
                    buf = jnp.zeros((M_r + 1,) + x.shape[1:], x.dtype)
                    buf = buf.at[send_idx].set(x)[:M_r]
                    if to is not None:
                        buf = lax.ppermute(
                            buf, AXIS,
                            perm=[(w, int(to[w])) for w in range(W)])
                    outs.append(buf[None])
                return tuple(outs)

            return mex.smap(f, 2 + len(sorted_leaves))

        return mex.cached(key, build)

    # identity round: the diagonal blocks, no communication
    f0 = round_program(0, None, caps[0])
    out0 = f0(sorted_dest, srow, *sorted_leaves)
    yield DeviceShards(mex, jax.tree.unflatten(treedef, list(out0)),
                       np.diag(S).astype(np.int64).copy())
    for r, to in enumerate(rounds):
        inv = np.empty(W, dtype=np.int64)
        inv[to] = np.arange(W)
        fr = round_program(r + 1, to, caps[r + 1])
        outr = fr(sorted_dest, srow, *sorted_leaves)
        counts_r = S[inv, np.arange(W)].astype(np.int64)
        yield DeviceShards(mex, jax.tree.unflatten(treedef, list(outr)),
                           counts_r.copy())


def _sticky_caps(mex: MeshExec, ident: Tuple, needed: Tuple[int, ...]
                 ) -> Tuple[int, ...]:
    """Monotone capacity agreement per program identity.

    Loops (PageRank etc.) re-plan every iteration; if capacities chased
    the data exactly, every wiggle past a power of two would recompile.
    Capacities only ever GROW for a given program identity, so once a
    loop reaches steady state its executables are reused verbatim.
    """
    cache = getattr(mex, "_sticky_caps", None)
    if cache is None:
        cache = mex._sticky_caps = {}
    prev = cache.get(ident)
    if prev is None:
        # a plan-store seed (service/plan_store.py) pre-ratchets the
        # site to its remembered steady-state capacities — monotone
        # merge below, exactly as if this process had learned them
        prev = _seeded_caps(mex, ident)
    grown = tuple(round_up_pow2(n) for n in needed)
    if prev is not None and len(prev) == len(grown):
        grown = tuple(max(p, g) for p, g in zip(prev, grown))
    cache[ident] = grown
    return grown


def dense_all_to_all_applies(mex: MeshExec, S: np.ndarray,
                             row_bytes: int = 8) -> bool:
    """Would the planner use the single dense all_to_all for this send
    matrix? Shared predicate so fused callers (Sort's run-merge path)
    take the fused program exactly when the generic exchange would have
    taken the dense plan."""
    return resolve_mode(mex) == "dense" and not _skewed(S, row_bytes,
                                                        mex)


def account_traffic(mex: MeshExec, S: np.ndarray, item_bytes: int,
                    site: str = "", **log_extra: Any) -> None:
    """Traffic accounting shared by every exchange plan (reference:
    net::Manager tx/rx counters feeding the end-of-job OverallStats
    AllReduce, api/context.cpp:1275-1341). On multi-slice meshes the
    bytes are split by tier: same-slice pairs ride ICI, cross-slice
    pairs DCN. Called exactly once per LOGICAL exchange — the
    optimistic path calls it at deferred-check time (hit), or lets the
    healed synced re-run account instead (miss).

    Partition-skew attribution (common/doctor.py) rides the same
    choke point: the per-worker receive totals of THIS send matrix
    feed the site's hot-slot detector, the ``skew_ratio`` lane fields
    of the exchange log line, and the ``kind=skew`` plan-lane
    instants ``ctx.explain()`` renders."""
    moved = int(S.sum()) - int(np.trace(S))       # off-diagonal items
    mex.stats_exchanges += 1
    mex.stats_items_moved += moved
    mex.stats_bytes_moved += moved * item_bytes
    sid = mex.slice_id
    if mex.num_slices > 1:
        cross = sid[:, None] != sid[None, :]
        dcn_items = int(S[cross].sum())
        mex.stats_bytes_dcn += dcn_items * item_bytes
        mex.stats_bytes_ici += (moved - dcn_items) * item_bytes
    else:
        dcn_items = 0
        mex.stats_bytes_ici += moved * item_bytes
    skew_ratio = hot_worker = hot_rows = None
    doc = getattr(mex, "doctor", None)
    if doc is not None and S.shape[0] > 1:
        # total receive rows per worker INCLUDING the diagonal: the
        # hot slot is whoever holds the most rows after the shuffle,
        # local items included — that worker's downstream compute is
        # the one the partition function overloaded
        skew = doc.record_exchange(
            site or "xchg:?", S.sum(axis=0), item_bytes,
            tracer=getattr(mex, "tracer", None),
            ledger=_decisions.ledger_of(mex))
        if skew is not None:
            ratio, hot_worker, hot_rows = skew
            skew_ratio = round(ratio, 3)
    log = getattr(mex, "logger", None)
    if log is not None and log.enabled:
        sent = (S.sum(axis=1) - np.diag(S)).astype(int)
        recv = (S.sum(axis=0) - np.diag(S)).astype(int)
        skew_extra = {}
        if site:
            skew_extra["site"] = site
        if skew_ratio is not None:
            # hot_rows is the hot worker's DIAGONAL-INCLUDED receive
            # total — the figure the ratio was computed from
            # (per_worker_recv below is off-diagonal by its own
            # long-standing contract); the offline doctor_report
            # reads it so both reports state the same rows
            skew_extra["skew_ratio"] = skew_ratio
            skew_extra["hot_worker"] = hot_worker
            skew_extra["hot_rows"] = hot_rows
        log.line(event="exchange", items=moved,
                 bytes=moved * item_bytes,
                 bytes_dcn=dcn_items * item_bytes,
                 per_worker_sent=sent.tolist(),
                 per_worker_recv=recv.tolist(),
                 **skew_extra, **log_extra)


def one_factor_rounds(mex: MeshExec) -> List[np.ndarray]:
    """Round schedule for the pairwise exchange: a list of partner
    permutations partner[w] covering every ordered pair exactly once
    (the identity round is excluded — the caller scatters locally).

    Single slice: the classic rotation partner = (w + r) % W
    (reference: 1-factor scheduling, thrill/net/group.hpp:90-107).
    Multi-slice (workers blocked by slice, equal block size B): rounds
    are decomposed over (slice shift ds, chip shift dc) so every round
    is TIER-PURE — either all pairs same-slice (ICI) or all cross-slice
    (DCN). Tier-pure rounds pad only to their own tier's maximum (a
    mixed round pays the global max even when DCN traffic is light),
    and the DCN rounds are grouped last so the latency-bound tail rides
    the wide-ICI rounds first.
    """
    W = mex.num_workers
    sid = mex.slice_id
    nS = mex.num_slices
    blocked = (nS > 1 and W % nS == 0 and
               np.array_equal(sid, np.repeat(np.arange(nS), W // nS)))
    if not blocked:
        return [np.array([(w + r) % W for w in range(W)])
                for r in range(1, W)]
    B = W // nS
    s, c = np.arange(W) // B, np.arange(W) % B
    rounds = []
    for dc in range(1, B):                         # intra-slice (ICI)
        rounds.append(s * B + (c + dc) % B)
    for ds in range(1, nS):                        # cross-slice (DCN)
        for dc in range(B):
            rounds.append(((s + ds) % nS) * B + (c + dc) % B)
    return rounds


def leaf_item_bytes(leaves) -> int:
    """Per-item byte width across [W, cap, ...] leaves."""
    return sum(int(np.dtype(l.dtype).itemsize) *
               int(np.prod(l.shape[2:], dtype=np.int64))
               for l in leaves)


# Break-even padded-byte volume per extra program launch: the dense
# all_to_all is ONE launch padded to the global cell maximum; the
# 1-factor schedule is (W-1) serialized launches padded per round.
# 1-factor wins iff the padding it saves outweighs its extra launches:
#
#   saved_padded_bytes > extra_launches * BYTES_EQ
#
# where BYTES_EQ = round_overhead * exchange_bandwidth, both measured
# on the actual mesh by benchmarks/exchange_crossover.py:
#   * virtual 8-device CPU mesh (this image, 2026-07-30, plan pinned
#     during calibration): round_overhead 119 us, dense bw 378 MB/s
#     -> BYTES_EQ ~45 KiB
#   * TPU ICI meshes: ~10-30 us launch overhead at multi-GB/s effective
#     -> O(1 MiB); re-measure with the same script on real hardware.
# Override with THRILL_TPU_XCHG_BYTES_EQ.
_BYTES_EQ_MEASURED = {"cpu": 45_000}
_BYTES_EQ_FALLBACK = 1 << 20
# Exchange bandwidth (bytes/s) for the LIVE calibration below — the
# other factor of BYTES_EQ. The launch-overhead factor is measured on
# this very mesh (the dispatch-latency spine); bandwidth stays a
# baked platform constant because measuring it needs a sized payload
# sweep (benchmarks/exchange_crossover.py), not a passive observer.
_BYTES_EQ_BANDWIDTH = {"cpu": 378e6}
_BYTES_EQ_BANDWIDTH_FALLBACK = 4e9      # TPU ICI order of magnitude
_BYTES_EQ_MIN_SAMPLES = 256


def _bytes_eq(mex: MeshExec) -> int:
    import os
    env = os.environ.get("THRILL_TPU_XCHG_BYTES_EQ")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    platform = mex.devices[0].platform if mex.devices else "cpu"
    static = _BYTES_EQ_MEASURED.get(platform, _BYTES_EQ_FALLBACK)
    # Live calibration: the dispatch-latency spine's running minimum
    # (parallel/mesh.py) is this mesh's pure launch overhead — compile
    # calls and data-bound dispatches are strictly slower, so the min
    # converges on it from above. BYTES_EQ = overhead * bandwidth, so a
    # machine 4x slower than the constants were measured on flips the
    # dense/1-factor choice where its hardware actually crosses over.
    # Clamped to [static/4, static*4] (the min is an estimate, not a
    # license to leave the measured regime) and gated on a sample count
    # so fresh meshes — including every plan-choice test — keep the
    # deterministic static constant. THRILL_TPU_XCHG_BYTES_EQ_CAL=0
    # pins the static value regardless of history.
    if (os.environ.get("THRILL_TPU_XCHG_BYTES_EQ_CAL", "1") != "0"
            and getattr(mex, "_disp_lat_n", 0) >= _BYTES_EQ_MIN_SAMPLES):
        bw = _BYTES_EQ_BANDWIDTH.get(platform,
                                     _BYTES_EQ_BANDWIDTH_FALLBACK)
        cal = int(mex._disp_lat_min * bw)
        cal = max(static // 4, min(cal, static * 4))
        led = _decisions.ledger_of(mex)
        if led is not None and led.enabled \
                and not getattr(mex, "_bytes_eq_logged", False):
            # once per mesh: the drift of the live measurement vs the
            # baked constant, audited immediately (actual = static)
            mex._bytes_eq_logged = True
            rec = led.record(
                "bytes_eq", "xchg:bytes_eq", "calibrated",
                predicted=cal, rejected=[("static", static)],
                reason="launch-min %.0fus x %s bw"
                       % (mex._disp_lat_min * 1e6, platform),
                samples=int(mex._disp_lat_n))
            led.resolve(rec, static)
        return cal
    return static


def _strategy_costs(mex: MeshExec, S: np.ndarray,
                    row_bytes: int) -> Tuple[int, int, int]:
    """(dense_bytes, onefactor_bytes, n_rounds): the estimated padded
    fabric volume of each candidate plan for this send matrix — the
    inputs of the dense-vs-1-factor choice, shared by :func:`_skewed`
    and the decision ledger's ``xchg_strategy`` record.

    Rows entering the fabric: dense ships W slots of the global max per
    worker; 1-factor ships each round's pair maximum. Fabric rows
    exclude self-traffic on BOTH sides: the dense plan's diagonal slot
    and the 1-factor identity round are local scatters."""
    W = S.shape[0]
    M_dense = int(S.max())
    rounds = one_factor_rounds(mex)
    M_rounds = [max(int(S[np.arange(W), to].max()), 1) for to in rounds]
    rb = max(row_bytes, 1)
    return (W * (W - 1) * M_dense * rb, W * sum(M_rounds) * rb,
            len(rounds))


def _skewed(S: np.ndarray, row_bytes: int, mex: MeshExec) -> bool:
    """Does the measured cost model prefer the 1-factor schedule over
    the single dense all_to_all for this send matrix?

    A sparse-but-balanced matrix (e.g. a neighbor shift) saves nothing
    and stays on the single all_to_all; a 100:1 hot-key skew saves
    ~W x the padding and flips as soon as the savings clear the
    per-round launch overhead."""
    dense_b, of_b, n_rounds = _strategy_costs(mex, S, row_bytes)
    return dense_b - of_b > n_rounds * _bytes_eq(mex)


def _dense_cap_ident(ident: Tuple, cap: int, treedef, sorted_leaves
                     ) -> Tuple:
    """Sticky-capacity / capacity-plan-cache key for the dense plan:
    per CALL SITE (ident), not per shape — two unrelated same-shaped
    exchanges must not ratchet each other's capacities."""
    return ("xchg_caps", ident, cap, treedef,
            tuple((l.dtype, l.shape[2:]) for l in sorted_leaves))


def _chunk_count(mex: MeshExec, W: int, M_pad: int,
                 item_bytes: int) -> int:
    """How many row-range chunks phase B splits into.

    ``THRILL_TPU_OVERLAP=0`` forces the single bulk dispatch;
    ``THRILL_TPU_XCHG_CHUNKS=K`` pins K; the auto policy chunks only
    exchanges whose padded volume is worth pipelining (chunking a
    kilobyte shuffle pays K-1 extra dispatches for nothing — and every
    chunk shape is its own compiled program). With the adaptive
    planner attached the choice is the planner's; the policy itself is
    :func:`chunk_policy` either way (ONE implementation — the
    planner-on and planner-off paths cannot drift)."""
    pl = _planner_of(mex)
    if pl is not None:
        return pl.chunk_count(W, M_pad, item_bytes)
    return chunk_policy(W, M_pad, item_bytes)


def chunk_policy(W: int, M_pad: int, item_bytes: int) -> int:
    """The phase-B chunking policy: overlap kill switch, env pin, then
    the measured break-even auto rule. Shared verbatim by the legacy
    per-site branch and the adaptive planner (api/planner.py)."""
    if not overlap_enabled():
        return 1
    env = os.environ.get("THRILL_TPU_XCHG_CHUNKS")
    if env:
        try:
            return max(1, min(int(env), M_pad))
        except ValueError:
            pass
    if W * M_pad * item_bytes < _CHUNK_MIN_BYTES:
        return 1
    return max(1, min(_CHUNK_DEFAULT, M_pad))


_CHUNK_DEFAULT = 4
_CHUNK_MIN_BYTES = 1 << 20
# minimum padded exchange volume (W * cap * item bytes) for phase-A
# range analysis + phase-B narrowing: below this the compile-time cost
# of the analysis exceeds what thinner rows could ever save
_NARROW_MIN_BYTES = 1 << 15
# every Nth use of a cached capacity plan takes the synced path anyway,
# so a site whose data turned skewed after warmup regains the 1-factor
# plan within N exchanges instead of never (perf-only: the overflow
# flag already guards correctness)
_CAP_RESYNC_EVERY = 32


def _optimistic_ok(mex: MeshExec, cap_ident: Tuple, min_cap: int,
                   ident: Tuple = (),
                   counts=None) -> Optional[Tuple[int, int]]:
    """Cached (M_pad, out_cap) when this site may dispatch phase B
    WITHOUT the host sync, else None.

    Requirements: the overlap/cap-cache knobs are on, the site's last
    synced plan was the dense one (a skew-flipped or ragged site needs
    the host S every time), a capacity plan is cached, no loop capture
    is recording (captures keep today's synced semantics so tapes bake
    the same plan they always did), and single-controller (a deferred
    per-process heal would desynchronize the collective schedule —
    same reasoning as the memory ladder's multi-process guard).

    With the adaptive planner attached (api/planner.py), the cached
    plan additionally survives the planner's verdict: a site marked
    for re-optimization (an audit or deferred check caught the learned
    state lying), or host-known input ``counts`` proving the cached
    capacities CANNOT hold (a guaranteed miss), re-chooses the synced
    plan instead — the stale sticky state is dropped so the re-plan
    ratchets from the current data, exactly the plan a cold run would
    build."""
    if not cap_cache_enabled():
        return None
    if mex.loop_recorder is not None:
        return None
    if getattr(mex, "num_processes", 1) > 1 \
            and not getattr(mex, "_plan_seed_symmetric", True):
        # per-process optimism on a multi-controller mesh is safe only
        # when every rank provably holds the SAME plan state. That is
        # the DEFAULT: in-process-learned state derives from the
        # replicated send matrix under the lockstep submission
        # contract, so a storeless steady-state service overlaps its
        # exchanges too (planner edge (a), ISSUE 18). The deferred
        # heal is then lockstep: the overflow flag is a function of
        # the replicated send matrix alone (narrow-range verdicts are
        # pmax'd), and checks drain at the same program points on
        # every controller. The flag only goes FALSE when seeds of
        # unknown provenance were installed (a per-rank store read —
        # install_plan_seeds without the symmetric attestation); the
        # rank-0 broadcast path re-attests it True. Without the
        # guarantee, keep the synced plan every time.
        return None
    if resolve_mode(mex) != "dense":
        return None
    plans = getattr(mex, "_xchg_plan", None)
    if plans is None:
        plans = mex._xchg_plan = {}
    kind = plans.get(cap_ident)
    if kind is None:
        # warm restart: the plan store remembers this site's last
        # synced verdict — a "dense" seed (with seeded capacities
        # below) lets the FIRST exchange of a fresh process dispatch
        # optimistically, zero host plan syncs
        kind = plan_seed(mex, "plan", cap_ident)
        if kind is not None:
            kind = plans[cap_ident] = str(kind)
    if kind != "dense":
        return None
    cache = getattr(mex, "_sticky_caps", None)
    if cache is None:
        cache = mex._sticky_caps = {}
    seeded = False
    caps = cache.get(cap_ident)
    if caps is None:
        caps = _seeded_caps(mex, cap_ident)
        if caps is not None:
            cache[cap_ident] = caps
            seeded = True
    if not caps or len(caps) != 2 or caps[1] < min_cap:
        return None
    pl = _planner_of(mex)
    if pl is not None:
        site = "xchg:" + _ident_digest(ident)[:10]
        if seeded:
            pl.note_seeded(site)
        ok, why = pl.optimistic_verdict(site, caps, counts,
                                        mex.num_workers)
        if not ok:
            # re-optimization: invalidate the learned state the lie
            # lives in so the forced synced plan re-ratchets from the
            # current data, and put the switched decision (with both
            # plans' costs) where explain() shows it
            cache.pop(cap_ident, None)
            getattr(mex, "_sticky_ranges", {}).pop(cap_ident, None)
            pl.note_switch()
            need = None
            if counts is not None:
                need = -(-int(np.asarray(counts).sum())
                         // max(mex.num_workers, 1))
            pl.record_replan(
                _decisions.ledger_of(mex), site, "synced",
                predicted=need,
                rejected=[("optimistic", float(caps[1]))],
                reason=why, unit="rows")
            faults.note("recovery", what="planner.replan",
                        site=site, why=why[:120], _quiet=True)
            return None
    # periodic re-plan: the dense-vs-1-factor skew decision needs the
    # host S, which steady-state hits elide — without this, skew that
    # develops AFTER warmup (and stays inside the monotone caps) would
    # keep the padded dense plan forever. Every Nth use of a site runs
    # the synced path, re-evaluating skew and re-recording the plan.
    hits = getattr(mex, "_xchg_plan_uses", None)
    if hits is None:
        hits = mex._xchg_plan_uses = {}
    n = hits.get(cap_ident, 0) + 1
    hits[cap_ident] = n
    if n % _CAP_RESYNC_EVERY == 0:
        return None
    return caps


def _dispatch_chunked(mex: MeshExec, treedef, sorted_dest, sorted_leaves,
                      smat, M_pad: int, out_cap: int, narrow=None,
                      ident: Tuple = ()):
    """The dense phase-B program(s): K row-range chunk dispatches over
    a shared output accumulator, all plan values derived IN-TRACE from
    the replicated [W, W] send matrix ``smat``.

    Chunk j ships destination-slot range [lo_j, hi_j) of every (src,
    dst) pair: the all_to_all blocks are [W, hi_j-lo_j] and the receive
    scatter lands rows at ``roff[src] + slot`` — exactly the bulk
    program's positions, so any K (including 1, the bulk form) is
    bit-identical. Each chunk is its own ``_CountedJit`` dispatch, so
    admission control, the OOM-retry ladder and dispatch stats cover
    every chunk, and jax async dispatch pipelines chunk i's collective
    with chunk i+1's scatter. The FIRST chunk additionally returns the
    device-resident output counts and the capacity-overflow flag (both
    functions of ``smat`` alone), so the optimistic path's deferred
    check blocks only until chunk 0 lands — chunks 1..K-1 and the
    consumer's next program keep overlapping.

    ``narrow`` (per-leaf dtype-str or None) ships eligible integer
    leaves across the fabric as their narrowed dtype — the cast is
    exact for in-range values, so results stay bit-identical; the
    scatter accumulator holds the narrow form and widens once, at the
    last chunk. Chunk 0's overflow flag then ALSO checks in-trace that
    every valid value fits its narrow dtype: synced plans derive the
    spec from the current data (the check can only pass), optimistic
    dispatches run on the LEARNED spec and data that outgrew it routes
    to the synced heal instead of truncating. One program serves both
    paths — a separate guarded twin would double every site's phase-B
    compiles for a check that costs two reductions.

    Returns (out_leaves, counts_dev [W, 1] int32, flag [1] int32).
    """
    W = mex.num_workers
    cap = sorted_leaves[0].shape[1] if sorted_leaves else \
        sorted_dest.shape[1]
    leafsig = tuple((l.dtype, l.shape[2:]) for l in sorted_leaves)
    n_leaves = len(sorted_leaves)
    item_bytes = leaf_item_bytes(sorted_leaves)
    K = _chunk_count(mex, W, M_pad, item_bytes)
    led = _decisions.ledger_of(mex)
    if led is not None:
        site = "xchg:" + _ident_digest(ident)[:10]
        vol = W * M_pad * item_bytes
        # mirror _chunk_count's precedence exactly: the overlap kill
        # switch wins over the env pin, and an unparseable pin falls
        # through to the auto policy — the recorded reason must match
        # the path actually taken
        env_k = os.environ.get("THRILL_TPU_XCHG_CHUNKS")
        try:
            int(env_k)          # any parseable pin governs (clamped)
            pinned = True
        except (TypeError, ValueError):
            pinned = False
        led.record(
            "xchg_chunks", site, str(K), predicted=vol,
            reason=("bulk: overlap off" if not overlap_enabled()
                    else "forced" if pinned
                    else "bulk: volume below pipelining break-even"
                    if K == 1 else "chunked: volume worth pipelining"))
        if narrow is not None:
            wide_b = W * (W - 1) * M_pad * item_bytes
            led.record(
                "xchg_narrow", site, "narrow",
                predicted=W * (W - 1) * M_pad
                * _narrow_item_bytes(sorted_leaves, narrow),
                rejected=[("wide", wide_b)],
                reason="learned integer ranges fit narrower dtypes",
                leaves=sum(1 for s in narrow if s is not None))
    bounds = dense_range_bounds(M_pad, K)
    ranges = [(int(bounds[j]), int(bounds[j + 1])) for j in range(K)
              if bounds[j + 1] > bounds[j]]
    from jax.sharding import PartitionSpec as P

    def chunk_program(lo: int, hi: int, first: bool, last: bool):
        M_j = hi - lo
        key = ("xchg_chunk", cap, M_pad, out_cap, lo, hi, first, last,
               W, treedef, leafsig, narrow)

        def build():
            def f(sdest, smat_a, *ls):
                from ..core import rowmove
                widx = lax.axis_index(AXIS)
                S_row = jnp.take(smat_a, widx, axis=0).astype(jnp.int32)
                S_col = jnp.take(smat_a, widx, axis=1).astype(jnp.int32)
                off = _ex_cumsum(S_row)
                roff = _ex_cumsum(S_col)
                d = sdest[0]
                i = jnp.arange(cap)
                dc = jnp.clip(d, 0, W - 1)
                slot = i - jnp.take(off, dc)
                sel = (d < W) & (slot >= lo) & (slot < hi)
                send_idx = jnp.where(sel, dc * M_j + (slot - lo),
                                     W * M_j)
                jj = jnp.arange(M_j)
                n_from = jnp.clip(S_col - lo, 0, M_j)
                pos = jnp.where(jj[None, :] < n_from[:, None],
                                roff[:, None] + lo + jj[None, :],
                                out_cap)
                # clamp: under a capacity overflow positions can pass
                # the dump row — those rows are garbage either way and
                # the flag below routes the whole exchange to the
                # synced re-run
                pos = jnp.minimum(pos.reshape(-1), out_cap)
                pack = rowmove.enabled()
                srcs, accs = ls[:n_leaves], ls[n_leaves:]
                outs = []
                range_bad = jnp.zeros((), jnp.int32)
                for li, l in enumerate(srcs):
                    xw = l[0]
                    nd = narrow[li] if narrow is not None else None
                    if nd is not None:
                        if first:
                            info = np.iinfo(np.dtype(nd))
                            v = d < W
                            vm = v.reshape((-1,) + (1,)
                                           * (xw.ndim - 1))
                            oob = vm & ((xw < info.min)
                                        | (xw > info.max))
                            range_bad = jnp.maximum(
                                range_bad,
                                jnp.max(oob.astype(jnp.int32)))
                        xw = xw.astype(np.dtype(nd))
                    x, m = rowmove.pack_rows(xw) if pack \
                        else (xw, None)
                    recv = ship_blocks(x, send_idx, W, M_j)
                    if first:
                        acc = jnp.zeros((out_cap + 1,) + x.shape[1:],
                                        x.dtype)
                    else:
                        acc = accs[li][0]
                    acc = acc.at[pos].set(recv)
                    if last:
                        wide = rowmove.unpack_rows(acc[:out_cap], m)
                        if nd is not None:
                            wide = wide.astype(l.dtype)
                        outs.append(wide[None])
                    else:
                        outs.append(acc[None])
                if not first:
                    return tuple(outs)
                cnt = jnp.sum(S_col).astype(jnp.int32)[None, None]
                ovf = jnp.logical_or(
                    smat_a.max() > M_pad,
                    smat_a.sum(axis=0).max() > out_cap)
                ovf = ovf.astype(jnp.int32)
                if narrow is not None:
                    # values past the narrow ranges spoil the cast on
                    # SOME worker: replicate the verdict so the
                    # deferred check sees it wherever it drains
                    ovf = jnp.maximum(ovf,
                                      lax.pmax(range_bad, AXIS))
                return (cnt, ovf.reshape(1), *outs)

            na = 2 + n_leaves + (0 if first else n_leaves)
            in_specs = (P(AXIS), P()) + (P(AXIS),) * (na - 2)
            out_specs = ((P(AXIS), P()) if first else ()) \
                + (P(AXIS),) * n_leaves
            return mex.smap(f, na, out_specs=out_specs,
                            in_specs=in_specs)

        return mex.cached(key, build)

    armed = faults.REGISTRY.active()
    # chunk i's accumulator is consumed exactly once by chunk i+1:
    # donate it so XLA aliases instead of copying the [W, out_cap]
    # buffers K-1 times. CPU has no input-output aliasing (and the OOM
    # ladder's donation-disarm story stays simplest un-donated under
    # armed faults / capture), so the twin is TPU/GPU-only.
    donate = (bool(mex.devices)
              and mex.devices[0].platform not in ("cpu",)
              and mex.loop_recorder is None and not armed)
    acc_pos = tuple(range(2 + n_leaves, 2 + 2 * n_leaves))
    counts_dev = flag = None
    accs: List[Any] = []
    with _trace.span_of(getattr(mex, "tracer", None), "exchange",
                        "phase_b", chunks=len(ranges),
                        narrowed=narrow is not None or None):
        for j, (lo, hi) in enumerate(ranges):
            first, last = j == 0, j == len(ranges) - 1
            fn = chunk_program(lo, hi, first, last)
            if armed:
                default_policy().run(
                    lambda j=j: faults.check(_F_CHUNK, chunk=j,
                                             chunks=len(ranges)),
                    what="xchg.chunk")
            if first:
                out = fn(sorted_dest, smat, *sorted_leaves)
                counts_dev, flag = out[0], out[1]
                accs = list(out[2:])
            else:
                if donate and acc_pos:
                    call = fn.donating(acc_pos)
                    # aliasing is real here (non-CPU, no capture): count
                    # the chunk handoffs whose accumulators were donated
                    # so benchmarks can report measured donation traffic
                    mex.stats_xchg_donated += len(acc_pos)
                else:
                    call = fn
                accs = list(call(sorted_dest, smat, *sorted_leaves,
                                 *accs))
    mex.stats_padded_rows += W * M_pad
    # wire truth vs raw equivalent: narrowed rows cross the fabric at
    # their cast width; the raw counter records what full-width rows
    # would have shipped (wire_compress_ratio's denominator)
    wire_rows = W * (W - 1) * M_pad
    mex.stats_bytes_wire_device += wire_rows * _narrow_item_bytes(
        sorted_leaves, narrow)
    mex.stats_bytes_wire_device_raw += wire_rows * item_bytes
    return accs, counts_dev, flag


def _exchange_optimistic(mex: MeshExec, treedef, sorted_dest,
                         sorted_leaves, send_mat, caps: Tuple[int, int],
                         ident: Tuple, min_cap: int = 1,
                         range_mat=None) -> DeviceShards:
    """Phase B on the CACHED capacity plan: no host sync, counts come
    back device-resident, and a deferred check (drained at the next
    consumer boundary / host realization, like the hinted-join
    overflow) verifies the cached capacities actually held — on a miss
    the exchange re-runs from the retained phase-A output under the
    freshly synced plan and heals the shards in place.

    Row narrowing rides the same optimism: the spec LEARNED from past
    synced runs narrows this dispatch, and chunk 0's flag verifies
    every value still fits it — data that outgrew the learned ranges
    is a miss like any other, healed by the synced re-run (which
    re-reads the device ranges and widens the sticky spec)."""
    M_pad, out_cap = caps
    W = mex.num_workers
    item_bytes = leaf_item_bytes(sorted_leaves)
    cap = sorted_leaves[0].shape[1] if sorted_leaves else 0
    cap_ident = _dense_cap_ident(ident, cap, treedef, sorted_leaves)
    narrow = None
    if range_mat is not None:
        narrow = _pack_degraded(
            _sticky_spec(mex, cap_ident, sorted_leaves))
    # the optimistic-vs-synced decision: predicted = the cached output
    # capacity the dispatch trusts; the actual need is only known at
    # deferred-check time, where the audit joins (hit or miss)
    dec = _decisions.record_of(
        mex, "xchg_optimistic", "xchg:" + _ident_digest(ident)[:10],
        "optimistic", predicted=out_cap,
        rejected=[("synced", None)], unit="rows",
        reason="capacity plan cached; host sync elided", m_pad=M_pad)
    with _trace.span_of(getattr(mex, "tracer", None), "exchange",
                        "optimistic", m_pad=M_pad, out_cap=out_cap):
        out_leaves, counts_dev, flag = _dispatch_chunked(
            mex, treedef, sorted_dest, sorted_leaves, send_mat, M_pad,
            out_cap, narrow=narrow, ident=ident)
    tree = jax.tree.unflatten(treedef, out_leaves)
    shards = DeviceShards(mex, tree, counts_dev)

    def check(counts: np.ndarray):
        doc = getattr(mex, "doctor", None)
        t0 = time.perf_counter() if doc is not None else 0.0
        overflowed = bool(mex._fetch_raw(flag).reshape(-1)[0])
        S = mex._fetch_raw(send_mat).astype(np.int64)
        if doc is not None:
            doc.record_wait("xchg.deferred_check", None,
                            time.perf_counter() - t0, lane="exchange")
        # the optimistic-vs-synced verdict, at the moment it is
        # actually known (deferred-check time)
        _trace.instant_of(getattr(mex, "tracer", None), "exchange",
                          "cap_hit" if not overflowed
                          else "capacity_miss",
                          m_pad=M_pad, out_cap=out_cap)
        # audit join: the truth the optimistic dispatch deferred — how
        # many rows each worker actually had to receive vs the cached
        # capacity it trusted (err = overprovision factor on a hit)
        _decisions.resolve_of(
            mex, dec, max(int(S.sum(axis=0).max()), 1),
            verdict="hit" if not overflowed else "miss")
        if not overflowed:
            # the exchange is accounted HERE, not at dispatch: a miss
            # must count one (synced) exchange, not an optimistic one
            # plus its healed re-run
            mex.stats_cap_cache_hits += 1
            mex.stats_exchanges_overlapped += 1
            account_traffic(mex, S, item_bytes,
                            site="xchg:" + _ident_digest(ident)[:10],
                            overlapped=True, cap_hit=True)
            pl = _planner_of(mex)
            if pl is not None and pl.skew_developed(S, item_bytes):
                # the observed send matrix now prefers the 1-factor
                # schedule: mark the site so the NEXT exchange re-syncs
                # and re-chooses immediately instead of riding the
                # cached dense plan out to the periodic resync window
                pl.mark_replan(
                    "xchg:" + _ident_digest(ident)[:10],
                    "deferred check observed a skewed send matrix")
            return None
        # capacity (or narrow-range) miss: the cached plan truncated —
        # re-run phases host+B from the retained phase-A output (the
        # synced plan grows the sticky caps and re-learns the ranges,
        # so the NEXT run hits again)
        mex.stats_cap_cache_misses += 1
        faults.note("recovery", what="xchg.capacity_miss",
                    cached=(M_pad, out_cap))
        ranges = (None if range_mat is None
                  else mex._fetch_raw(range_mat))
        healed = _exchange_planned(mex, treedef, sorted_dest,
                                   sorted_leaves, S, min_cap=min_cap,
                                   ident=ident, smat_dev=send_mat,
                                   ranges=ranges)
        shards.tree = healed.tree
        return healed.counts

    shards._counts_check = check
    # backstop drain point: any tracked fetch / action egress heals an
    # exchange whose output a pipeline abandoned before consuming.
    # WEAK ref only — the hinted-join precedent (join.py): a lingering
    # queue entry must pin no device buffers, or a fetch-free steady-
    # state loop would grow one [W, out_cap] output per query and an
    # HbmGovernor spill could never actually free the HBM
    ref = weakref.ref(shards)

    def _backstop():
        s = ref()
        if s is not None:
            s.validate_pending()

    mex._pending_checks.append(_backstop)
    return shards


def _exchange_planned(mex: MeshExec, treedef, sorted_dest, sorted_leaves,
                      S: np.ndarray, min_cap: int = 1,
                      ident: Tuple = (),
                      smat_dev: Optional[Any] = None,
                      ranges: Optional[np.ndarray] = None
                      ) -> DeviceShards:
    """Phases host+B given phase-A output (also used by scatter paths).

    ``smat_dev`` is the replicated device copy of ``S`` when phase A
    produced one (saves the plan upload); callers with a host-only
    plan (Sort's presorted entry) leave it None. ``ranges`` is the
    fetched [L, 2] per-leaf min/max when phase A computed it — the
    narrow spec derived from it (union'd with the site's remembered
    ranges, so it covers the current data by construction) ships the
    padded rows at their narrowed widths."""
    W = mex.num_workers
    cap = sorted_leaves[0].shape[1] if sorted_leaves else 0
    R = S.sum(axis=0)                             # recv totals per worker
    new_counts = R.astype(np.int64)

    account_traffic(mex, S, leaf_item_bytes(sorted_leaves),
                    site="xchg:" + _ident_digest(ident)[:10])

    if W == 1:
        # no movement: items are already dest-sorted (valid first)
        tree = jax.tree.unflatten(treedef, sorted_leaves)
        return DeviceShards(mex, tree, new_counts)

    # every path below constructs a plan FROM THE SYNCED HOST S — the
    # event the plan store exists to make a warm restart skip
    count_plan_build(mex)
    cap_ident = _dense_cap_ident(ident, cap, treedef, sorted_leaves)
    mode = resolve_mode(mex)
    item_bytes = leaf_item_bytes(sorted_leaves)
    # one cost evaluation serves both the skew verdict and the decision
    # record, so the recorded estimates are EXACTLY the numbers the
    # choice was made from (same math as _skewed). With the adaptive
    # planner attached the CHOICE is the planner's (api/planner.py
    # exchange_strategy — the same inequality, owned by the one cost
    # model); without it the legacy per-site form decides.
    dense_b, of_b, n_rounds = _strategy_costs(mex, S, item_bytes)
    pl = _planner_of(mex)
    if pl is not None:
        chosen_mode, _, _, _why = pl.exchange_strategy(S, item_bytes,
                                                       mode)
        skew = mode == "dense" and chosen_mode == "onefactor"
    else:
        skew = (mode == "dense"
                and dense_b - of_b > n_rounds * _bytes_eq(mex))
    led = _decisions.ledger_of(mex)
    if led is not None:
        # the strategy choice, with the rejected plan's estimated cost
        # — audited immediately against the true (unpadded) payload:
        # err = how much padding the chosen plan ships per real byte
        site = "xchg:" + _ident_digest(ident)[:10]
        if mode == "ragged":
            chosen, pred, rej, why = "ragged", (
                (int(S.sum()) - int(np.trace(S))) * item_bytes), \
                [("dense", dense_b)], "configured mode"
        elif mode == "onefactor" or skew:
            chosen, pred, rej = "onefactor", of_b, [("dense", dense_b)]
            why = "skewed send matrix" if skew else "configured mode"
        else:
            chosen, pred, rej = "dense", dense_b, [("onefactor", of_b)]
            why = "balanced send matrix"
        rec = led.record("xchg_strategy", site, chosen, predicted=pred,
                         rejected=rej, reason=why,
                         items=int(S.sum()))
        led.resolve(rec, (int(S.sum()) - int(np.trace(S)))
                    * item_bytes)
    # the narrow spec is derived ONCE, before the strategy branch, and
    # keyed by the DENSE cap_ident — every phase-B flavor (dense
    # chunked, 1-factor rounds, ragged) shares one learned range store
    # per site. Synced paths union the current ranges in, so the spec
    # covers this exchange's data by construction (cast is exact, no
    # in-trace guard needed); the chunk-0 overflow guard remains on
    # the optimistic dense path, which trusts ranges it did not fetch.
    narrow = _pack_degraded(_spec_from_ranges(
        mex, cap_ident, sorted_leaves,
        _narrowable_leaves(sorted_leaves), ranges))
    with _trace.span_of(getattr(mex, "tracer", None), "exchange",
                        "synced", mode=mode):
        if mode == "ragged":
            mex._xchg_plan[cap_ident] = "sync"
            return _exchange_ragged(mex, treedef, sorted_leaves, S,
                                    min_cap, narrow=narrow)
        if mode == "onefactor" or skew:
            # a skew-flipped site stays synced: the dense-vs-1-factor
            # decision needs the host S, which the optimistic path
            # elides
            mex._xchg_plan[cap_ident] = "sync"
            return _exchange_onefactor(mex, treedef, sorted_dest,
                                       sorted_leaves, S, min_cap,
                                       ident=ident, narrow=narrow)

        M_pad, out_cap = _sticky_caps(
            mex, cap_ident,
            (max(int(S.max()), 1), max(int(R.max()), min_cap, 1)))
        mex._xchg_plan[cap_ident] = "dense"
        smat = smat_dev if smat_dev is not None else \
            mex.put_small(S.astype(np.int32), replicated=True)
        out_leaves, _counts_dev, _flag = _dispatch_chunked(
            mex, treedef, sorted_dest, sorted_leaves, smat, M_pad,
            out_cap, narrow=narrow, ident=ident)
        tree = jax.tree.unflatten(treedef, out_leaves)
        return DeviceShards(mex, tree, new_counts)


def _exchange_onefactor(mex: MeshExec, treedef, sorted_dest, sorted_leaves,
                        S: np.ndarray, min_cap: int = 1,
                        ident: Tuple = (),
                        narrow=None) -> DeviceShards:
    """Skew-proof dense exchange: W-1 ``ppermute`` rounds, one partner
    per round, each round padded only to ITS pair maximum.

    The reference schedules point-to-point exchanges the same way
    (1-factor rounds, thrill/net/group.hpp:90-107). Under a 100:1 key
    skew the uniform all_to_all pads every pair to the global maximum
    (W x waste); here round r ships worker w -> (w + r) % W with
    capacity max_w S[w, (w+r)%W], so bytes track the actual data. The
    diagonal (r = 0) is a local scatter with no communication.
    """
    W = mex.num_workers
    cap = sorted_leaves[0].shape[1] if sorted_leaves else 0
    R = S.sum(axis=0)
    new_counts = R.astype(np.int64)
    rounds = one_factor_rounds(mex)               # tier-pure if sliced
    cap_ident = ("xchg_of_caps", ident, cap, treedef,
                 tuple((l.dtype, l.shape[2:]) for l in sorted_leaves))
    needed = tuple(
        max(int(S[np.arange(W), to].max()), 1) for to in rounds
    ) + (max(int(R.max()), min_cap, 1),)
    caps = _sticky_caps(mex, cap_ident, needed)
    M_rounds, out_cap = caps[:-1], caps[-1]
    mex.stats_padded_rows += sum(M_rounds)
    # rounds ship at the narrowed width; _raw keeps the full-width
    # equivalent (the two halves of wire_compress_ratio)
    of_rows = W * sum(M_rounds)
    mex.stats_bytes_wire_device += of_rows * _narrow_item_bytes(
        sorted_leaves, narrow)
    mex.stats_bytes_wire_device_raw += of_rows * leaf_item_bytes(
        sorted_leaves)

    key_b = ("xchg_of", cap, M_rounds, out_cap, mex.num_slices, treedef,
             narrow,
             tuple((l.dtype, l.shape[2:]) for l in sorted_leaves))
    wide_dts = [l.dtype for l in sorted_leaves]

    def build_b():
        def fb(sdest, srow, scol, *ls):
            from ..core import rowmove
            d = sdest[0]
            S_row = srow[0]
            S_col = scol[0]
            off = _ex_cumsum(S_row)
            roff = _ex_cumsum(S_col)
            i = jnp.arange(cap)
            widx = lax.axis_index(AXIS)
            raw = [l[0] for l in ls]
            if narrow is not None:
                # cast eligible leaves to their learned narrow dtype
                # before any round ships; the spec covers this data
                # (synced plan, ranges union'd), so the round-trip
                # cast is exact
                raw = [x if narrow[li] is None
                       else x.astype(np.dtype(narrow[li]))
                       for li, x in enumerate(raw)]
            if rowmove.enabled():
                xs, metas = rowmove.pack_leaves(raw)
            else:
                xs, metas = raw, [None] * len(raw)
            outs = [jnp.zeros((out_cap + 1,) + x.shape[1:], x.dtype)
                    for x in xs]
            # identity round: local scatter, no communication
            sel0 = d == widx
            slot0 = i - jnp.take(off, widx)
            pos0 = jnp.where(sel0, jnp.take(roff, widx) + slot0, out_cap)
            outs = [o.at[pos0].set(x) for o, x in zip(outs, xs)]
            for r, to in enumerate(rounds):
                inv = np.empty(W, dtype=np.int64)
                inv[to] = np.arange(W)
                d_r = jnp.take(jnp.asarray(to), widx)   # partner I send to
                s_r = jnp.take(jnp.asarray(inv), widx)  # partner I recv from
                sel = d == d_r
                slot = i - jnp.take(off, d_r)
                M_r = M_rounds[r]
                send_idx = jnp.where(sel, slot, M_r)
                perm = [(w, int(to[w])) for w in range(W)]
                j = jnp.arange(M_r)
                n_recv = jnp.take(S_col, s_r)
                pos = jnp.where(j < n_recv, jnp.take(roff, s_r) + j,
                                out_cap)
                for li, x in enumerate(xs):
                    buf = jnp.zeros((M_r + 1,) + x.shape[1:], x.dtype)
                    buf = buf.at[send_idx].set(x)[:M_r]
                    recv = lax.ppermute(buf, AXIS, perm=perm)
                    outs[li] = outs[li].at[pos].set(recv)
            res = []
            for li, (o, m) in enumerate(zip(outs, metas)):
                y = rowmove.unpack_rows(o[:out_cap], m)
                if y.dtype != wide_dts[li]:
                    y = y.astype(wide_dts[li])     # widen back
                res.append(y[None])
            return tuple(res)

        return mex.smap(fb, 3 + len(sorted_leaves))

    fb = mex.cached(key_b, build_b)
    srow = mex.put_small(S.astype(np.int32))
    scol = mex.put_small(S.T.copy().astype(np.int32))
    out_leaves = list(fb(sorted_dest, srow, scol, *sorted_leaves))
    tree = jax.tree.unflatten(treedef, out_leaves)
    return DeviceShards(mex, tree, new_counts)


def _ragged_builder(mex: MeshExec, out_cap: int, num_leaves: int,
                    narrow=None):
    """The jitted ragged-exchange program (shared by the execution path
    and by :func:`lower_ragged_exchange`, which plan-validates it on
    builds whose XLA backend cannot execute the op). ``narrow`` casts
    eligible leaves to their learned narrow dtype before the collective
    and widens after (exact: the synced spec covers the data)."""

    def f(srow, scol, olanding, *ls):
        from ..core import rowmove
        S_row = srow[0].astype(jnp.int32)     # my sends by dest
        S_col = scol[0].astype(jnp.int32)     # my recvs by source
        in_off = _ex_cumsum(S_row)
        # where MY chunk lands inside each destination's buffer:
        # sources before me writing to that destination
        out_off = olanding[0].astype(jnp.int32)
        pack = rowmove.enabled()
        outs = []
        for li, l in enumerate(ls):
            x0 = l[0]
            wide_dt = x0.dtype
            nd = narrow[li] if narrow is not None else None
            if nd is not None:
                x0 = x0.astype(np.dtype(nd))
            x, m = rowmove.pack_rows(x0) if pack else (x0, None)
            out = jnp.zeros((out_cap,) + x.shape[1:], x.dtype)
            res = lax.ragged_all_to_all(
                x, out, in_off, S_row, out_off, S_col,
                axis_name=AXIS)
            y = rowmove.unpack_rows(res, m)
            if y.dtype != wide_dt:
                y = y.astype(wide_dt)              # widen back
            outs.append(y[None])
        return tuple(outs)

    return mex.smap(f, 3 + num_leaves)


def _warn_ragged_untested(mex: MeshExec) -> None:
    """Loud one-time gate: the ragged path cannot RUN on this image
    (XLA:CPU lacks the op), so a user forcing it off-TPU must know the
    path is lowering-validated only (see __graft_entry__ dryrun)."""
    if getattr(mex, "_warned_ragged", False):
        return
    mex._warned_ragged = True
    plat = mex.devices[0].platform if mex.devices else "?"
    if plat not in ("tpu",):
        import sys
        print(f"thrill_tpu: THRILL_TPU_EXCHANGE=ragged on platform "
              f"'{plat}' — lax.ragged_all_to_all is UNIMPLEMENTED on "
              f"XLA:CPU; this path is plan/lowering-validated on this "
              f"build but has never executed here. Expect a compile "
              f"error; use dense/onefactor off-TPU.", file=sys.stderr)


def _exchange_ragged(mex: MeshExec, treedef, sorted_leaves, S: np.ndarray,
                     min_cap: int = 1, narrow=None) -> DeviceShards:
    """TPU fast path: ``lax.ragged_all_to_all`` — no per-pair padding.

    Phase-A output is already destination-contiguous, which is exactly
    the operand layout ragged_all_to_all wants: per-destination input
    offsets are the exclusive cumsum of the send-count row; receive
    offsets group by source (rank order), preserving the same
    deterministic item order as the dense path. XLA:CPU lacks this op,
    so the path is only selected via THRILL_TPU_EXCHANGE=ragged.
    """
    _warn_ragged_untested(mex)
    R = S.sum(axis=0)
    new_counts = R.astype(np.int64)
    # ragged ships exactly the off-diagonal items — no padding tax;
    # narrowed widths on the device counter, full width on _raw
    ragged_items = int(S.sum()) - int(np.trace(S))
    mex.stats_bytes_wire_device += ragged_items * _narrow_item_bytes(
        sorted_leaves, narrow)
    mex.stats_bytes_wire_device_raw += ragged_items * leaf_item_bytes(
        sorted_leaves)
    out_cap = round_up_pow2(max(int(R.max()), min_cap, 1))
    key = ("xchg_ragged", out_cap, treedef, narrow,
           tuple((l.dtype, l.shape[1:]) for l in sorted_leaves))
    fb = mex.cached(key, lambda: _ragged_builder(mex, out_cap,
                                                 len(sorted_leaves),
                                                 narrow=narrow))
    srow = mex.put_small(S.astype(np.int32))
    scol = mex.put_small(S.T.copy().astype(np.int32))
    # landing[w, d] = sum of S[0:w, d] (receiver-side offset of w's chunk)
    landing = (np.cumsum(S, axis=0) - S).astype(np.int32)
    out_leaves = list(fb(srow, scol, mex.put_small(landing), *sorted_leaves))
    tree = jax.tree.unflatten(treedef, out_leaves)
    return DeviceShards(mex, tree, new_counts)


def lower_ragged_exchange(mex: MeshExec, leaf_specs, S: np.ndarray,
                          min_cap: int = 1) -> str:
    """Trace + lower (NOT compile) the ragged exchange program over the
    current mesh and return its StableHLO text.

    This is the strongest validation available on builds whose XLA
    backend lacks the op: the full plan — offset/size computation,
    packed row movement, shard_map specs, static shapes — is traced
    exactly as the execution path would (same builder), and the caller
    can assert the ragged-all-to-all collective is present. Executed by
    the driver's ``dryrun_multichip`` so a pod user is not the first
    trace of this code.

    ``leaf_specs``: [(dtype, row_shape), ...] for the phase-A sorted
    leaves (leading dims [W, cap] are derived from ``S``).
    """
    W = mex.num_workers
    cap = int(round_up_pow2(max(int(S.sum(axis=1).max()), min_cap, 1)))
    out_cap = int(round_up_pow2(max(int(S.sum(axis=0).max()),
                                    min_cap, 1)))
    fb = _ragged_builder(mex, out_cap, len(leaf_specs))
    i32 = jax.ShapeDtypeStruct((W, W), jnp.int32)
    leaves = [jax.ShapeDtypeStruct((W, cap) + tuple(shape), dtype)
              for dtype, shape in leaf_specs]
    lowered = fb.lower(i32, i32, i32, *leaves)
    return lowered.as_text()


# The host-path shuffle lives in data/multiplexer.py (host_exchange):
# single-process bucketing plus the cross-process framed-batch plane.
