"""All-to-all item exchange: the TPU-native shuffle data plane.

The reference moves items between workers through serialized Block
streams multiplexed over TCP/MPI connections (reference:
thrill/data/multiplexer.hpp:67, cat_stream.hpp:155, mix_stream.hpp:126,
stream.hpp:77-210 ``Scatter``). The TPU-native equivalent is a
bulk-synchronous exchange of columnar shards over the ICI mesh:

  Phase A (jit): compute each item's destination worker, stably sort
      items by destination, count per-destination sends
      -> the analog of the reference's per-destination BlockWriters.
  Host step: agree on padded block capacity from the [W, W] send-count
      matrix (tiny transfer; shapes must be static for XLA). Capacities
      round up to powers of two so recompilation is rare.
  Phase B (jit): scatter into [W, M] padded per-destination blocks,
      ``lax.all_to_all`` over the mesh, compact received blocks into a
      fresh [out_cap] shard -> the analog of Multiplexer block transit +
      receive-side BlockQueues.

On real TPU pods `lax.ragged_all_to_all` can skip the padding (config
``exchange='ragged'``); XLA:CPU lacks that op, so the dense padded path
is the portable default.

Destination builders cover every DOp shuffle pattern:
  hash partition (ReduceByKey/GroupBy/Join), range partition by splitter
  search (Sort/Merge), index ranges (ReduceToIndex/Zip/Concat/Rebalance)
  and explicit per-item targets.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common.config import round_up_pow2
from ..parallel.mesh import AXIS, MeshExec
from .shards import DeviceShards, HostShards


def _ex_cumsum(x):
    return jnp.cumsum(x) - x


def send_counts(dest: jnp.ndarray, W: int) -> jnp.ndarray:
    """Traced helper (inside shard_map): per-destination send histogram,
    all-gathered into the replicated [W, W] matrix every worker needs
    for the host planning step. ``dest`` uses W for invalid items."""
    from ..core.pallas_kernels import partition_histogram
    send = partition_histogram(dest, W)
    return lax.all_gather(send, AXIS)


def exchange_presorted(mex: MeshExec, treedef, sorted_dest, sorted_leaves,
                       S: np.ndarray, min_cap: int = 1) -> DeviceShards:
    """Ship items that are ALREADY grouped by destination.

    Public entry for operators whose upstream order makes destinations
    monotone (Sort: items are key-sorted, so splitter rank never
    decreases) — they skip the generic phase-A destination sort
    entirely. Contract: ``sorted_dest`` is [W, cap] int32 with each
    worker's valid items contiguous per destination in rank order
    (monotone suffices) and W marking invalid slots; ``sorted_leaves``
    are [W, cap, ...] in that same order; ``S[w, d]`` counts w's items
    bound for d (as produced by ``send_counts``).
    """
    return _exchange_planned(mex, treedef, sorted_dest, sorted_leaves, S,
                             min_cap=min_cap)


def exchange(shards: DeviceShards, dest_builder: Callable, cache_key: Tuple,
             min_cap: int = 1) -> DeviceShards:
    """Move every valid item to the worker computed by ``dest_builder``.

    ``dest_builder(tree, valid_mask, worker_index) -> int32 [cap]`` is
    traced inside the phase-A program; ``cache_key`` must identify it
    (plus its static parameters) for executable caching.
    """
    mex = shards.mesh_exec
    W = mex.num_workers
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)

    # ---- Phase A: destination, local sort, send counts ---------------
    key_a = ("xchg_a", cache_key, cap, treedef,
             tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build_a():
        def fa(counts_dev, *ls):
            count = counts_dev[0, 0]
            mask = jnp.arange(cap) < count
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            widx = lax.axis_index(AXIS)
            dest = dest_builder(tree, mask, widx).astype(jnp.int32)
            dest = jnp.where(mask, jnp.clip(dest, 0, W - 1), W)
            from ..core.device_sort import argsort_words
            perm = argsort_words([dest.astype(jnp.uint64)])
            sorted_dest = jnp.take(dest, perm)
            sorted_ls = [jnp.take(l[0], perm, axis=0) for l in ls]
            # replicate the [W, W] send-count matrix: every process can
            # then fetch it locally (multi-controller safe host step)
            all_send = send_counts(sorted_dest, W)
            return (sorted_dest[None], all_send,
                    *[sl[None] for sl in sorted_ls])

        from jax.sharding import PartitionSpec as P
        return mex.smap(fa, 1 + len(leaves),
                        out_specs=(P(AXIS), P()) +
                        (P(AXIS),) * len(leaves))

    fa = mex.cached(key_a, build_a)
    out_a = fa(shards.counts_device(), *leaves)
    sorted_dest, send_mat = out_a[0], out_a[1]
    sorted_leaves = list(out_a[2:])

    S = mex.fetch(send_mat)                       # [W, W] S[w, d]
    return _exchange_planned(mex, treedef, sorted_dest, sorted_leaves, S,
                             min_cap=min_cap)


def _exchange_planned(mex: MeshExec, treedef, sorted_dest, sorted_leaves,
                      S: np.ndarray, min_cap: int = 1) -> DeviceShards:
    """Phases host+B given phase-A output (also used by scatter paths)."""
    W = mex.num_workers
    cap = sorted_leaves[0].shape[1] if sorted_leaves else 0
    R = S.sum(axis=0)                             # recv totals per worker
    new_counts = R.astype(np.int64)

    # traffic accounting (reference: net::Manager tx/rx counters feeding
    # the end-of-job OverallStats AllReduce, api/context.cpp:1275-1341)
    moved = int(S.sum()) - int(np.trace(S))       # off-diagonal items
    item_bytes = sum(int(np.dtype(l.dtype).itemsize) *
                     int(np.prod(l.shape[2:], dtype=np.int64))
                     for l in sorted_leaves)
    mex.stats_exchanges += 1
    mex.stats_items_moved += moved
    mex.stats_bytes_moved += moved * item_bytes

    if W == 1:
        # no movement: items are already dest-sorted (valid first)
        tree = jax.tree.unflatten(treedef, sorted_leaves)
        return DeviceShards(mex, tree, new_counts)

    import os
    mode = os.environ.get("THRILL_TPU_EXCHANGE") or \
        getattr(mex, "exchange_mode", "dense")
    if mode == "ragged":
        return _exchange_ragged(mex, treedef, sorted_leaves, S, min_cap)

    M_pad = round_up_pow2(max(int(S.max()), 1))
    out_cap = round_up_pow2(max(int(R.max()), min_cap, 1))

    key_b = ("xchg_b", cap, M_pad, out_cap, treedef,
             tuple((l.dtype, l.shape[2:]) for l in sorted_leaves))

    def build_b():
        def fb(sdest, srow, scol, *ls):
            d = sdest[0]                          # [cap] dest-sorted
            S_row = srow[0]                       # my send counts [W]
            S_col = scol[0]                       # my recv counts by src [W]
            off = _ex_cumsum(S_row)
            i = jnp.arange(cap)
            valid = d < W
            slot = i - jnp.take(off, jnp.clip(d, 0, W - 1))
            send_idx = jnp.where(valid, jnp.clip(d, 0, W - 1) * M_pad + slot,
                                 W * M_pad)
            roff = _ex_cumsum(S_col)
            j = jnp.arange(M_pad)[None, :]
            rc_valid = j < S_col[:, None]
            out_idx = jnp.where(rc_valid, roff[:, None] + j, out_cap)

            outs = []
            for l in ls:
                x = l[0]                          # [cap, ...]
                trail = x.shape[1:]
                buf = jnp.zeros((W * M_pad + 1,) + trail, x.dtype)
                buf = buf.at[send_idx].set(x)
                blocks = buf[:W * M_pad].reshape((W, M_pad) + trail)
                recv = lax.all_to_all(blocks, AXIS, split_axis=0,
                                      concat_axis=0, tiled=True)
                out = jnp.zeros((out_cap + 1,) + trail, x.dtype)
                out = out.at[out_idx.reshape(-1)].set(
                    recv.reshape((W * M_pad,) + trail))
                outs.append(out[:out_cap][None])
            return tuple(outs)

        return mex.smap(fb, 3 + len(sorted_leaves))

    fb = mex.cached(key_b, build_b)
    srow = mex.put(S.astype(np.int32))            # row w on worker w
    scol = mex.put(S.T.copy().astype(np.int32))   # col w on worker w
    out_leaves = list(fb(sorted_dest, srow, scol, *sorted_leaves))
    tree = jax.tree.unflatten(treedef, out_leaves)
    return DeviceShards(mex, tree, new_counts)


def _exchange_ragged(mex: MeshExec, treedef, sorted_leaves, S: np.ndarray,
                     min_cap: int = 1) -> DeviceShards:
    """TPU fast path: ``lax.ragged_all_to_all`` — no per-pair padding.

    Phase-A output is already destination-contiguous, which is exactly
    the operand layout ragged_all_to_all wants: per-destination input
    offsets are the exclusive cumsum of the send-count row; receive
    offsets group by source (rank order), preserving the same
    deterministic item order as the dense path. XLA:CPU lacks this op,
    so the path is only selected via THRILL_TPU_EXCHANGE=ragged.
    """
    W = mex.num_workers
    R = S.sum(axis=0)
    new_counts = R.astype(np.int64)
    out_cap = round_up_pow2(max(int(R.max()), min_cap, 1))
    key = ("xchg_ragged", out_cap, treedef,
           tuple((l.dtype, l.shape[1:]) for l in sorted_leaves))

    def build():
        def f(srow, scol, olanding, *ls):
            S_row = srow[0].astype(jnp.int32)     # my sends by dest
            S_col = scol[0].astype(jnp.int32)     # my recvs by source
            in_off = _ex_cumsum(S_row)
            # where MY chunk lands inside each destination's buffer:
            # sources before me writing to that destination
            out_off = olanding[0].astype(jnp.int32)
            outs = []
            for l in ls:
                x = l[0]
                out = jnp.zeros((out_cap,) + x.shape[1:], x.dtype)
                res = lax.ragged_all_to_all(
                    x, out, in_off, S_row, out_off, S_col,
                    axis_name=AXIS)
                outs.append(res[None])
            return tuple(outs)

        return mex.smap(f, 3 + len(sorted_leaves))

    fb = mex.cached(key, build)
    srow = mex.put(S.astype(np.int32))
    scol = mex.put(S.T.copy().astype(np.int32))
    # landing[w, d] = sum of S[0:w, d] (receiver-side offset of w's chunk)
    landing = (np.cumsum(S, axis=0) - S).astype(np.int32)
    out_leaves = list(fb(srow, scol, mex.put(landing), *sorted_leaves))
    tree = jax.tree.unflatten(treedef, out_leaves)
    return DeviceShards(mex, tree, new_counts)


def host_exchange(shards: HostShards, dest_fn: Callable[[Any], int]
                  ) -> HostShards:
    """Host-path shuffle: bucket every item to its destination worker."""
    W = shards.num_workers
    buckets: List[List[Any]] = [[] for _ in range(W)]
    for items in shards.lists:
        for it in items:
            buckets[dest_fn(it) % W].append(it)
    return HostShards(W, buckets)
