"""Distributed item storage: the DIA data plane.

The reference stores DIA data as serialized byte Blocks in a BlockPool
with spill-to-disk (reference: thrill/data/block.hpp:52,
block_pool.hpp:42, file.hpp:56). The TPU-native design replaces
serialized row storage with **columnar struct-of-arrays**: a pytree of
arrays with leading shape ``[W, cap]`` sharded over the worker mesh axis,
plus per-worker valid-item counts. Static ``cap`` keeps XLA shapes
static; ragged per-worker sizes (the essence of DIA partitions, e.g.
after Filter) live in the counts.

Two storage classes implement one concept:

* ``DeviceShards`` — HBM-resident columnar blocks (the hot path).
* ``HostShards``   — per-worker Python lists for arbitrary objects
  (strings, tuples of variable length...), the analog of the
  reference's host-side serialized Files.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common.config import round_up, round_up_pow2
from ..parallel.mesh import MeshExec
from ..common.partition import dense_range_bounds


def resplit_leaves(per_worker_leaves: List[List[np.ndarray]],
                   new_w: int) -> List[List[np.ndarray]]:
    """Re-split per-worker leaf lists across a NEW worker count: the
    concatenation (old worker-rank order) sliced by
    ``dense_range_bounds(total, new_w)`` — exactly the layout a fresh
    ``new_w``-wide run of the same pipeline would produce, which is
    what keeps a resized mesh's results bit-identical to a fixed-W
    run (api/checkpoint.py repartition)."""
    if not per_worker_leaves:
        return [[] for _ in range(new_w)]
    nleaves = len(per_worker_leaves[0])
    merged = [np.concatenate([pw[i] for pw in per_worker_leaves],
                             axis=0)
              for i in range(nleaves)]
    n = merged[0].shape[0] if merged else 0
    bounds = dense_range_bounds(n, new_w).tolist()
    return [[leaf[bounds[w]:bounds[w + 1]] for leaf in merged]
            for w in range(new_w)]


def tree_leaves(tree):
    return jax.tree.leaves(tree)


def columnarize(items, treedef):
    """List of fixed-shape pytree items -> one pytree of stacked
    columns. Flattens each item once (shared by HostShards.to_device
    and the multi-controller multiplexer.host_to_device)."""
    flat = [jax.tree.leaves(it) for it in items]
    cols = [np.asarray([f[i] for f in flat])
            for i in range(treedef.num_leaves)]
    return jax.tree.unflatten(treedef, cols)


def itemize(tree) -> list:
    """Columnar pytree -> list of per-item trees, with scalar (1-D)
    columns unboxed to native Python scalars and bare-leaf items
    unwrapped. THE unboxing used everywhere device columns become host
    items (to_host_shards, the GroupByKey radix path) — item types must
    not depend on which engine materialized them."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return []
    # columnar slices: one tolist()/list() per leaf, not one python
    # round trip per item per leaf
    cols = [leaf.tolist() if leaf.ndim == 1 else list(leaf)
            for leaf in leaves]
    if treedef == jax.tree.structure(0):
        return cols[0]
    return [jax.tree.unflatten(treedef, vals) for vals in zip(*cols)]


def tree_map(fn, *trees):
    return jax.tree.map(fn, *trees)


class DeviceShards:
    """Columnar device storage: leaves [W, cap, ...], sharded on axis 0.

    Per-worker valid counts live in EITHER form and convert lazily:

    * host (numpy [W] int64) — needed by plan steps (exchange sizing,
      splitters, action results);
    * device (sharded [W, 1] int32, a program output) — enough to feed
      the next jitted program.

    A chain of device operators therefore never blocks on a
    device->host counts fetch between programs: jax's async dispatch
    keeps the device running ahead, and the host syncs only where a
    plan genuinely needs the numbers (the analog of the reference's
    overlapped post-phase thread, api/reduce_by_key.hpp:142-168).
    """

    def __init__(self, mesh_exec: MeshExec, tree: Any, counts) -> None:
        self.mesh_exec = mesh_exec
        self.tree = tree
        if isinstance(counts, np.ndarray):
            self._counts_host: Optional[np.ndarray] = counts
            self._counts_dev = None
        else:
            self._counts_host = None
            self._counts_dev = counts          # sharded [W, 1] int32
        # optional deferred validation run when lazy device counts are
        # first realized on the host (e.g. InnerJoin out_size_hint
        # overflow detection — the op skipped its blocking size sync
        # and owes the check at the next natural host realization)
        self._counts_check: Optional[Callable[[np.ndarray], None]] = None

    @property
    def counts(self) -> np.ndarray:
        """Host counts; fetches (and caches) from device on first use."""
        if self._counts_host is None:
            counts = self.mesh_exec.fetch(
                self._counts_dev).reshape(-1).astype(np.int64)
            if self._counts_check is not None:
                # validate BEFORE caching: if the check raises (sticky
                # overflow), the next access re-validates instead of
                # silently serving truncated counts. A RECOVERING check
                # (hinted-join lineage retry) heals self.tree in place
                # and may return REPLACEMENT counts (a fused-chain
                # recovery recomputes downstream counts too).
                fixed = self._counts_check(counts)
                self._counts_check = None
                if fixed is not None:
                    counts = fixed
            self._counts_host = counts
        return self._counts_host

    def validate_pending(self) -> None:
        """Run a deferred counts check NOW (no-op without one).

        Called by the stage driver when these shards flow into a
        downstream operator (api/dia_base.py ParentLink.pull): a
        hinted-join overflow must be detected — and recovered — BEFORE
        any consumer bakes truncated columns into its own program. The
        transfer rides ``_fetch_raw`` (untracked): the producing op
        started it asynchronously at compute time, so by pull time it
        usually only confirms an already-landed host copy instead of
        stalling the dispatch stream like a plan sync would.
        """
        if self._counts_check is None:
            return
        if self._counts_host is not None:
            counts = self._counts_host
        else:
            counts = self.mesh_exec._fetch_raw(
                self._counts_dev).reshape(-1).astype(np.int64)
        fixed = self._counts_check(counts)  # sticky: stays set on raise
        self._counts_check = None
        if fixed is not None:
            self._counts_host = fixed
        elif self._counts_host is None:
            self._counts_host = counts

    @property
    def num_workers(self) -> int:
        return self.mesh_exec.num_workers

    @property
    def cap(self) -> int:
        return tree_leaves(self.tree)[0].shape[1]

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    def counts_device(self) -> jax.Array:
        """Counts as a sharded [W, 1] device array (one scalar per
        shard); cached so repeated programs reuse one transfer."""
        if self._counts_dev is None:
            self._counts_dev = self.mesh_exec.put_small(
                self.counts.astype(np.int32)[:, None])
        return self._counts_dev

    # -- conversion -----------------------------------------------------
    @staticmethod
    def from_worker_arrays(mesh_exec: MeshExec, per_worker: Sequence[Any],
                           cap: int = 0,
                           counts: Optional[np.ndarray] = None
                           ) -> "DeviceShards":
        """Build from W per-worker pytrees of numpy arrays (item axis 0).

        ``counts`` overrides the per-worker lengths (multi-controller
        builds pass globally agreed counts while supplying data only
        for the workers this process owns)."""
        W = mesh_exec.num_workers
        assert len(per_worker) == W
        if counts is None:
            counts = np.array(
                [np.shape(tree_leaves(t)[0])[0] if tree_leaves(t) else 0
                 for t in per_worker], dtype=np.int64)
        if cap <= 0:
            cap = max(1, round_up_pow2(int(counts.max()) if len(counts) else 1))

        def pad_stack(*leaves):
            out = []
            for leaf in leaves:
                leaf = np.asarray(leaf)
                pad = [(0, cap - leaf.shape[0])] + [(0, 0)] * (leaf.ndim - 1)
                out.append(np.pad(leaf, pad))
            return np.stack(out)

        host_tree = tree_map(pad_stack, *per_worker)
        return DeviceShards(mesh_exec, mesh_exec.put_tree(host_tree), counts)

    @staticmethod
    def from_global_numpy(mesh_exec: MeshExec, tree: Any) -> "DeviceShards":
        """Evenly range-split one global pytree (item axis 0) across workers.

        Leaves that are ALREADY device arrays (single-controller) split
        on device for any n/W: one eager gather per leaf, all async —
        no device->host round trip. An iterative driver can therefore
        feed an ``AllGatherArrays`` result (or any eager jnp math on
        it) straight back into ``Distribute`` without leaving jax's
        dispatch stream (the suffix-sorting doubling loop pattern)."""
        W = mesh_exec.num_workers
        leaves = tree_leaves(tree)
        n = leaves[0].shape[0] if leaves else 0
        all_device = bool(leaves) and all(
            isinstance(l, jax.Array) for l in leaves) and \
            getattr(mesh_exec, "num_processes", 1) == 1
        if all_device and n > 0:
            # device-side split for ANY n/W: one eager gather per leaf
            # builds the [W, cap] layout (rows past each worker's count
            # repeat row n-1 — masked by counts like all pad rows).
            # Validity counts are host-known (n is), so no sync.
            bnd = dense_range_bounds(n, W)
            counts = np.diff(bnd)
            cap = max(1, round_up_pow2(int(counts.max())))
            idx = jnp.asarray(np.minimum(
                np.arange(cap)[None, :] + bnd[:W, None], n - 1
            ).reshape(-1))

            def place(leaf):
                arr = jnp.take(leaf, idx, axis=0).reshape(
                    (W, cap) + leaf.shape[1:])
                return jax.device_put(arr, mesh_exec.sharded)

            return DeviceShards(mesh_exec, tree_map(place, tree), counts)
        bounds = dense_range_bounds(n, W).tolist()
        per_worker = [tree_map(lambda a: np.asarray(a)[bounds[w]:bounds[w + 1]], tree)
                      for w in range(W)]
        return DeviceShards.from_worker_arrays(mesh_exec, per_worker)

    def to_worker_arrays(self, local_only: bool = False) -> List[Any]:
        """Fetch to host: W pytrees of numpy arrays trimmed to counts.

        ``local_only`` (multi-controller): read only this process's
        addressable device shards — no cross-process allgather of the
        bulk data — and return ``None`` for non-local workers."""
        # deferred producer validation BEFORE the bulk fetch: a
        # recovering check swaps self.tree, and fetching first would
        # materialize the pre-recovery columns
        self.validate_pending()
        if local_only and getattr(self.mesh_exec, "num_processes", 1) > 1:
            return self._local_worker_arrays()
        host_tree = self.mesh_exec.fetch_tree(self.tree)
        out = []
        for w in range(self.num_workers):
            c = int(self.counts[w])
            out.append(tree_map(lambda a: a[w, :c], host_tree))
        return out

    def _local_worker_arrays(self) -> List[Any]:
        """Per-worker arrays from addressable shards only (None for
        workers owned by other processes)."""
        leaves, treedef = jax.tree.flatten(self.tree)
        per_leaf: List[dict] = []
        for leaf in leaves:
            m: dict = {}
            for sh in leaf.addressable_shards:
                w0 = sh.index[0].start or 0
                data = np.asarray(sh.data)
                for i in range(data.shape[0]):
                    m[w0 + i] = data[i]
            per_leaf.append(m)
        out: List[Any] = []
        local = set(per_leaf[0]) if per_leaf else set(
            getattr(self.mesh_exec, "local_workers", []))
        for w in range(self.num_workers):
            if w not in local:
                out.append(None)
                continue
            c = int(self.counts[w])
            out.append(jax.tree.unflatten(
                treedef, [pl[w][:c] for pl in per_leaf]))
        return out

    def to_global_numpy(self) -> Any:
        """Concatenate all workers' valid items in worker-rank order."""
        per_worker = self.to_worker_arrays()
        return tree_map(lambda *leaves: np.concatenate(leaves, axis=0),
                        *per_worker)

    def to_host_shards(self, reason: str = "unspecified") -> "HostShards":
        """Itemize into per-worker Python lists (scalars unboxed).

        This is a device->host DEMOTION: the pipeline leaves columnar
        device storage and continues at Python speed. Every demotion is
        logged (``reason`` says which operator path forced it) so users
        can see why a "device" pipeline slowed down.
        """
        log = getattr(self.mesh_exec, "logger", None)
        if log is not None and log.enabled:
            log.line(event="device_to_host", reason=reason,
                     items=int(self.counts.sum()))
        lists: List[List[Any]] = []
        # multi-controller: materialize only this process's workers
        # (the host-storage invariant, data/multiplexer.py) — the bulk
        # data never crosses processes on a demotion
        for tree in self.to_worker_arrays(local_only=True):
            lists.append([] if tree is None else itemize(tree))
        return HostShards(self.num_workers, lists)


@dataclasses.dataclass
class HostShards:
    """Per-worker Python item lists (the generic fallback storage)."""

    num_workers: int
    lists: List[List[Any]]

    @property
    def counts(self) -> np.ndarray:
        return np.array([len(l) for l in self.lists], dtype=np.int64)

    @property
    def total(self) -> int:
        return sum(len(l) for l in self.lists)

    def validate_pending(self) -> None:
        """Host storage carries no deferred device validations; the
        no-op keeps the fused-boundary contract uniform (a plan's
        memory-pressure host fallback returns HostShards through
        ``FusionPlan.finish``, which validates unconditionally)."""

    def repartition(self, new_w: int) -> "HostShards":
        """Re-split the items across ``new_w`` workers by the dense
        range layout (concatenate in worker-rank order, slice by
        ``dense_range_bounds`` — the same split every layout site
        uses, common/partition.py)."""
        merged: List[Any] = []
        for items in self.lists:
            merged.extend(items)
        bounds = dense_range_bounds(len(merged), new_w).tolist()
        return HostShards(new_w,
                          [merged[bounds[w]:bounds[w + 1]]
                           for w in range(new_w)])

    def to_device(self, mesh_exec: MeshExec) -> DeviceShards:
        """Columnarize (requires items be fixed-shape pytrees of numbers)."""
        if getattr(mesh_exec, "num_processes", 1) > 1:
            # capacity/counts/schema must be agreed across controllers
            from . import multiplexer
            if multiplexer.multiprocess(mesh_exec):
                return multiplexer.host_to_device(mesh_exec, self)
        per_worker = []
        for items in self.lists:
            if items:
                per_worker.append(columnarize(
                    items, jax.tree.structure(items[0])))
            else:
                per_worker.append(None)
        # empty workers: borrow structure from a non-empty one
        template = next((t for t in per_worker if t is not None), None)
        if template is None:
            raise ValueError("cannot infer schema of an entirely empty DIA")
        empty = tree_map(lambda a: a[:0], template)
        per_worker = [t if t is not None else empty for t in per_worker]
        return DeviceShards.from_worker_arrays(mesh_exec, per_worker)


def compact_valid(tree, mask):
    """Inside-jit compaction: move valid items to the front, stably.

    tree leaves: [n, ...]; mask: [n] bool. Returns (tree, count).
    O(n) cumsum + scatter (invalid items land in a dropped overflow
    slot) — cheaper than a sort and independent of the sort lowering.
    """
    n = mask.shape[0]
    pos = jnp.where(mask, jnp.cumsum(mask.astype(jnp.int32)) - 1, n)

    def scatter(leaf):
        buf = jnp.zeros((n + 1,) + leaf.shape[1:], leaf.dtype)
        return buf.at[pos].set(leaf)[:n]

    out = tree_map(scatter, tree)
    return out, jnp.sum(mask.astype(jnp.int32))
