"""Item serialization for the host data plane.

Equivalent of the reference's Serialization traits
(reference: thrill/data/serialization.hpp:34 — POD memcpy path, strings,
pairs/tuples, vectors; optional cereal adapter). Fixed-size numeric
records take a raw-bytes fast path (the memcpy analog); everything else
goes through pickle (the cereal analog).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import numpy as np

_RAW = 0       # np.ndarray with given dtype/shape
_PICKLE = 1


def serialize_batch(items: List[Any]) -> bytes:
    """Serialize a list of items into one block payload."""
    if items and all(isinstance(it, np.ndarray) for it in items) and \
            len({(it.dtype.str, it.shape) for it in items}) == 1:
        arr = np.stack(items)
        header = pickle.dumps((_RAW, arr.dtype.str, arr.shape))
        return struct.pack("<I", len(header)) + header + \
            np.ascontiguousarray(arr).tobytes()
    header = pickle.dumps((_PICKLE, None, len(items)))
    return struct.pack("<I", len(header)) + header + pickle.dumps(items)


def deserialize_batch(data: bytes) -> List[Any]:
    (hlen,) = struct.unpack_from("<I", data, 0)
    kind, dstr, shape_or_n = pickle.loads(data[4:4 + hlen])
    payload = data[4 + hlen:]
    if kind == _RAW:
        arr = np.frombuffer(payload, dtype=np.dtype(dstr)).reshape(
            shape_or_n)
        return list(arr)
    return pickle.loads(payload)


def serialize_leaves(leaves: List[np.ndarray]) -> bytes:
    """Serialize an ordered list of numpy leaf arrays into one payload
    (length-prefixed :func:`serialize_batch` per leaf, so every leaf
    keeps the RAW fixed-size fast path regardless of dtype/shape
    differences between leaves). The checkpoint layer
    (api/checkpoint.py) stores one such payload per (node, worker)."""
    parts = [struct.pack("<I", len(leaves))]
    for leaf in leaves:
        payload = serialize_batch([np.ascontiguousarray(leaf)])
        parts.append(struct.pack("<Q", len(payload)))
        parts.append(payload)
    return b"".join(parts)


def deserialize_leaves(data: bytes) -> List[np.ndarray]:
    """Inverse of :func:`serialize_leaves`."""
    (n,) = struct.unpack_from("<I", data, 0)
    pos = 4
    leaves: List[np.ndarray] = []
    for _ in range(n):
        (plen,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        batch = deserialize_batch(data[pos:pos + plen])
        pos += plen
        if len(batch) != 1:
            raise ValueError(
                f"corrupt leaf payload: {len(batch)} items in a "
                f"1-item batch")
        leaves.append(np.asarray(batch[0]))
    return leaves


def deserialize_slice(data: bytes, lo: int, hi: int) -> List[Any]:
    """Decode only items [lo, hi) of a batch payload.

    Fixed-size records (the RAW path) decode exactly the requested
    rows by byte arithmetic — the analog of the reference's
    ``is_fixed_size`` scatter fast path (thrill/data/serialization.hpp,
    stream.hpp:77-210: Blocks are re-sliced without deserializing).
    Variable items (pickle) must decode the whole batch first."""
    (hlen,) = struct.unpack_from("<I", data, 0)
    kind, dstr, shape_or_n = pickle.loads(data[4:4 + hlen])
    if kind == _RAW:
        dt = np.dtype(dstr)
        row_shape = tuple(shape_or_n[1:])
        row_bytes = dt.itemsize * int(np.prod(row_shape, dtype=np.int64))
        base = 4 + hlen + lo * row_bytes
        arr = np.frombuffer(data, dtype=dt, count=(hi - lo) *
                            (row_bytes // dt.itemsize), offset=base)
        return list(arr.reshape((hi - lo,) + row_shape))
    return pickle.loads(data[4 + hlen:])[lo:hi]
