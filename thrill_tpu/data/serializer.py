"""Item serialization for the host data plane.

Equivalent of the reference's Serialization traits
(reference: thrill/data/serialization.hpp:34 — POD memcpy path, strings,
pairs/tuples, vectors; optional cereal adapter). Fixed-size numeric
records take a raw-bytes fast path (the memcpy analog); everything else
goes through pickle (the cereal analog).

Container kinds of one block payload::

    [u32 hlen][hlen pickled header][payload]

* ``_RAW``    — header ``(0, dtype_str, shape)``; payload is one
  contiguous ndarray (a stack of same-shape ndarray items).
* ``_PICKLE`` — header ``(1, None, n)``; payload pickles the item list.
* ``_COLS``   — header ``(2, (template, dtype_strs), nrows)``; payload
  is the concatenation of fixed-dtype scalar COLUMNS, one per template
  leaf. The native-records kind (ISSUE 15): items built from python
  scalars and (nested) tuples of them encode as numpy columns with NO
  per-item pickle work, decode by zero-copy ``np.frombuffer`` views,
  and slice by byte arithmetic like ``_RAW``. The schema probe and the
  vectorized encode live in data/records.py; anything it cannot
  represent EXACTLY (mixed types, out-of-int64 ints, trailing-NUL
  strings, ndarray/ragged payloads) falls back to ``_PICKLE``
  byte-compatibly, and ``THRILL_TPU_NATIVE_RECORDS=0`` restores the
  pre-columnar encode bit-identically (decode of all three kinds stays
  on, so stores written by either setting always read back).

The template grammar is tiny: ``"x"`` is one scalar leaf consuming one
column (unboxed exactly like ``data/shards.itemize`` unboxes device
columns — ``ndarray.tolist()`` element types: int64->int, bool->bool,
float64->float, U->str, S->bytes, so item types never depend on which
engine materialized them); ``"s"`` is a str leaf COMPACTED to an S
(1 byte/char) column — ASCII only, chosen at encode time so spilled
strings do not pay UCS-4's 4x on disk, decoded back by one vectorized
``S->U`` cast; ``("T", sub, ...)`` is a tuple of sub-templates; ``("A", dstr, shape)``
is a fixed-shape, fixed-dtype ndarray leaf (ISSUE 17) stored as ONE
column of ``|V{row_bytes}`` rows — the (N, *shape) stack's bytes laid
out row-major, so the byte arithmetic (slices, native gather, run
spills) that works for scalar columns works unchanged, and decode is
one zero-copy dtype view + reshape per column. Ragged or
dtype-deviating batches fall back to pickle per batch (the probe
template pins the exact ``dtype.str`` and shape).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterator, List, Optional, Sequence, Tuple

import numpy as np

_RAW = 0       # np.ndarray with given dtype/shape
_PICKLE = 1
_COLS = 2      # fixed-dtype scalar columns (native records)


def serialize_batch(items: List[Any]) -> bytes:
    """Serialize a list of items into one block payload."""
    if items and all(isinstance(it, np.ndarray) for it in items) and \
            len({(it.dtype.str, it.shape) for it in items}) == 1:
        arr = np.stack(items)
        header = pickle.dumps((_RAW, arr.dtype.str, arr.shape))
        return struct.pack("<I", len(header)) + header + \
            np.ascontiguousarray(arr).tobytes()
    if items:
        # the columnar fast path (knob-gated inside records; returns
        # None for anything it cannot represent exactly)
        from . import records
        enc = records.encode_batch_columns(items)
        if enc is not None:
            return serialize_columns(enc[0], enc[1])
    header = pickle.dumps((_PICKLE, None, len(items)))
    return struct.pack("<I", len(header)) + header + pickle.dumps(items)


# ----------------------------------------------------------------------
# the columnar container kind
# ----------------------------------------------------------------------

def leaf_count(tmpl) -> int:
    """Columns a template consumes (one per scalar or ndarray leaf)."""
    if tmpl in ("x", "s") or tmpl[0] == "A":
        return 1
    return sum(leaf_count(s) for s in tmpl[1:])


def columnar_header(tmpl, dstrs: Sequence[str], nrows: int) -> bytes:
    """Length-prefixed header of a columnar block (the caller appends
    exactly ``nrows`` rows of each column, in order)."""
    header = pickle.dumps((_COLS, (tmpl, tuple(dstrs)), nrows))
    return struct.pack("<I", len(header)) + header


def serialize_columns(tmpl, cols: List[np.ndarray]) -> bytes:
    """Pack template + columns into one block payload (the pure-python
    assembly; the em_sort run spiller writes the same layout through
    the native gather instead, data/records.py)."""
    nrows = len(cols[0]) if cols else 0
    head = columnar_header(tmpl, [c.dtype.str for c in cols], nrows)
    return head + b"".join(
        np.ascontiguousarray(c).tobytes() for c in cols)


def _cols_views(data: bytes, dstrs, nrows: int, base: int, lo: int,
                hi: int, take: Optional[Sequence[int]] = None
                ) -> List[np.ndarray]:
    """Zero-copy views of rows [lo, hi) of each column (or only the
    column indices in ``take``)."""
    out = []
    off = base
    for c, dstr in enumerate(dstrs):
        isz = np.dtype(dstr).itemsize
        if take is None or c in take:
            out.append(np.frombuffer(data, dtype=np.dtype(dstr),
                                     count=hi - lo,
                                     offset=off + lo * isz))
        off += nrows * isz
    return out


def _build_items(tmpl, cols: List[np.ndarray]) -> List[Any]:
    """Rebuild the item list from sliced column views: one ``tolist``
    per column (C-speed unboxing), tuples assembled by ``zip``."""
    it = iter(cols)

    def build(t):
        if t == "x":
            return next(it).tolist()
        if t == "s":   # ASCII-compacted str: one vectorized S->U cast
            col = next(it)
            return col.astype(f"U{col.dtype.itemsize}").tolist()
        if t[0] == "A":
            # ndarray leaf: the V rows view back to the element dtype
            # (one zero-copy reinterpret + reshape for the whole
            # column); like _RAW, items are read-only views into the
            # block's buffer
            _, dstr, shape = t
            col = next(it)
            arr = col.view(np.dtype(dstr)).reshape(
                (len(col),) + tuple(shape))
            return list(arr)
        parts = [build(s) for s in t[1:]]
        return list(zip(*parts))

    return build(tmpl)


def _sub_template(tmpl, project: int):
    """(sub_template, column_indices) of tuple element ``project``."""
    assert tmpl not in ("x", "s") and tmpl[0] == "T" \
        and len(tmpl) > project + 1, (tmpl, project)
    skip = sum(leaf_count(s) for s in tmpl[1:1 + project])
    sub = tmpl[1 + project]
    return sub, range(skip, skip + leaf_count(sub))


def _parse_header(data: bytes):
    (hlen,) = struct.unpack_from("<I", data, 0)
    kind, meta, n = pickle.loads(data[4:4 + hlen])
    return kind, meta, n, 4 + hlen


def deserialize_batch(data: bytes) -> List[Any]:
    kind, meta, shape_or_n, base = _parse_header(data)
    payload = data[base:]
    if kind == _RAW:
        arr = np.frombuffer(payload, dtype=np.dtype(meta)).reshape(
            shape_or_n)
        return list(arr)
    if kind == _COLS:
        tmpl, dstrs = meta
        return _build_items(tmpl, _cols_views(data, dstrs, shape_or_n,
                                              base, 0, shape_or_n))
    return pickle.loads(payload)


def serialize_leaves(leaves: List[np.ndarray]) -> bytes:
    """Serialize an ordered list of numpy leaf arrays into one payload
    (length-prefixed :func:`serialize_batch` per leaf, so every leaf
    keeps the RAW fixed-size fast path regardless of dtype/shape
    differences between leaves). The checkpoint layer
    (api/checkpoint.py) stores one such payload per (node, worker)."""
    parts = [struct.pack("<I", len(leaves))]
    for leaf in leaves:
        payload = serialize_batch([np.ascontiguousarray(leaf)])
        parts.append(struct.pack("<Q", len(payload)))
        parts.append(payload)
    return b"".join(parts)


def deserialize_leaves(data: bytes) -> List[np.ndarray]:
    """Inverse of :func:`serialize_leaves`."""
    (n,) = struct.unpack_from("<I", data, 0)
    pos = 4
    leaves: List[np.ndarray] = []
    for _ in range(n):
        (plen,) = struct.unpack_from("<Q", data, pos)
        pos += 8
        batch = deserialize_batch(data[pos:pos + plen])
        pos += plen
        if len(batch) != 1:
            raise ValueError(
                f"corrupt leaf payload: {len(batch)} items in a "
                f"1-item batch")
        leaves.append(np.asarray(batch[0]))
    return leaves


def deserialize_slice(data: bytes, lo: int, hi: int) -> List[Any]:
    """Decode only items [lo, hi) of a batch payload.

    Fixed-size records (the RAW and COLS paths) decode exactly the
    requested rows by byte arithmetic — the analog of the reference's
    ``is_fixed_size`` scatter fast path (thrill/data/serialization.hpp,
    stream.hpp:77-210: Blocks are re-sliced without deserializing).
    Variable items (pickle) must decode the whole batch first."""
    kind, meta, shape_or_n, base = _parse_header(data)
    if kind == _RAW:
        dt = np.dtype(meta)
        row_shape = tuple(shape_or_n[1:])
        row_bytes = dt.itemsize * int(np.prod(row_shape, dtype=np.int64))
        arr = np.frombuffer(data, dtype=dt, count=(hi - lo) *
                            (row_bytes // dt.itemsize),
                            offset=base + lo * row_bytes)
        return list(arr.reshape((hi - lo,) + row_shape))
    if kind == _COLS:
        tmpl, dstrs = meta
        return _build_items(tmpl, _cols_views(data, dstrs, shape_or_n,
                                              base, lo, hi))
    return pickle.loads(data[base:])[lo:hi]


def deserialize_iter(data: bytes, lo: int, hi: int,
                     project: Optional[int] = None) -> Iterator[Any]:
    """Items [lo, hi) as an iterator whose DECODE is deferred to the
    first pull (nothing happens at generator construction): columnar
    blocks slice their column views zero-copy and ``project`` yields
    only tuple element ``project`` of each item — the OTHER elements'
    columns are never decoded at all (the partitioned merge consumes
    only the item half of its (pos, item) records, so the pos columns
    stay raw bytes). The item OBJECTS of a block still materialize
    together at that first pull (one ``tolist`` per column + zip —
    C-speed, no pickle); per-block memory matches the eager path.
    Non-columnar kinds degrade to the eager decode."""
    if hi <= lo:
        return
    kind, meta, shape_or_n, base = _parse_header(data)
    if kind == _COLS:
        tmpl, dstrs = meta
        take = None
        if project is not None:
            tmpl, take = _sub_template(tmpl, project)
        views = _cols_views(data, dstrs, shape_or_n, base, lo, hi, take)
        yield from _build_items(tmpl, views)
        return
    items = deserialize_slice(data, lo, hi)
    if project is None:
        yield from items
    else:
        for t in items:
            yield t[project]
