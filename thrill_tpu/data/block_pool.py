"""Python interface to the native block store.

Equivalent of the reference's BlockPool/ByteBlock layer
(reference: thrill/data/block_pool.hpp:42 — soft/hard limits, pin/unpin,
LRU eviction to disk): bytes live in the C++ store (native/
blockstore.cpp, built on first use with g++), Python handles only ids.
Falls back to a pure-Python store when no compiler is available — with
the SAME soft-limit spill-to-disk ladder (write-behind evictions via
data/writeback.py, synchronous with THRILL_TPU_WRITEBACK=0; same
pid/store/host file naming so ``purge_stale_spills`` reclaims its
files too), so a compiler-less host degrades instead of growing
unbounded.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Optional

from ..common import faults
from ..common.retry import default_policy

# spill-store I/O: both operations are idempotent (put allocates a
# fresh id; get re-reads immutable bytes), so transient storage faults
# retry under the shared backoff policy before surfacing. Unlike the
# injection-only frame/dispatch sites there is no active() fast-path
# gate here: REAL disk faults on the native spill files are retryable
# too, and the policy cost is noise against per-block I/O.
_F_PUT = faults.declare("data.blockstore.put")
_F_GET = faults.declare("data.blockstore.get")

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _sanitized_host() -> str:
    """This host's tag as it appears in spill file names. ASCII-only
    sanitization matching the C-locale std::isalnum the native writer
    uses — the fallback writer and the purge sweeper must map a
    hostname IDENTICALLY to the native store (and to each other) or
    the host tag never matches and stale spills leak."""
    import socket
    return "".join(c if (c.isascii() and c.isalnum()) else "_"
                   for c in socket.gethostname()) or "unknown"


def _load_native() -> Optional[ctypes.CDLL]:
    """Build-from-source-only loader (hash-named artifact; shared
    lifecycle in common/native_build.py — a stale or foreign binary is
    never loaded, it is rebuilt from the reviewed source instead)."""
    global _LIB, _LIB_FAILED
    with _LIB_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        from ..common.native_build import build_and_load
        lib = build_and_load("blockstore.cpp")
        if lib is None:
            _LIB_FAILED = True
            return None
        lib.bs_create.restype = ctypes.c_void_p
        lib.bs_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_int]
        lib.bs_destroy.argtypes = [ctypes.c_void_p]
        lib.bs_flush.argtypes = [ctypes.c_void_p]
        lib.bs_pending.restype = ctypes.c_int64
        lib.bs_pending.argtypes = [ctypes.c_void_p]
        lib.bs_put.restype = ctypes.c_int64
        # c_void_p (not c_char_p) so numpy buffers pass by POINTER:
        # a spill of an ndarray (native records block, an HBM leaf
        # shard) hands the store its memory without first copying it
        # into a python bytes object on the GIL
        lib.bs_put.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_int64]
        lib.bs_size.restype = ctypes.c_int64
        lib.bs_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bs_get.restype = ctypes.c_int
        lib.bs_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_void_p]
        lib.bs_pin.restype = ctypes.c_int
        lib.bs_pin.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bs_unpin.restype = ctypes.c_int
        lib.bs_unpin.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bs_drop.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bs_resident.restype = ctypes.c_int
        lib.bs_resident.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bs_mem_usage.restype = ctypes.c_int64
        lib.bs_mem_usage.argtypes = [ctypes.c_void_p]
        lib.bs_num_blocks.restype = ctypes.c_int64
        lib.bs_num_blocks.argtypes = [ctypes.c_void_p]
        lib.bs_scan_lines.restype = ctypes.c_int64
        lib.bs_scan_lines.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_int64),
                                      ctypes.c_int64]
        _LIB = lib
        return _LIB


def resident_override() -> Optional[int]:
    """``THRILL_TPU_SPILL_RESIDENT``: override a spill store's RAM
    residency budget outright (bytes, SI/IEC suffixes; floor 64 KiB).
    How the bench em lane and the out-of-core tests pin a genuinely
    disk-resident merge/restore regime regardless of the rig's
    negotiated grant; None = the owner's own sizing policy."""
    env = os.environ.get("THRILL_TPU_SPILL_RESIDENT")
    if not env:
        return None
    from ..common.config import parse_si_iec_units
    try:
        return max(parse_si_iec_units(env), 1 << 16)
    except (ValueError, TypeError):
        return None


def spill_pool(spill_dir: str, mem_limit) -> "BlockPool":
    """The EM operators' shared spill-store sizing policy: keep a
    quarter of the negotiated grant resident before evicting to disk
    (floor 8 MiB; 64 MiB residency when ungranted). One definition so
    Sort/Reduce/GroupBy spill behavior can never silently diverge."""
    soft = resident_override()
    if soft is None:
        soft = max((mem_limit or 256 << 20) // 4, 8 << 20)
    return BlockPool(spill_dir=spill_dir, soft_limit=soft)


class BlockPool:
    """Byte-block store with a soft RAM limit and disk spill.

    ``async_io=True`` (default) spills through the store's writer
    thread — Put/Unpin never block on disk, like the reference's
    foxxll-backed BlockPool; ``flush()`` barriers on in-flight writes.
    """

    def __init__(self, spill_dir: str = "/tmp", soft_limit: int = 0,
                 async_io: bool = True) -> None:
        self._lib = _load_native()
        self.native = self._lib is not None
        # one policy per pool, not per block (env knobs are stable for
        # a pool's lifetime)
        self._policy = default_policy()
        # cumulative payload bytes accepted by put() — the write-behind
        # accounting hook (em_sort measures a spill job's bytes as the
        # delta across its writes; single-writer FIFO makes that exact)
        self.bytes_put = 0
        self._refs: Dict[int, int] = {}   # shared-Block refcounts (>1)
        self._ref_lock = threading.Lock()
        if self.native:
            self._h = self._lib.bs_create(spill_dir.encode(), soft_limit,
                                          1 if async_io else 0)
        else:
            # pure-python fallback: resident dict + spill to disk past
            # the soft limit, the same degradation ladder as the
            # native store (a host without a compiler must not grow
            # unbounded — it gets slower, not bigger). With
            # ``async_io`` (and THRILL_TPU_WRITEBACK on) the spill
            # writes ride a bounded write-behind thread like the
            # native store's writer — Put never blocks on disk; the
            # block stays RAM-resident until its write completes, so a
            # failed flush degrades to over-budget, never data loss.
            # Spill files carry the native pid/store/host naming so
            # purge_stale_spills reclaims them after a kill -9.
            self._blocks: Dict[int, bytes] = {}   # resident (insertion=LRU)
            self._spilled: Dict[int, str] = {}    # block id -> file path
            self._pins: Dict[int, int] = {}
            self._next = 1
            self._soft = soft_limit
            self._mem = 0
            self._spill_dir = spill_dir
            self._host_tag = _sanitized_host()
            self._py_lock = threading.RLock()
            self._async_io = async_io
            self._writer = None                   # lazy AsyncWriter
            self._inflight: Dict[int, int] = {}   # bid -> len(data)

    # -- pure-python spill ladder ---------------------------------------
    def _spill_path(self, block_id: int) -> str:
        return os.path.join(
            self._spill_dir,
            f"ttpu-blk-{os.getpid()}-{hex(id(self))}-{block_id}-"
            f"{self._host_tag}.spill")

    def _maybe_spill_py(self) -> None:
        """Evict coldest unpinned resident blocks to disk until the
        resident bytes fit the soft limit. A failed write keeps the
        block resident (over budget beats data loss), mirroring the
        native store's failed-spill handling. With write-behind armed
        the evictions are POSTED to the bounded writer thread and the
        caller returns immediately; the block leaves RAM only when its
        bytes are durably on disk."""
        if self._soft <= 0 or self._mem <= self._soft:
            return
        if self._async_io:
            from .writeback import writeback_enabled
            if writeback_enabled():
                return self._spill_async_py()
        # synchronous path: the same write-then-locked-move job the
        # writer thread runs (readahead threads may hold _py_lock in
        # get()/resident() concurrently even in sync-writeback mode,
        # so the mutations must take the lock here too)
        with self._py_lock:
            victims = [(bid, self._blocks[bid])
                       for bid in self._blocks
                       if self._pins.get(bid, 0) <= 0]
        for bid, data in victims:
            with self._py_lock:
                if self._mem <= self._soft:
                    break
                if bid not in self._blocks:
                    continue
            self._spill_job(bid, data)

    # -- write-behind spill (fallback store) ----------------------------
    def _get_writer(self):
        if self._writer is None:
            from .writeback import AsyncWriter
            # degrade semantics, not poison: a failed eviction write
            # keeps the block resident — over budget beats data loss,
            # exactly the synchronous path's contract
            self._writer = AsyncWriter("data.blockpool.spill",
                                       poison=False,
                                       on_error=self._spill_failed)
        return self._writer

    def _spill_failed(self, exc: BaseException, bid) -> None:
        with self._py_lock:
            self._inflight.pop(bid, None)

    def _spill_async_py(self) -> None:
        """Post enough unpinned cold blocks to the write-behind queue
        that the PROJECTED residency (current minus in-flight) fits
        the soft limit; each block leaves ``_blocks`` only when its
        file is fully written."""
        writer = self._get_writer()
        with self._py_lock:
            projected = self._mem - sum(self._inflight.values())
            victims = []
            for bid in list(self._blocks.keys()):
                if projected <= self._soft:
                    break
                if self._pins.get(bid, 0) > 0 or bid in self._inflight:
                    continue
                victims.append((bid, self._blocks[bid]))
                self._inflight[bid] = len(self._blocks[bid])
                projected -= len(self._blocks[bid])
        for bid, data in victims:
            writer.submit(
                lambda bid=bid, data=data: self._spill_job(bid, data),
                tag=bid)

    def _spill_job(self, bid: int, data: bytes) -> int:
        """One write-behind eviction (runs on the writer thread)."""
        path = self._spill_path(bid)
        try:
            with open(path, "wb") as f:
                f.write(data)
        except OSError as e:
            try:
                os.unlink(path)
            except OSError:
                pass
            faults.note("recovery", what="blockpool.spill_skipped",
                        block=bid, error=repr(e)[:200])
            with self._py_lock:
                self._inflight.pop(bid, None)
            return 0
        with self._py_lock:
            self._inflight.pop(bid, None)
            if bid not in self._blocks or self._pins.get(bid, 0) > 0:
                # dropped or pinned while the write was in flight: the
                # RAM copy stays authoritative; discard the file
                try:
                    os.unlink(path)
                except OSError:
                    pass
                return 0
            self._spilled[bid] = path
            del self._blocks[bid]
            self._mem -= len(data)
        return len(data)

    def put(self, data) -> int:
        """Store one immutable byte block; returns its id. ``data`` is
        ``bytes`` or a C-contiguous ``np.ndarray`` — arrays reach the
        native store as a raw pointer (its Put copies internally, GIL
        released for the whole ctypes call), so the encode side never
        materializes an interpreter-side bytes copy."""
        return self._policy.run(lambda: self._put_once(data),
                                what="blockstore.put")

    def _put_once(self, data) -> int:
        import numpy as np
        is_arr = isinstance(data, np.ndarray)
        if is_arr and not data.flags.c_contiguous:
            data = np.ascontiguousarray(data)
        n = data.nbytes if is_arr else len(data)
        faults.check(_F_PUT, nbytes=n)
        self.bytes_put += n
        if self.native:
            ptr = data.ctypes.data_as(ctypes.c_void_p) if is_arr \
                else data
            return self._lib.bs_put(self._h, ptr, n)
        with self._py_lock:
            bid = self._next
            self._next += 1
            self._blocks[bid] = data.tobytes() if is_arr \
                else bytes(data)
            self._mem += n
        self._maybe_spill_py()
        return bid

    def get(self, block_id: int) -> bytes:
        return self._policy.run(lambda: self._get_once(block_id),
                                what="blockstore.get")

    def _get_once(self, block_id: int) -> bytes:
        faults.check(_F_GET, block=block_id)
        if self.native:
            size = self._lib.bs_size(self._h, block_id)
            if size < 0:
                raise KeyError(f"unknown block {block_id}")
            buf = ctypes.create_string_buffer(max(size, 1))
            rc = self._lib.bs_get(self._h, block_id, buf)
            if rc != 0:
                raise IOError(f"block {block_id} fetch failed rc={rc}")
            return buf.raw[:size]
        with self._py_lock:
            if block_id in self._blocks:
                return self._blocks[block_id]
            path = self._spilled.get(block_id)
        if path is None:
            raise KeyError(f"unknown block {block_id}")
        with open(path, "rb") as f:
            return f.read()

    def resident(self, block_id: int) -> bool:
        """Is the block servable from RAM (no disk read)? Drives the
        surgical merge readahead: a background fetch only pays for
        itself when the demand read would actually touch disk, so
        RAM-resident blocks are read inline. Unknown ids report True —
        the demand read is where a missing block must surface."""
        if self.native:
            return self._lib.bs_resident(self._h, block_id) != 0
        with self._py_lock:
            # unknown ids (not spilled either) report True, matching
            # the native -1 mapping: the DEMAND read surfaces them
            return block_id in self._blocks \
                or block_id not in self._spilled

    def pin(self, block_id: int) -> None:
        if self.native:
            self._lib.bs_pin(self._h, block_id)
        else:
            with self._py_lock:
                self._pins[block_id] = self._pins.get(block_id, 0) + 1

    def unpin(self, block_id: int) -> None:
        if self.native:
            self._lib.bs_unpin(self._h, block_id)
        else:
            with self._py_lock:
                n = self._pins.get(block_id, 0) - 1
                if n > 0:
                    self._pins[block_id] = n
                else:
                    self._pins.pop(block_id, None)

    def drop(self, block_id: int) -> None:
        if self.native:
            self._lib.bs_drop(self._h, block_id)
        else:
            with self._py_lock:
                data = self._blocks.pop(block_id, None)
                if data is not None:
                    self._mem -= len(data)
                self._pins.pop(block_id, None)
                self._inflight.pop(block_id, None)
                path = self._spilled.pop(block_id, None)
            if path is not None:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- sharing (reference: ByteBlock reference counting,
    # thrill/data/byte_block.hpp:51 — Blocks are slices of shared
    # ref-counted byte buffers; the last release frees the bytes) ------
    def addref(self, block_id: int) -> None:
        """Another Block now shares this byte block."""
        with self._ref_lock:
            self._refs[block_id] = self._refs.get(block_id, 1) + 1

    def release(self, block_id: int) -> None:
        """Drop one shared reference; frees the bytes at zero."""
        with self._ref_lock:
            n = self._refs.get(block_id, 1) - 1
            if n > 0:
                self._refs[block_id] = n
                return
            self._refs.pop(block_id, None)
        self.drop(block_id)

    def flush(self) -> None:
        """Wait for every queued/in-flight spill write to complete."""
        if self.native:
            self._lib.bs_flush(self._h)
        elif self._writer is not None:
            self._writer.flush()

    @property
    def pending_spills(self) -> int:
        if self.native:
            return self._lib.bs_pending(self._h)
        with self._py_lock:
            return len(self._inflight)

    @property
    def mem_usage(self) -> int:
        if self.native:
            return self._lib.bs_mem_usage(self._h)
        with self._py_lock:
            return self._mem

    @property
    def num_blocks(self) -> int:
        if self.native:
            return self._lib.bs_num_blocks(self._h)
        with self._py_lock:
            return len(self._blocks) + len(self._spilled)

    def close(self) -> None:
        if self.native:
            if self._h:
                self._lib.bs_destroy(self._h)
                self._h = None
        else:
            if self._writer is not None:
                # abandon the eviction backlog (those blocks are still
                # RAM-resident — nothing is lost) and join the thread
                # so no late job races the file sweep below
                self._writer.close(drain=False)
                self._writer = None
            with self._py_lock:
                for path in self._spilled.values():
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                self._spilled.clear()
                self._blocks.clear()
                self._inflight.clear()
                self._mem = 0

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


def purge_stale_spills(spill_dir: str) -> int:
    """Remove spill files abandoned by DEAD processes.

    The store (native and the pure-python fallback alike) names its
    files ``ttpu-blk-<pid>-<store>-<id>-
    <host>.spill`` and unlinks them in its destructor — but a kill
    -9'd or aborted worker never runs destructors, leaking its spills
    into the shared spill dir. Context.close() calls this after an
    abort (and supervised relaunches inherit a clean dir): files whose
    owning pid no longer exists ON THIS HOST are reclaimed; files
    written by OTHER hosts (a spill dir on shared storage) are never
    judged — a local pid probe says nothing about a remote process.
    Returns the number removed."""
    import glob as _glob
    my_host = _sanitized_host()
    removed = 0
    for path in _glob.glob(os.path.join(spill_dir, "ttpu-blk-*.spill")):
        parts = os.path.basename(path)[:-len(".spill")].split("-")
        try:
            pid = int(parts[2])
            host = "-".join(parts[5:])
        except (IndexError, ValueError):
            continue                   # legacy/foreign name: leave it
        if host != my_host:
            continue                   # another host's file: not ours
        if pid == os.getpid():
            continue
        try:
            os.kill(pid, 0)
            continue                   # owner is alive
        except ProcessLookupError:
            pass                       # owner is gone: reclaim
        except PermissionError:
            continue                   # alive, other user
        except OSError:
            continue
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    if removed:
        faults.note("recovery", what="spill.purge_stale",
                    removed=removed, dir=spill_dir)
    return removed


def scan_line_offsets(data: bytes, max_lines: int = 1 << 22):
    """Offsets of line starts in data (C++ memchr scan when available)."""
    lib = _load_native()
    if lib is None:
        out = [0] if data else []
        pos = 0
        while True:
            nl = data.find(b"\n", pos)
            if nl < 0 or nl + 1 >= len(data):
                break
            out.append(nl + 1)
            pos = nl + 1
        return out
    arr = (ctypes.c_int64 * max_lines)()
    n = lib.bs_scan_lines(data, len(data), arr, max_lines)
    return list(arr[:n])
