"""Block: an item-range view of a shared, pooled byte block.

Equivalent of the reference's Block over ByteBlock
(reference: thrill/data/block.hpp:52 — a [begin, end) slice of a
ref-counted byte buffer with item count and first-item offset, enabling
zero-copy slicing and item-granular scatter; byte_block.hpp:51 for the
shared buffer). Here the bytes live in the BlockPool (native C++ store
with LRU disk spill) as one serialized batch; a Block names a slice
[lo, hi) of that batch's items. Slicing adjusts the range and bumps the
pool refcount — bytes are shared, never copied — and fixed-size record
batches decode ONLY the sliced rows (serializer.deserialize_slice).
"""

from __future__ import annotations

from typing import Any, List

from .serializer import deserialize_iter, deserialize_slice


class Block:
    __slots__ = ("pool", "bid", "lo", "hi")

    def __init__(self, pool, bid: int, lo: int, hi: int) -> None:
        self.pool = pool
        self.bid = bid
        self.lo = lo
        self.hi = hi

    @property
    def num_items(self) -> int:
        return self.hi - self.lo

    def items(self) -> List[Any]:
        """Decode this Block's items (only the sliced rows for
        fixed-size batches)."""
        if self.hi == self.lo:
            return []
        return deserialize_slice(self.pool.get(self.bid), self.lo,
                                 self.hi)

    def iter_items(self, project=None):
        """Items as an iterator with decode deferred to the first pull
        (serializer.deserialize_iter): columnar batches (native
        records, ``_COLS``) slice zero-copy column views, and
        ``project`` yields only tuple element ``project`` — the other
        elements' columns are never decoded (the partitioned merge
        reads just the item half of its (pos, item) records, skipping
        the pos columns entirely)."""
        if self.hi == self.lo:
            return iter(())
        return deserialize_iter(self.pool.get(self.bid), self.lo,
                                self.hi, project)

    def item_at(self, i: int) -> Any:
        return deserialize_slice(self.pool.get(self.bid),
                                 self.lo + i, self.lo + i + 1)[0]

    def slice(self, lo: int, hi: int) -> "Block":
        """Zero-copy sub-range [lo, hi) relative to this Block; shares
        the bytes (pool refcount, reference: PinnedBlock slicing)."""
        if not 0 <= lo <= hi <= self.num_items:
            raise IndexError((lo, hi, self.num_items))
        self.pool.addref(self.bid)
        return Block(self.pool, self.bid, self.lo + lo, self.lo + hi)

    def share(self) -> "Block":
        return self.slice(0, self.num_items)

    def release(self) -> None:
        """Give up this view; the pool frees the bytes with the last
        reference."""
        if self.bid >= 0:
            self.pool.release(self.bid)
            self.bid = -1
