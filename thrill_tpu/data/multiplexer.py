"""Cross-process host-storage data plane: the Multiplexer equivalent.

The reference moves serialized Blocks between hosts for every stream
through its Multiplexer (reference: thrill/data/multiplexer.cpp:282-440
— per-destination BlockWriters, framed block dispatch over the async
group, receive-side BlockQueues with rank-ordered CatStream delivery).

The TPU-native repo keeps the BULK data plane on XLA collectives
(data/exchange.py); this module is its host-storage sibling for items
that cannot live in device columns (strings, variable-shape pytrees).
Invariant in multi-controller runs: a ``HostShards`` holds items ONLY
for the workers whose device this process owns — every other worker's
list is empty. The helpers here move items between processes over the
authenticated TCP control plane (``mex.host_net``) and restore that
invariant:

* ``host_exchange``   — per-item destination shuffle (CatStream order:
  each receiving worker sees batches in source-worker rank order).
* ``ensure_replicated`` — every process gets every worker's items (the
  demotion for host ops that genuinely need a global view).
* ``localize``        — drop non-local lists (after a replicated
  computation produced full lists identically on every process).
* ``host_to_device``  — HostShards -> DeviceShards with globally agreed
  capacity/counts/schema.

Single-controller runs (every worker local) take the direct in-process
paths — identical behavior to the pre-multiplexer code.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Any, Callable, List, Optional

import numpy as np

from ..common import faults
from ..common import trace as _trace
from ..common.config import _env_flag, overlap_enabled, round_up_pow2
from ..common.retry import default_policy
from ..net.group import poison_on_error
from .shards import DeviceShards, HostShards

_MISSING = "__thrill_tpu_missing__"

# frame-level injection: fires before the frame hits the transport, so
# a retry is safe (nothing was sent); real mid-stream transport errors
# are permanent here (the stream position is unrecoverable)
_F_SEND = faults.declare("net.multiplexer.frame_send",
                         exc=faults.InjectedConnectionError)
_F_RECV = faults.declare("net.multiplexer.frame_recv",
                         exc=faults.InjectedConnectionError)
# fires in the BACKGROUND sender thread before a frame is posted to
# the transport (nothing sent yet -> retry-safe, same contract as
# frame_send); the error is re-raised on the exchange's main thread
_F_ASYNC = faults.declare("net.multiplexer.async_send",
                          exc=faults.InjectedConnectionError)
_FRAME_RETRY = dict(transient=(faults.InjectedConnectionError,))


def _async_send_enabled() -> bool:
    """MixStream-analog sender: frames ride a background thread with a
    bounded queue so the send side overlaps the receive side instead
    of strictly alternating per peer. THRILL_TPU_ASYNC_SEND=0 (or the
    THRILL_TPU_OVERLAP=0 master switch) restores the serial sender."""
    return overlap_enabled() and _env_flag("THRILL_TPU_ASYNC_SEND",
                                           True)


def _mix_delivery(rank_order: bool) -> bool:
    """Arrival-order (MixStream) delivery: only for call sites that
    DECLARED tolerance (``rank_order=False`` — hash-partition targets)
    and only when explicitly opted in: the default stays CatStream
    source-rank order everywhere so results are bit-identical to the
    serial plane (float folds are order-sensitive)."""
    return (not rank_order) and _env_flag("THRILL_TPU_HOST_MIX", False)


def _send_queue_depth() -> int:
    try:
        return max(1, int(os.environ.get("THRILL_TPU_SEND_QUEUE",
                                         "4") or 4))
    except ValueError:
        return 4


def _frame_bytes(msg: Any) -> int:
    """Serialized size of one frame — what the TCP plane would put on
    the wire (net/wire.py is the transport's framing codec, column
    compression included). FALLBACK only: the TCP transport reports
    its serialized byte count from ``send`` itself (counted once,
    where the frame is encoded); this measurement serialization is
    paid only on transports that pass objects by reference (the mock
    test plane) and report None."""
    try:
        from ..net import wire
        return len(wire.dumps(msg, allow_pickle=True))
    except Exception:
        return 0


def _send_frame(group, peer: int, msg: Any, what: str) -> int:
    """Send one frame; returns its wire byte count (transport-reported
    where the transport serializes, else measured here once)."""
    if not faults.REGISTRY.active():     # disarmed hot path: direct
        nb = group.send_to(peer, msg)
    else:
        def op():
            faults.check(_F_SEND, peer=peer, what=what)
            return group.send_to(peer, msg)
        nb = default_policy(**_FRAME_RETRY).run(op, what=f"{what}:send")
    return nb if nb is not None else _frame_bytes(msg)


def _recv_frame(group, peer: int, what: str) -> Any:
    if not faults.REGISTRY.active():
        return group.recv_from(peer)

    def op():
        faults.check(_F_RECV, peer=peer, what=what)
        return group.recv_from(peer)
    return default_policy(**_FRAME_RETRY).run(op, what=f"{what}:recv")


def _recv_frame_any(group, peers, what: str):
    """Any-source receive: drain whichever peer's frame lands first
    (ROADMAP exchange item (d)); returns (peer, msg). The injection
    site fires BEFORE the receive (nothing consumed), so a transient
    retry is safe exactly like the per-peer site."""
    if not faults.REGISTRY.active():
        return group.recv_any(peers)

    def op():
        faults.check(_F_RECV, peer=-1, what=what)
        return group.recv_any(peers)
    return default_policy(**_FRAME_RETRY).run(op, what=f"{what}:recv")


def multiprocess(mex) -> bool:
    """Is the host plane split across controllers?

    Loud by design: a multi-process mesh WITHOUT a working host control
    plane cannot run host-storage pipelines correctly (each process
    holds only its workers' items and has no way to ship the rest), so
    that configuration raises here rather than silently computing
    per-process answers."""
    if getattr(mex, "num_processes", 1) <= 1:
        return False
    _net(mex)
    return True


def _net(mex):
    net = getattr(mex, "host_net", None)
    if net is None or net.num_workers != mex.num_processes:
        raise RuntimeError(
            "multi-process host-storage pipeline needs the host control "
            "plane: set THRILL_TPU_HOSTLIST/RANK/SECRET so every "
            "controller joins the TCP group")
    return net


def local_worker_set(mex) -> set:
    """Workers this process materializes host storage for. All of them
    in a single-controller run; in a multi-controller run only the
    local block (and the control plane must exist — see multiprocess)."""
    if multiprocess(mex):
        return set(mex.local_workers)
    return set(range(mex.num_workers))


def host_exchange(mex, shards: HostShards, dest_fn: Callable[[Any], int],
                  reason: str = "host-exchange",
                  rank_order: bool = True) -> HostShards:
    """Move every item to the worker ``dest_fn(item) % W`` computes.

    Single-controller: in-process bucketing (the old fast path).
    Multi-controller: this process buckets its local workers' items,
    ships each remote process one framed message of
    ``{dest_worker: {src_worker: [items...]}}`` over the TCP group
    (large frames ride the async dispatcher). By default frames are
    POSTED to a background sender thread with a bounded queue — the
    MixStream-analog data plane (reference: the multiplexer's async
    dispatch thread, thrill/data/multiplexer.cpp:282) — so sends
    overlap receives instead of alternating serially per peer
    (``THRILL_TPU_ASYNC_SEND=0`` / ``THRILL_TPU_OVERLAP=0`` restore
    the serial sender).

    Delivery order: each receiving worker sees batches in source-worker
    rank order — the CatStream guarantee (reference:
    thrill/data/cat_stream.hpp:155) — regardless of the sender mode.
    Call sites whose consumer does not need rank order (hash-partition
    targets: ReduceByKey, GroupByKey, hash InnerJoin) declare it with
    ``rank_order=False``; with ``THRILL_TPU_HOST_MIX=1`` those merge
    frames in RECEIVE-SEQUENCE order instead (per-source batches stay
    internally ordered, batch interleaving does not). Scope honestly
    stated: receives still drain on the fixed per-peer schedule, so
    this relaxes the ordering CONTRACT (batch interleaving may differ
    from source-rank order) — the wall-clock overlap comes from the
    async sender; true consume-whichever-peer-arrives-first needs an
    any-source receive in the transports (ROADMAP, exchange item).
    Sort/Merge/index-partition sites never pass ``rank_order=False``.
    """
    W = shards.num_workers
    if not multiprocess(mex):
        buckets: List[List[Any]] = [[] for _ in range(W)]
        for items in shards.lists:
            for it in items:
                buckets[dest_fn(it) % W].append(it)
        return HostShards(W, buckets)

    net = _net(mex)
    wp = mex.worker_process
    me = mex.process_index
    P = mex.num_processes
    # bucket local items: {dest_worker: {src_worker: [items]}} per
    # destination process (iterating local workers in rank order keeps
    # each batch internally ordered)
    outgoing: List[dict] = [dict() for _ in range(P)]
    for sw in mex.local_workers:
        for it in shards.lists[sw]:
            dw = int(dest_fn(it)) % W
            msg = outgoing[int(wp[dw])]
            msg.setdefault(dw, {}).setdefault(sw, []).append(it)

    received = [outgoing[me]]
    sent_items = 0
    wire_bytes = 0
    group = net.group
    use_async = _async_send_enabled() and P > 1
    mix = _mix_delivery(rank_order)
    from ..net import wire as _wire
    csnap = _wire.compress_stats()
    # group._at names the phase for the watchdog AND routes the
    # per-peer recv waits to the doctor's exchange lane (the site
    # prefix "host_exchange" classifies them, common/doctor.py) — the
    # host-plane exchange barrier's arrival deltas
    with _trace.span_of(getattr(mex, "tracer", None), "host",
                        "host_exchange", reason=reason,
                        mode="async" if use_async else "serial"), \
            poison_on_error(group, "host_exchange"), \
            group._at("host_exchange"):
        if use_async:
            sent_items, wire_bytes = _exchange_frames_async(
                mex, group, outgoing, received, me, P, mix)
        else:
            for r in range(1, P):
                to, frm = (me + r) % P, (me - r) % P
                sent_items += sum(len(b)
                                  for dws in outgoing[to].values()
                                  for b in dws.values())
                # byte accounting rides the transport's own send-path
                # serialization (ROADMAP exchange item (e): counted
                # once, where the frame is encoded)
                wire_bytes += _send_frame(group, to, outgoing[to],
                                          "host_exchange")
                received.append(_recv_frame(group, frm,
                                            "host_exchange"))
    # column-codec savings attributed to this exchange window: raw
    # bytes the compressed columns held minus what actually shipped.
    # The counters are process-global, so when several simulated
    # controllers share one process (the mock test plane) concurrent
    # windows can cross-attribute each other's savings — a stats-only
    # imprecision; real deployments run one controller per process
    _, raw0, out0 = csnap
    _, raw1, out1 = _wire.compress_stats()
    saved = max((raw1 - raw0) - (out1 - out0), 0)

    lists: List[List[Any]] = [[] for _ in range(W)]
    for w in mex.local_workers:
        if mix:
            # MixStream: frames in arrival order, each frame's batches
            # in source order (deterministic WITHIN a frame only)
            for msg in received:
                for sw in sorted(msg.get(w, {})):
                    lists[w].extend(msg[w][sw])
        else:
            per_src: dict = {}
            for msg in received:
                per_src.update(msg.get(w, {}))
            for sw in sorted(per_src):
                lists[w].extend(per_src[sw])

    mex.stats_exchanges += 1
    mex.stats_items_moved += sent_items
    mex.stats_bytes_wire_host = getattr(mex, "stats_bytes_wire_host",
                                        0) + wire_bytes
    mex.stats_bytes_wire_host_saved = getattr(
        mex, "stats_bytes_wire_host_saved", 0) + saved
    log = getattr(mex, "logger", None)
    if log is not None and log.enabled:
        log.line(event="host_exchange", reason=reason,
                 items_sent=sent_items, processes=P,
                 bytes=wire_bytes, bytes_saved=saved,
                 mode="mix" if mix else "cat",
                 async_send=use_async)
    return HostShards(W, lists)


def _exchange_frames_async(mex, group, outgoing: List[dict],
                           received: List[dict], me: int, P: int,
                           mix: bool = False):
    """Ship the P-1 outgoing frames from a background sender thread
    (bounded queue) while the main thread drains the P-1 receives.

    A sender-thread failure is re-raised here on the main thread —
    inside the caller's ``poison_on_error`` scope, so the peers still
    convert to fast attributable aborts. The queue bound applies
    backpressure instead of buffering every frame at once; posting
    never deadlocks on a dead sender (the post loop watches the error
    slot).

    With ``mix`` (a rank-order-tolerant site under THRILL_TPU_HOST_MIX)
    and a transport that can probe readiness, receives drain ANY-SOURCE
    — whichever peer's frame lands first is consumed first (ROADMAP
    exchange item (d); the true MixStream receive discipline,
    reference: mix_stream.hpp:126). CatStream sites keep the fixed
    per-peer schedule: their merge is per-source anyway, and identical
    scheduling keeps the serial and async planes easiest to compare."""
    q: "queue.Queue" = queue.Queue(maxsize=_send_queue_depth())
    err: List[BaseException] = []
    wire_holder = [0]
    # explicit trace propagation across the thread boundary: the
    # sender thread's per-frame spans parent under the exchange span
    # opened on THIS thread (a thread-local stack cannot cross)
    tr = getattr(mex, "tracer", None)
    tr_on = tr is not None and tr.enabled
    parent_id = tr.current_id() if tr_on else None

    def _sender():
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                peer, msg = item
                if faults.REGISTRY.active():
                    def op(peer=peer):
                        faults.check(_F_ASYNC, peer=peer)
                    default_policy(**_FRAME_RETRY).run(
                        op, what="host_exchange:async_send")
                # byte accounting rides the sender thread (and, on
                # serializing transports, the transport's own encode),
                # off the send critical path
                if tr_on:
                    with tr.span("host", "async_send",
                                 parent=parent_id, peer=peer):
                        wire_holder[0] += _send_frame(
                            group, peer, msg, "host_exchange")
                else:
                    wire_holder[0] += _send_frame(group, peer, msg,
                                                  "host_exchange")
        except BaseException as e:  # surfaced on the main thread
            err.append(e)
            # the main thread may be BLOCKED in a peer recv that can
            # now never complete (our frame will not arrive, and with
            # the watchdog off a recv has no deadline) — and the PEER
            # may be symmetrically blocked on us. Poison the scope:
            # peers abort fast with the root cause, and their relay
            # frees OUR blocked recv too, instead of a mutual hang.
            try:
                group.poison_peers(e)
            except Exception:
                pass

    t = threading.Thread(target=_sender, daemon=True,
                         name="thrill-tpu-mux-send")
    t.start()
    sent_items = 0
    posted_sentinel = False
    try:
        for r in range(1, P):
            to = (me + r) % P
            sent_items += sum(len(b) for dws in outgoing[to].values()
                              for b in dws.values())
            while True:
                if err:
                    raise err[0]
                try:
                    q.put((to, outgoing[to]), timeout=0.1)
                    break
                except queue.Full:
                    continue
        while True:
            # sentinel rides the same err-watching bounded post as the
            # frames: a sender that died with the queue FULL must not
            # park this thread in a blocking put forever
            if err:
                raise err[0]
            try:
                q.put(None, timeout=0.1)
                break
            except queue.Full:
                continue
        posted_sentinel = True
        if mix and getattr(group, "supports_recv_any", False):
            pending = [(me - r) % P for r in range(1, P)]
            while pending:
                frm, msg = _recv_frame_any(group, pending,
                                           "host_exchange")
                pending.remove(frm)
                received.append(msg)
        else:
            for r in range(1, P):
                frm = (me - r) % P
                received.append(_recv_frame(group, frm,
                                            "host_exchange"))
    finally:
        if err or not posted_sentinel:
            # STOP the sender cleanly on any failure path — the
            # sender's own error, or a receive-side abort before the
            # sentinel was posted (without this, a receive failure
            # stranded the sender blocked on q.get() forever: a thread
            # leaked per aborted exchange). Frames still queued are
            # moot; drain them so the sentinel fits the bounded queue.
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            try:
                q.put_nowait(None)
            except queue.Full:
                pass            # sender mid-get will drain to it
    # sender drain deadline: the collective-watchdog knob
    # (THRILL_TPU_HANG_TIMEOUT_S) — the same deadline every blocking
    # collective honors. Watchdog off (None) = wait for the send like
    # the serial plane would; a legitimately slow large frame is not a
    # fault.
    from ..net.group import hang_timeout_s
    t.join(timeout=hang_timeout_s())
    if err:
        raise err[0]
    if t.is_alive():
        # our receives never depend on our OWN sends, so the recv loop
        # can complete while a send is still wedged — returning success
        # would strand the peer waiting for this frame with nothing
        # attributing the cause. Raise inside the caller's
        # poison_on_error scope instead.
        raise RuntimeError(
            "host_exchange async sender exceeded the hang deadline "
            "with a frame still in flight (wedged send to a peer); "
            "aborting the exchange")
    return sent_items, wire_holder[0]


def ensure_replicated(mex, shards: HostShards,
                      reason: str = "host-global") -> HostShards:
    """Every process gets every worker's items (identical full lists).

    The demotion for host operators that need a global item view (EM
    sort, zip alignment, generic prefix sums...). Idempotent: each
    worker's list is taken from its owning process only.
    """
    if not multiprocess(mex):
        return shards
    net = _net(mex)
    W = shards.num_workers
    local = {w: shards.lists[w] for w in mex.local_workers
             if shards.lists[w]}
    with poison_on_error(net.group, "host_replicate"):
        gathered = net.all_gather(local)
    lists: List[List[Any]] = [[] for _ in range(W)]
    for msg in gathered:
        for w, items in msg.items():
            lists[int(w)] = list(items)
    log = getattr(mex, "logger", None)
    if log is not None and log.enabled:
        log.line(event="host_replicate", reason=reason,
                 items=sum(len(l) for l in lists))
    return HostShards(W, lists)


def localize(mex, shards: HostShards) -> HostShards:
    """Restore the local-only invariant after a replicated computation
    produced identical full lists on every process."""
    if not multiprocess(mex):
        return shards
    local = set(mex.local_workers)
    return HostShards(shards.num_workers,
                      [shards.lists[w] if w in local else []
                       for w in range(shards.num_workers)])


def global_counts(mex, shards: HostShards) -> np.ndarray:
    """Per-worker item counts agreed across processes."""
    if not multiprocess(mex):
        return shards.counts
    net = _net(mex)
    counts = np.zeros(shards.num_workers, dtype=np.int64)
    local = {w: len(shards.lists[w]) for w in mex.local_workers}
    with poison_on_error(net.group, "global_counts"):
        gathered = net.all_gather(local)
    for msg in gathered:
        for w, n in msg.items():
            counts[int(w)] = int(n)
    return counts


def global_total(mex, shards: HostShards) -> int:
    if not multiprocess(mex):
        return shards.total
    return int(_net(mex).all_reduce(
        sum(len(shards.lists[w]) for w in mex.local_workers)))


def all_items(mex, shards: HostShards) -> List[Any]:
    """Every item in worker-rank order, on every process."""
    return [it for l in ensure_replicated(mex, shards, "all-items").lists
            for it in l]


def net_fold(mex, local: Any, op: Callable[[Any, Any], Any],
             empty: bool = False) -> Any:
    """Fold per-process partial results over the control plane.

    ``local`` is this process's partial (ignored when ``empty``);
    returns the rank-ordered fold of all non-empty partials, or raises
    if every process was empty."""
    if not multiprocess(mex):
        if empty:
            raise ValueError("fold over an empty DIA")
        return local
    net = _net(mex)
    with poison_on_error(net.group, "net_fold"):
        vals = net.all_gather(_MISSING if empty else local)
    vals = [v for v in vals if not (isinstance(v, str) and v == _MISSING)]
    if not vals:
        raise ValueError("fold over an empty DIA")
    acc = vals[0]
    for v in vals[1:]:
        acc = op(acc, v)
    return acc


def host_to_device(mex, shards: HostShards) -> DeviceShards:
    """HostShards -> DeviceShards in a multi-controller run.

    Three things must be agreed across processes before the device_put:
    the padded capacity (shapes must match), the global per-worker
    counts (each process only knows its own), and the item schema (a
    process whose workers are all empty must still build correctly
    shaped zero blocks)."""
    counts = global_counts(mex, shards)
    cap = max(1, round_up_pow2(int(counts.max()) if len(counts) else 1))
    net = _net(mex)
    sample = next((items[0] for w in mex.local_workers
                   for items in [shards.lists[w]] if items), None)
    samples = net.all_gather(_MISSING if sample is None else sample)
    sample = next((s for s in samples
                   if not (isinstance(s, str) and s == _MISSING)), None)
    if sample is None:
        raise ValueError("cannot infer schema of an entirely empty DIA")
    import jax

    from .shards import columnarize
    treedef = jax.tree.structure(sample)
    local = set(mex.local_workers)
    empty = jax.tree.map(lambda a: np.asarray([a])[:0], sample)
    per_worker = []
    for w in range(shards.num_workers):
        items = shards.lists[w] if w in local else []
        per_worker.append(columnarize(items, treedef) if items else empty)
    return DeviceShards.from_worker_arrays(mex, per_worker, cap=cap,
                                           counts=counts)
