"""Cross-process host-storage data plane: the Multiplexer equivalent.

The reference moves serialized Blocks between hosts for every stream
through its Multiplexer (reference: thrill/data/multiplexer.cpp:282-440
— per-destination BlockWriters, framed block dispatch over the async
group, receive-side BlockQueues with rank-ordered CatStream delivery).

The TPU-native repo keeps the BULK data plane on XLA collectives
(data/exchange.py); this module is its host-storage sibling for items
that cannot live in device columns (strings, variable-shape pytrees).
Invariant in multi-controller runs: a ``HostShards`` holds items ONLY
for the workers whose device this process owns — every other worker's
list is empty. The helpers here move items between processes over the
authenticated TCP control plane (``mex.host_net``) and restore that
invariant:

* ``host_exchange``   — per-item destination shuffle (CatStream order:
  each receiving worker sees batches in source-worker rank order).
* ``ensure_replicated`` — every process gets every worker's items (the
  demotion for host ops that genuinely need a global view).
* ``localize``        — drop non-local lists (after a replicated
  computation produced full lists identically on every process).
* ``host_to_device``  — HostShards -> DeviceShards with globally agreed
  capacity/counts/schema.

Single-controller runs (every worker local) take the direct in-process
paths — identical behavior to the pre-multiplexer code.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from ..common import faults
from ..common.config import round_up_pow2
from ..common.retry import default_policy
from ..net.group import poison_on_error
from .shards import DeviceShards, HostShards

_MISSING = "__thrill_tpu_missing__"

# frame-level injection: fires before the frame hits the transport, so
# a retry is safe (nothing was sent); real mid-stream transport errors
# are permanent here (the stream position is unrecoverable)
_F_SEND = faults.declare("net.multiplexer.frame_send",
                         exc=faults.InjectedConnectionError)
_F_RECV = faults.declare("net.multiplexer.frame_recv",
                         exc=faults.InjectedConnectionError)
_FRAME_RETRY = dict(transient=(faults.InjectedConnectionError,))


def _send_frame(group, peer: int, msg: Any, what: str) -> None:
    if not faults.REGISTRY.active():     # disarmed hot path: direct
        return group.send_to(peer, msg)

    def op():
        faults.check(_F_SEND, peer=peer, what=what)
        group.send_to(peer, msg)
    default_policy(**_FRAME_RETRY).run(op, what=f"{what}:send")


def _recv_frame(group, peer: int, what: str) -> Any:
    if not faults.REGISTRY.active():
        return group.recv_from(peer)

    def op():
        faults.check(_F_RECV, peer=peer, what=what)
        return group.recv_from(peer)
    return default_policy(**_FRAME_RETRY).run(op, what=f"{what}:recv")


def multiprocess(mex) -> bool:
    """Is the host plane split across controllers?

    Loud by design: a multi-process mesh WITHOUT a working host control
    plane cannot run host-storage pipelines correctly (each process
    holds only its workers' items and has no way to ship the rest), so
    that configuration raises here rather than silently computing
    per-process answers."""
    if getattr(mex, "num_processes", 1) <= 1:
        return False
    _net(mex)
    return True


def _net(mex):
    net = getattr(mex, "host_net", None)
    if net is None or net.num_workers != mex.num_processes:
        raise RuntimeError(
            "multi-process host-storage pipeline needs the host control "
            "plane: set THRILL_TPU_HOSTLIST/RANK/SECRET so every "
            "controller joins the TCP group")
    return net


def local_worker_set(mex) -> set:
    """Workers this process materializes host storage for. All of them
    in a single-controller run; in a multi-controller run only the
    local block (and the control plane must exist — see multiprocess)."""
    if multiprocess(mex):
        return set(mex.local_workers)
    return set(range(mex.num_workers))


def host_exchange(mex, shards: HostShards, dest_fn: Callable[[Any], int],
                  reason: str = "host-exchange") -> HostShards:
    """Move every item to the worker ``dest_fn(item) % W`` computes.

    Single-controller: in-process bucketing (the old fast path).
    Multi-controller: this process buckets its local workers' items,
    ships each remote process one framed message of
    ``{dest_worker: {src_worker: [items...]}}`` over the TCP group
    (large frames ride the async dispatcher), and assembles its own
    workers' receives in source-worker rank order — the CatStream
    delivery guarantee (reference: thrill/data/cat_stream.hpp:155).
    """
    W = shards.num_workers
    if not multiprocess(mex):
        buckets: List[List[Any]] = [[] for _ in range(W)]
        for items in shards.lists:
            for it in items:
                buckets[dest_fn(it) % W].append(it)
        return HostShards(W, buckets)

    net = _net(mex)
    wp = mex.worker_process
    me = mex.process_index
    P = mex.num_processes
    # bucket local items: {dest_worker: {src_worker: [items]}} per
    # destination process (iterating local workers in rank order keeps
    # each batch internally ordered)
    outgoing: List[dict] = [dict() for _ in range(P)]
    for sw in mex.local_workers:
        for it in shards.lists[sw]:
            dw = int(dest_fn(it)) % W
            msg = outgoing[int(wp[dw])]
            msg.setdefault(dw, {}).setdefault(sw, []).append(it)

    received = [outgoing[me]]
    sent_items = 0
    group = net.group
    with poison_on_error(group, "host_exchange"):
        for r in range(1, P):
            to, frm = (me + r) % P, (me - r) % P
            sent_items += sum(len(b) for dws in outgoing[to].values()
                              for b in dws.values())
            _send_frame(group, to, outgoing[to], "host_exchange")
            received.append(_recv_frame(group, frm, "host_exchange"))

    lists: List[List[Any]] = [[] for _ in range(W)]
    for w in mex.local_workers:
        per_src: dict = {}
        for msg in received:
            per_src.update(msg.get(w, {}))
        for sw in sorted(per_src):
            lists[w].extend(per_src[sw])

    mex.stats_exchanges += 1
    mex.stats_items_moved += sent_items
    log = getattr(mex, "logger", None)
    if log is not None and log.enabled:
        log.line(event="host_exchange", reason=reason,
                 items_sent=sent_items, processes=P)
    return HostShards(W, lists)


def ensure_replicated(mex, shards: HostShards,
                      reason: str = "host-global") -> HostShards:
    """Every process gets every worker's items (identical full lists).

    The demotion for host operators that need a global item view (EM
    sort, zip alignment, generic prefix sums...). Idempotent: each
    worker's list is taken from its owning process only.
    """
    if not multiprocess(mex):
        return shards
    net = _net(mex)
    W = shards.num_workers
    local = {w: shards.lists[w] for w in mex.local_workers
             if shards.lists[w]}
    with poison_on_error(net.group, "host_replicate"):
        gathered = net.all_gather(local)
    lists: List[List[Any]] = [[] for _ in range(W)]
    for msg in gathered:
        for w, items in msg.items():
            lists[int(w)] = list(items)
    log = getattr(mex, "logger", None)
    if log is not None and log.enabled:
        log.line(event="host_replicate", reason=reason,
                 items=sum(len(l) for l in lists))
    return HostShards(W, lists)


def localize(mex, shards: HostShards) -> HostShards:
    """Restore the local-only invariant after a replicated computation
    produced identical full lists on every process."""
    if not multiprocess(mex):
        return shards
    local = set(mex.local_workers)
    return HostShards(shards.num_workers,
                      [shards.lists[w] if w in local else []
                       for w in range(shards.num_workers)])


def global_counts(mex, shards: HostShards) -> np.ndarray:
    """Per-worker item counts agreed across processes."""
    if not multiprocess(mex):
        return shards.counts
    net = _net(mex)
    counts = np.zeros(shards.num_workers, dtype=np.int64)
    local = {w: len(shards.lists[w]) for w in mex.local_workers}
    with poison_on_error(net.group, "global_counts"):
        gathered = net.all_gather(local)
    for msg in gathered:
        for w, n in msg.items():
            counts[int(w)] = int(n)
    return counts


def global_total(mex, shards: HostShards) -> int:
    if not multiprocess(mex):
        return shards.total
    return int(_net(mex).all_reduce(
        sum(len(shards.lists[w]) for w in mex.local_workers)))


def all_items(mex, shards: HostShards) -> List[Any]:
    """Every item in worker-rank order, on every process."""
    return [it for l in ensure_replicated(mex, shards, "all-items").lists
            for it in l]


def net_fold(mex, local: Any, op: Callable[[Any, Any], Any],
             empty: bool = False) -> Any:
    """Fold per-process partial results over the control plane.

    ``local`` is this process's partial (ignored when ``empty``);
    returns the rank-ordered fold of all non-empty partials, or raises
    if every process was empty."""
    if not multiprocess(mex):
        if empty:
            raise ValueError("fold over an empty DIA")
        return local
    net = _net(mex)
    with poison_on_error(net.group, "net_fold"):
        vals = net.all_gather(_MISSING if empty else local)
    vals = [v for v in vals if not (isinstance(v, str) and v == _MISSING)]
    if not vals:
        raise ValueError("fold over an empty DIA")
    acc = vals[0]
    for v in vals[1:]:
        acc = op(acc, v)
    return acc


def host_to_device(mex, shards: HostShards) -> DeviceShards:
    """HostShards -> DeviceShards in a multi-controller run.

    Three things must be agreed across processes before the device_put:
    the padded capacity (shapes must match), the global per-worker
    counts (each process only knows its own), and the item schema (a
    process whose workers are all empty must still build correctly
    shaped zero blocks)."""
    counts = global_counts(mex, shards)
    cap = max(1, round_up_pow2(int(counts.max()) if len(counts) else 1))
    net = _net(mex)
    sample = next((items[0] for w in mex.local_workers
                   for items in [shards.lists[w]] if items), None)
    samples = net.all_gather(_MISSING if sample is None else sample)
    sample = next((s for s in samples
                   if not (isinstance(s, str) and s == _MISSING)), None)
    if sample is None:
        raise ValueError("cannot infer schema of an entirely empty DIA")
    import jax

    from .shards import columnarize
    treedef = jax.tree.structure(sample)
    local = set(mex.local_workers)
    empty = jax.tree.map(lambda a: np.asarray([a])[:0], sample)
    per_worker = []
    for w in range(shards.num_workers):
        items = shards.lists[w] if w in local else []
        per_worker.append(columnarize(items, treedef) if items else empty)
    return DeviceShards.from_worker_arrays(mex, per_worker, cap=cap,
                                           counts=counts)
