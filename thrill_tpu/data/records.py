"""Native columnar spill records: schema probe, vectorized encode, and
the ctypes driver for native/records.cpp.

The out-of-core hot path's GIL ceiling (ROADMAP edge (a), measured in
PR 13): the write-behind spill overlapped disk I/O but the per-run
pickle/tuple encode ran ON the interpreter, so the writer thread and
the main thread time-sliced one GIL. This module moves the encode
outside it:

* **Schema probe + vectorized columns.** Items built from python
  scalars (int/bool/float/str/bytes) and (nested) tuples of them map
  to the serializer's columnar container kind (data/serializer.py
  ``_COLS``): one numpy column per scalar leaf, built by ONE
  vectorized call per field per batch — zero per-item python objects.
  Anything the mapping cannot represent EXACTLY returns None and the
  caller keeps the pickle path: out-of-int64 ints (OverflowError),
  mixed types at one position, numpy scalars, trailing-NUL
  strings/bytes (numpy's U/S dtypes strip them — detected by
  vectorized length comparison), ndarray or ragged payloads.
* **Native sort + gather** (native/records.cpp, built on first use
  like blockstore/hostsort/mwmerge). ``argsort_rows`` memcmp-argsorts
  a run's fixed-width key rows and ``write_run_blocks`` gathers pos +
  payload columns straight into block buffers — ctypes releases the
  GIL for the whole call, so a spill job on the write-behind thread
  runs GENUINELY in parallel with the main thread's next run. Without
  the toolchain both fall back to numpy (same bytes, GIL semantics of
  numpy — the format never depends on the compiler).
* **Degrade contract** (fault site ``data.records.encode``): any
  encode failure — injected or real — falls back to the pickle path
  and notes the recovery. Slower, never wrong data; decode handles
  every container kind regardless of knobs.

``THRILL_TPU_NATIVE_RECORDS=0`` disables the columnar kind entirely:
``serialize_batch`` and the em_sort run spiller produce today's pickle
bytes bit-identically (tests/data/test_records.py pins this against a
reference implementation).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..common import faults
from ..common.config import _env_flag
from ..common.iostats import IO as _IOSTATS

_F_ENCODE = faults.declare("data.records.encode")

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def enabled() -> bool:
    """THRILL_TPU_NATIVE_RECORDS=0 restores the pre-columnar encode
    bit-identically (pickle blocks, (offs, blob) key chunks). Decode of
    already-written columnar blocks stays on either way."""
    return _env_flag("THRILL_TPU_NATIVE_RECORDS", True)


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        from ..common.native_build import build_and_load
        lib = build_and_load("records.cpp")
        if lib is not None:
            lib.rec_argsort.restype = ctypes.c_int32
            lib.rec_argsort.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p]
            lib.rec_gather.restype = ctypes.c_int64
            lib.rec_gather.argtypes = [
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p]
        _LIB = lib
        return _LIB


def native_available() -> bool:
    """Is the GIL-free engine loaded? (The FORMAT does not require it —
    numpy fallbacks produce identical bytes.)"""
    return enabled() and _load() is not None


# ----------------------------------------------------------------------
# schema probe + vectorized column encode
# ----------------------------------------------------------------------

#: exact python scalar types a column leaf may hold (numpy scalars are
#: deliberately excluded: round-trip identity is the contract, and the
#: canonical item unboxing — data/shards.itemize — yields these types)
_LEAF_TYPES = (bool, int, float, str, bytes)


def template_of(item: Any):
    """Serializer template of one sample item, or None (unsupported)."""
    t = type(item)
    if t in _LEAF_TYPES:
        return "x"
    if t is np.ndarray:
        # fixed-shape, fixed-dtype ndarray leaf (ISSUE 17): one
        # ``("A", dstr, shape)`` column of V rows. The probe pins the
        # EXACT dtype.str and shape — any batch member deviating
        # (ragged shapes, upcast dtypes, 0-d, empty, object dtype)
        # makes the encoder return None and that batch pickles.
        if (item.ndim >= 1 and item.size > 0
                and item.dtype.kind in "biufcSU"
                and item.dtype.itemsize > 0):
            return ("A", item.dtype.str, item.shape)
        return None
    if t is tuple and item:
        subs = tuple(template_of(e) for e in item)
        if any(s is None for s in subs):
            return None
        return ("T",) + subs
    return None


def _is_leaf(tmpl) -> bool:
    return tmpl in ("x", "s") or tmpl[0] == "A"


def _leaf_values(tmpl, items: List[Any], out: List[list]) -> None:
    """Transpose items into per-leaf value lists (template order)."""
    if _is_leaf(tmpl):
        out.append(items)
        return
    # every row must be a tuple of EXACTLY the probed arity (both
    # checks are single C-level passes): zip would silently truncate a
    # longer row — a wrong-data bug, not a fallback
    if set(map(type, items)) != {tuple} or \
            set(map(len, items)) != {len(tmpl) - 1}:
        raise TypeError("tuple shape deviates from the probed schema")
    for sub, vals in zip(tmpl[1:], zip(*items)):
        _leaf_values(sub, list(vals), out)


def _leaf_templates(tmpl, out: List[Any]) -> None:
    """Flatten a probe template into its leaves, in column order."""
    if _is_leaf(tmpl):
        out.append(tmpl)
        return
    for sub in tmpl[1:]:
        _leaf_templates(sub, out)


def _encode_leaf(vals: list
                 ) -> Optional[Tuple[str, np.ndarray]]:
    """One scalar column as ``(leaf_tag, array)``, or None when the
    values cannot ride a fixed dtype EXACTLY. Raises OverflowError on
    out-of-int64 ints (caller treats any raise as fallback too).

    ASCII str columns compact to S storage (tag ``"s"``, 1 byte/char
    on disk instead of UCS-4's four — spill volume is the out-of-core
    tier's real currency); non-ASCII strings keep the exact U column
    (tag ``"x"``)."""
    kinds = set(map(type, vals))
    if len(kinds) != 1:
        return None
    t = kinds.pop()
    n = len(vals)
    if t is bool:
        return "x", np.fromiter(vals, dtype=np.bool_, count=n)
    if t is int:
        # OverflowError on out-of-int64 values -> caller falls back
        return "x", np.fromiter(vals, dtype=np.int64, count=n)
    if t is float:
        return "x", np.fromiter(vals, dtype=np.float64, count=n)
    if t is str or t is bytes:
        arr = np.asarray(vals)
        if arr.dtype.kind not in ("U", "S") or arr.dtype.itemsize == 0:
            return None
        # numpy's U/S dtypes strip TRAILING NULs at unbox time; a value
        # whose true length disagrees with the stored length cannot
        # round-trip and must fall back (vectorized: one str_len pass
        # against the python lengths)
        lens = np.fromiter(map(len, vals), dtype=np.int64, count=n)
        if (np.char.str_len(arr) != lens).any():
            return None
        if t is str:
            try:
                return "s", arr.astype(
                    f"S{max(arr.dtype.itemsize // 4, 1)}")
            except (UnicodeEncodeError, UnicodeError):
                return "x", arr          # non-ASCII: exact U column
        return "x", arr
    return None


def _encode_array_leaf(vals: list, tmpl
                       ) -> Optional[Tuple[Any, np.ndarray]]:
    """One ndarray-leaf column: the (N, *shape) stack's bytes as a 1D
    ``|V{row_bytes}`` array (itemsize == one element's bytes), so the
    downstream byte machinery — run-block gather, slice arithmetic,
    native widths — treats it exactly like any other fixed-width
    column. None when any value deviates from the probed dtype/shape
    (ragged batches pickle, never lie)."""
    _, dstr, shape = tmpl
    shape = tuple(shape)
    for v in vals:
        if type(v) is not np.ndarray or v.dtype.str != dstr \
                or v.shape != shape:
            return None
    n = len(vals)
    stacked = np.ascontiguousarray(np.stack(vals))
    rb = stacked.dtype.itemsize * int(
        np.prod(shape, dtype=np.int64))
    col = stacked.reshape(n, -1).view(f"V{rb}").reshape(n)
    return tmpl, col


def _retag(tmpl, tags) -> Any:
    """Template with each leaf replaced by its encode-time tag
    (``tags`` iterates in leaf order; ndarray leaves tag as their full
    ``("A", ...)`` template)."""
    if _is_leaf(tmpl):
        return next(tags)
    return ("T",) + tuple(_retag(s, tags) for s in tmpl[1:])


def _encode_columns(tmpl, items: List[Any]
                    ) -> Optional[Tuple[Any, List[np.ndarray]]]:
    """(retagged_template, columns) or None. May raise (callers own
    the fallback)."""
    leaves: List[list] = []
    _leaf_values(tmpl, items, leaves)
    ltmpls: List[Any] = []
    _leaf_templates(tmpl, ltmpls)
    cols: List[np.ndarray] = []
    tags: List[Any] = []
    for lt, vals in zip(ltmpls, leaves):
        enc = _encode_array_leaf(vals, lt) if lt != "x" \
            else _encode_leaf(vals)
        if enc is None:
            return None
        tags.append(enc[0])
        cols.append(enc[1])
    return _retag(tmpl, iter(tags)), cols


def make_run_encoder(sample_item: Any) -> Optional[Callable]:
    """Payload encoder for the em_sort run spiller, or None.

    ``encoder(batch) -> (template, list[np.ndarray]) | None``: the
    batch's payload columns plus the encode-time template (leaf tags
    like the ASCII-compact ``"s"`` are data-dependent), or None when
    this batch deviates from the probed schema. The CALLER requires
    one template per run (columns concatenate across batches) and
    falls back to the item-list path when batches disagree."""
    if not enabled():
        return None
    tmpl = template_of(sample_item)
    if tmpl is None:
        return None

    def encode(batch: List[Any]):
        try:
            return _encode_columns(tmpl, batch)
        except (TypeError, ValueError, OverflowError):
            return None

    # self-check on the sample (e.g. a trailing-NUL sample string)
    if encode([sample_item]) is None:
        return None
    return encode


def encode_batch_columns(items: List[Any]
                         ) -> Optional[Tuple[Any, List[np.ndarray]]]:
    """One-shot columnar encode for ``serialize_batch``: (template,
    columns) or None (the caller pickles). Never raises — the
    ``data.records.encode`` fault site degrades here too."""
    if not enabled():
        return None
    tmpl = template_of(items[0])
    if tmpl is None:
        return None
    try:
        if faults.REGISTRY.active():
            faults.check(_F_ENCODE, n=len(items))
        enc = _encode_columns(tmpl, items)
    except faults.InjectedFault as e:
        faults.note("recovery", what="records.encode_degraded",
                    error=repr(e)[:200])
        return None
    except (TypeError, ValueError, OverflowError):
        return None
    if enc is None:
        return None
    _IOSTATS.add(records_blocks=1)
    return enc


# ----------------------------------------------------------------------
# native sort + gather (numpy fallbacks: identical bytes, GIL held)
# ----------------------------------------------------------------------

def argsort_rows(arr: np.ndarray) -> np.ndarray:
    """Lexicographic argsort of an ``S{w}`` row array as int64. The
    native engine memcmp-sorts with the GIL released; the numpy
    fallback is order-identical (S comparison == padded memcmp: the
    \\0 pad is the minimum byte), so on/off results are bit-equal."""
    lib = _load() if enabled() else None
    if lib is None:
        return np.argsort(arr, kind="stable").astype(np.int64)
    arr = np.ascontiguousarray(arr)
    out = np.empty(len(arr), dtype=np.int64)
    rc = lib.rec_argsort(arr.ctypes.data_as(ctypes.c_void_p),
                         arr.dtype.itemsize, len(arr),
                         out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise RuntimeError(f"rec_argsort failed rc={rc}")
    return out


def _gather_native(cols: List[np.ndarray], order: np.ndarray,
                   i0: int, i1: int, out_view: np.ndarray) -> None:
    """Gather rows order[i0:i1] of every column into ``out_view``
    (uint8, exactly the gathered bytes), natively when available."""
    lib = _load() if enabled() else None
    if lib is not None:
        ptrs = (ctypes.c_void_p * len(cols))(
            *[c.ctypes.data for c in cols])
        widths = np.array([c.dtype.itemsize for c in cols],
                          dtype=np.int64)
        n = lib.rec_gather(
            len(cols), ptrs, widths.ctypes.data_as(ctypes.c_void_p),
            order.ctypes.data_as(ctypes.c_void_p), i0, i1,
            out_view.ctypes.data_as(ctypes.c_void_p))
        if n != out_view.nbytes:
            raise RuntimeError(
                f"rec_gather wrote {n} of {out_view.nbytes} bytes")
        return
    # numpy fallback: same bytes, fancy-index per column
    idx = order[i0:i1]
    off = 0
    for c in cols:
        w = c.dtype.itemsize
        nb = (i1 - i0) * w
        out_view[off:off + nb] = c[idx].view(np.uint8)
        off += nb


def gather_rows(arr: np.ndarray, order: np.ndarray) -> np.ndarray:
    """``arr[order]`` for a fixed-width row array through the native
    gather (GIL-free) — the sorted key rows the merge's key file
    spills."""
    arr = np.ascontiguousarray(arr)
    order = np.ascontiguousarray(order, dtype=np.int64)
    out = np.empty(len(order) * arr.dtype.itemsize, dtype=np.uint8)
    _gather_native([arr], order, 0, len(order), out)
    return out.view(arr.dtype)


def write_run_blocks(file, order: np.ndarray, p0: int,
                     pay_cols: List[np.ndarray], item_tmpl,
                     block_items: int) -> int:
    """Write one sorted run's (pos, item) records into ``file`` as
    columnar blocks, gathered by ``order`` — ONE native call per block
    instead of per-item tuple+pickle work; the assembled buffer is
    handed to the block store whole (zero-copy into the native store's
    Put). Runs on the write-behind thread; raises on any failure (the
    caller owns the degrade-to-pickle fallback). Returns rows written.

    The ``data.records.encode`` site fires here too, exercising the
    degrade contract on the REAL spill path."""
    from .block import Block
    from .serializer import columnar_header
    if faults.REGISTRY.active():
        faults.check(_F_ENCODE, rows=len(order))
    n = len(order)
    order = np.ascontiguousarray(order, dtype=np.int64)
    cols = [np.arange(p0, p0 + n, dtype=np.int64)] \
        + [np.ascontiguousarray(c) for c in pay_cols]
    tmpl = ("T", "x", item_tmpl)
    dstrs = [c.dtype.str for c in cols]
    row_bytes = sum(c.dtype.itemsize for c in cols)
    pool = file.pool
    nblocks = 0
    for i0 in range(0, n, block_items):
        i1 = min(i0 + block_items, n)
        head = columnar_header(tmpl, dstrs, i1 - i0)
        buf = np.empty(len(head) + (i1 - i0) * row_bytes,
                       dtype=np.uint8)
        buf[:len(head)] = np.frombuffer(head, dtype=np.uint8)
        _gather_native(cols, order, i0, i1, buf[len(head):])
        bid = pool.put(buf)
        file.blocks.append(Block(pool, bid, 0, i1 - i0))
        nblocks += 1
    # counted only once the WHOLE run wrote: a mid-run failure's
    # blocks are discarded by the caller's degrade path and must not
    # read as a surviving columnar spill
    _IOSTATS.add(records_blocks=nblocks)
    return n
