"""Bounded write-behind executor: the spill side of the out-of-core
overlap tier.

The reference's foxxll-backed BlockPool never blocks an operator on a
spill write — sorted runs stream to disk while the next run forms
(PAPER.md, the async external-memory block manager Thrill's whole
batch story rests on). This module is that contract for the Python
layers that used to flush synchronously on the caller's thread: the
BlockPool pure-python fallback and em_sort's run spilling.

:class:`AsyncWriter` is the PR-6 async-sender pattern
(data/multiplexer.py ``_exchange_frames_async``) recast for storage:

* ONE background writer thread, FIFO — submission order is completion
  order, so run files land in the order the sort produced them;
* a bounded queue (``THRILL_TPU_WRITEBACK_QUEUE``) applies
  backpressure instead of buffering every pending run in RAM;
* errors are captured and RE-RAISED on the submitting thread at the
  next ``submit``/``flush``/``close`` — the poison scope: a failed
  flush surfaces with its root cause before any consumer reads the
  (absent) data, never silent loss. ``poison=False`` writers (the
  BlockPool fallback, where a failed eviction write legitimately
  keeps the block RAM-resident) route errors to an ``on_error``
  callback instead;
* ``THRILL_TPU_WRITEBACK=0`` (or the ``THRILL_TPU_OVERLAP=0`` master
  switch) runs every job inline on the caller — today's synchronous
  behavior exactly, same bytes, same file naming.

The ``data.spill.writeback`` fault site fires on the WRITER thread
before a job runs (nothing written yet), exercising both contracts:
poison writers surface it at the barrier, degrade writers keep the
data resident and note the recovery.

:func:`make_readahead` is the read-side sibling for sites that
prefetch BLOCKS rather than byte streams (the k-way merge's
one-slot-per-run readahead, the double-buffered spill restore): a
short-lived, bounded thread pool the caller shuts down with its
operation, so no framework thread outlives the work it overlapped.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from ..common import faults
from ..common.config import _env_flag, overlap_enabled
from ..common.iostats import IO as _IOSTATS

_F_WRITEBACK = faults.declare("data.spill.writeback")


def writeback_enabled() -> bool:
    """THRILL_TPU_WRITEBACK=0 restores synchronous spill writes on the
    caller's thread (byte-identical, same file naming); the
    THRILL_TPU_OVERLAP=0 master switch disables it too."""
    return overlap_enabled() and _env_flag("THRILL_TPU_WRITEBACK", True)


def writeback_queue_depth() -> int:
    """THRILL_TPU_WRITEBACK_QUEUE: max queued spill jobs (default 2 —
    at most depth+1 runs resident beyond the synchronous baseline)."""
    try:
        return max(1, int(os.environ.get("THRILL_TPU_WRITEBACK_QUEUE",
                                         "2") or 2))
    except ValueError:
        return 2


class AsyncWriter:
    """Single-threaded bounded write-behind queue (see module doc)."""

    def __init__(self, what: str, depth: Optional[int] = None,
                 sync: Optional[bool] = None, poison: bool = True,
                 tracer=None,
                 on_error: Optional[Callable[[BaseException, Any],
                                             None]] = None) -> None:
        self.what = what
        self.sync = (not writeback_enabled()) if sync is None else sync
        self.depth = writeback_queue_depth() if depth is None else depth
        self.poison = poison
        self.on_error = on_error
        self._tracer = tracer
        self._parent = (tracer.current_id()
                        if tracer is not None and tracer.enabled
                        else None)
        self._cv = threading.Condition()
        self._jobs: collections.deque = collections.deque()
        self._err: Optional[BaseException] = None
        self._closed = False
        self._idle = True
        self._t: Optional[threading.Thread] = None
        self.jobs_run = 0
        self.bytes_written = 0

    # -- writer thread --------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._t is None:
            self._t = threading.Thread(target=self._run, daemon=True,
                                       name="thrill-tpu-writeback")
            self._t.start()

    def _run(self) -> None:
        tr = self._tracer
        while True:
            with self._cv:
                while not self._jobs and not self._closed:
                    self._idle = True
                    self._cv.notify_all()
                    self._cv.wait(0.1)
                if not self._jobs and self._closed:
                    self._idle = True
                    self._cv.notify_all()
                    return
                fn, tag = self._jobs.popleft()
                self._idle = False
                self._cv.notify_all()
            try:
                if faults.REGISTRY.active():
                    faults.check(_F_WRITEBACK, what=self.what, tag=tag)
                t0 = time.perf_counter()
                if tr is not None and tr.enabled:
                    with tr.span("io", "writeback", parent=self._parent,
                                 what=self.what, tag=tag):
                        nbytes = fn()
                else:
                    nbytes = fn()
                nbytes = int(nbytes or 0)
                _IOSTATS.add(io_busy_s=time.perf_counter() - t0,
                             writeback_bytes=nbytes)
                with self._cv:
                    self.jobs_run += 1
                    self.bytes_written += nbytes
                    self._cv.notify_all()
            except BaseException as e:
                if self.poison:
                    # poison scope: drop the backlog (its files will
                    # never be read — the error surfaces first) and
                    # park the error for the submitting thread
                    with self._cv:
                        self._err = e
                        self._jobs.clear()
                        self._idle = True
                        self._cv.notify_all()
                    return
                faults.note("recovery", what=f"{self.what}.degraded",
                            error=repr(e)[:200])
                if self.on_error is not None:
                    try:
                        self.on_error(e, tag)
                    except Exception:
                        pass
                with self._cv:
                    self._cv.notify_all()

    # -- submitting side ------------------------------------------------
    def _raise_pending(self) -> None:
        if self._err is not None:
            err, self._err = self._err, None
            self._closed = True
            raise err

    def submit(self, fn: Callable[[], Any], tag: Any = None) -> None:
        """Queue one write job (``fn() -> bytes written``); runs inline
        in sync mode. Blocks (counted as ``io_wait_s``) only when the
        queue is ``depth`` jobs behind; re-raises a pending writer
        error instead of queueing behind a dead writer."""
        if self.sync:
            nbytes = int(fn() or 0)
            _IOSTATS.add(writeback_bytes=nbytes)
            with self._cv:
                self.jobs_run += 1
                self.bytes_written += nbytes
            return
        self._ensure_thread()
        t0 = None
        with self._cv:
            self._raise_pending()
            if self._closed:
                raise RuntimeError(f"{self.what}: writer is closed")
            while len(self._jobs) >= self.depth and self._err is None:
                if t0 is None:
                    t0 = time.perf_counter()
                self._cv.wait(0.1)
            self._raise_pending()
            self._jobs.append((fn, tag))
            depth_now = len(self._jobs) + (0 if self._idle else 1)
            self._cv.notify_all()
        if t0 is not None:
            _IOSTATS.add(io_wait_s=time.perf_counter() - t0)
        _IOSTATS.note_queue_depth(depth_now)

    def flush(self) -> None:
        """Barrier: every queued/in-flight job is durably done (or the
        writer's error re-raises here, before any consumer trusts the
        flushed data)."""
        if self.sync or self._t is None:
            self._raise_pending()
            return
        t0 = time.perf_counter()
        with self._cv:
            while (self._jobs or not self._idle) and self._err is None:
                self._cv.wait(0.1)
            dt = time.perf_counter() - t0
            self._raise_pending()
        if dt > 1e-4:
            _IOSTATS.add(io_wait_s=dt)

    def close(self, drain: bool = True) -> None:
        """Stop the writer. ``drain=True`` barriers first (and
        re-raises a pending error); ``drain=False`` abandons the
        backlog (abort paths — the job is already failing)."""
        if self._t is None:
            if drain:
                self._raise_pending()
            self._closed = True
            return
        try:
            if drain:
                self.flush()
        finally:
            with self._cv:
                self._closed = True
                if not drain:
                    self._jobs.clear()
                    self._err = None
                self._cv.notify_all()
            # the join must OUTLAST a slow in-flight job: callers free
            # the backing store right after close() (em_sort's finally
            # does pool.close()), so returning with the writer alive
            # would let the job write into freed memory. A genuinely
            # wedged disk therefore blocks close loudly rather than
            # corrupting — same contract as the native store's
            # destructor barrier.
            self._t.join(timeout=30)
            while self._t.is_alive():
                import sys
                print(f"thrill_tpu.writeback: {self.what} writer "
                      f"still flushing; waiting before teardown",
                      file=sys.stderr)
                self._t.join(timeout=30)

    def __enter__(self) -> "AsyncWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        # on an exception the scope is already poisoned: don't let a
        # drain barrier (or its own error) mask the original
        self.close(drain=exc_type is None)


def make_readahead(depth: int, workers: int = 0
                   ) -> Optional[ThreadPoolExecutor]:
    """A bounded, short-lived block-readahead pool for one operation
    (k-way merge, spill restore), or None when prefetch is off
    (``depth`` <= 0). The CALLER shuts it down (``shutdown(wait=...)``)
    when the operation ends — readahead threads never outlive the work
    they overlap."""
    if depth <= 0:
        return None
    return ThreadPoolExecutor(
        max_workers=workers or max(2, min(depth, 8)),
        thread_name_prefix="thrill-tpu-readahead")


def readahead_get(fut, demand: Callable[[], Any], what: str) -> Any:
    """Consume one readahead future with the degrade contract: a
    background failure (injected ``vfs.prefetch`` or a real read
    error) falls back to the DEMAND read on the calling thread —
    slower, never wrong data. Readahead is OPPORTUNISTIC: a future
    still queued behind the pool (not yet started) is cancelled and
    the block demand-read instead — waiting on the backlog would turn
    a cheap RAM-resident get into a queue stall. Accounts
    hit/miss/wait like the vfs reader."""
    if fut is None:
        return demand()
    waited = False
    if fut.done():
        pass
    elif fut.cancel():
        # never started: the consumer outran the pool — demand-read
        _IOSTATS.add(prefetch_misses=1)
        return demand()
    else:
        # mid-flight: finishing the started read beats issuing a
        # second one for the same bytes
        t0 = time.perf_counter()
        try:
            fut.result()
        except BaseException:
            pass
        _IOSTATS.add(prefetch_misses=1,
                     io_wait_s=time.perf_counter() - t0)
        waited = True
    try:
        out = fut.result()
    except BaseException as e:
        # a completed-with-error future is a MISS (the hit-rate signal
        # must not rise when prefetch fails), then the degrade path
        if not waited:
            _IOSTATS.add(prefetch_misses=1)
        faults.note("recovery", what=f"{what}.prefetch_degraded",
                    error=repr(e)[:200])
        return demand()
    if not waited:
        _IOSTATS.add(prefetch_hits=1)
    return out


def overlapped_fetch(items, fetch: Callable[[Any], Any], what: str,
                     ra: Optional[ThreadPoolExecutor],
                     skip_fn: Optional[Callable[[Any], bool]] = None,
                     stats: Optional[dict] = None):
    """Yield ``(item, fetch(item))`` with the NEXT item's fetch already
    in flight behind the current item's consumption — THE one-ahead
    overlap loop (checkpoint shard restores, HBM spill restores), in
    one place so the degrade contract and hit/miss accounting cannot
    diverge between call sites. ``skip_fn`` marks items whose fetch is
    cheap inline (RAM-resident blocks — the surgical policy);
    ``stats["prefetched"]`` counts the fetches that actually rode the
    pool. ``ra=None`` degrades to plain sequential fetches."""
    items = list(items)
    fut = None
    for j, it in enumerate(items):
        nxt = None
        if ra is not None and j + 1 < len(items):
            nit = items[j + 1]
            if skip_fn is None or not skip_fn(nit):
                nxt = ra.submit(readahead_job(
                    lambda nit=nit: fetch(nit), what))
                if stats is not None:
                    stats["prefetched"] = stats.get("prefetched", 0) + 1
        out = readahead_get(fut, lambda it=it: fetch(it), what)
        fut = nxt
        yield it, out


def readahead_job(fn: Callable[[], Any],
                  what: str) -> Callable[[], Any]:
    """Wrap a block-load callable for the readahead pool: the
    ``vfs.prefetch`` injection gate plus busy-time accounting. Every
    wrap is one SUBMISSION (``prefetch_submits``) — with the spill
    store settled at the merge barrier this count is deterministic,
    which is what lets the perf sentinel contract it exactly."""
    _IOSTATS.add(prefetch_submits=1)

    def job():
        if faults.REGISTRY.active():
            faults.check("vfs.prefetch", what=what)
        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            _IOSTATS.add(io_busy_s=time.perf_counter() - t0)
    return job
