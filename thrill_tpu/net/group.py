"""Abstract point-to-point group and generic collective algorithms.

Equivalent of the reference's net::Group / net::Connection and the
templated collectives implemented generically over connections
(reference: thrill/net/group.hpp:47, net/connection.hpp:49,
net/collective.hpp:52-579). Like the reference, collective algorithms are
implemented *in the framework*, generically over any transport backend
(mock in-process queues now; TCP across hosts later), and auto-select by
group size: dissemination prefix-sum, binomial-tree broadcast,
recursive-doubling all-gather and hypercube all-reduce.

These host-level collectives form the *control plane* — small values,
blocking semantics. The bulk data plane on TPU is XLA collectives inside
jitted programs (see net/xla.py); this layer coordinates the Python hosts
around those device programs (multi-host bootstrap, scalar agreement,
barriers), the role MPI plays for jax.distributed.
"""

from __future__ import annotations

import abc
import contextlib
import operator
import os
import time
from typing import Any, Callable, List, Optional

from ..common import faults

#: magic key of a poison control frame (a plain dict so it passes the
#: non-executing wire codec unauthenticated)
POISON_KEY = "__thrill_tpu_poison__"

#: magic key of a heartbeat frame (net/heartbeat.py): liveness chatter
#: multiplexed over the same connections — transports discard it before
#: it can reach a collective's payload stream
HEARTBEAT_KEY = "__thrill_tpu_hb__"

#: injectable hang: an armed fire at this site makes the next blocking
#: collective recv behave as if its deadline expired with no frame —
#: the watchdog's abort path runs for real, no actual wedged peer needed
_F_HANG = faults.declare("net.group.recv_hang")

#: heartbeat-probe site (checked per heartbeat send, net/heartbeat.py)
F_HEARTBEAT = faults.declare("net.heartbeat",
                             exc=faults.InjectedConnectionError)


class CollectiveHangTimeout(TimeoutError):
    """A blocking collective recv exceeded THRILL_TPU_HANG_TIMEOUT_S
    with no frame from the peer: the collective is wedged. Raised by
    transports (tcp/mock); the Group watchdog converts it into a
    ClusterAbort naming the collective and the silent peer rank."""


def hang_timeout_s() -> Optional[float]:
    """Collective-recv watchdog deadline (None = watchdog off — the
    default: a healthy slow peer must never be declared hung unless
    the operator opted into a bound)."""
    v = os.environ.get("THRILL_TPU_HANG_TIMEOUT_S", "")
    try:
        t = float(v)
    except ValueError:
        return None
    return t if t > 0 else None


class ClusterAbort(ConnectionError):
    """A peer broadcast a poison frame: its ROOT CAUSE, not a local
    secondary symptom. ConnectionError subclass so existing dead-peer
    handling (tests, cleanup paths) treats an abort as fatal transport
    loss — but the retry policy classifies it permanent (never retry
    a coordinated shutdown)."""

    def __init__(self, origin: int, cause: str) -> None:
        super().__init__(
            f"cluster abort from rank {origin}: {cause}")
        self.origin = origin
        self.cause = cause


class Connection(abc.ABC):
    """Reliable ordered duplex message channel to one peer."""

    @abc.abstractmethod
    def send(self, obj: Any) -> Optional[int]:
        """Send one message. Transports that serialize the payload
        return the serialized byte count (the wire truth, measured
        ONCE where the frame is encoded — data/multiplexer.py's
        byte accounting reads it instead of re-serializing); queue
        transports that pass objects by reference return None."""

    @abc.abstractmethod
    def recv(self) -> Any: ...

    def recv_deadline(self, deadline_s: float) -> Any:
        """Receive one message, raising :class:`CollectiveHangTimeout`
        after ``deadline_s`` with no complete frame. Transports without
        timed receives fall back to a plain blocking recv (the watchdog
        then covers only transports that implement it)."""
        return self.recv()

    def send_bounded(self, obj: Any, deadline_s: float) -> None:
        """Send with a bounded blocking time, raising TimeoutError on
        expiry. Used by the abort protocol: poisoning a peer whose
        socket buffer is full must not hang the aborting worker. The
        default delegates to plain send (queue-backed transports never
        block)."""
        self.send(obj)


class Group(abc.ABC):
    """A p-way clique of connections; my_rank in [0, num_hosts)."""

    def __init__(self, my_rank: int, num_hosts: int) -> None:
        self.my_rank = my_rank
        self._num_hosts = num_hosts
        # poison frames relay AT MOST ONCE per (origin, cause)
        # (transitivity without ping-pong, while a LATER unrelated
        # abort on a surviving group still relays): keys added by
        # poison_peers and by received poison frames
        self._poison_relayed: set = set()
        # failure detector state: which collective the caller is inside
        # (named in hang-abort causes), last heartbeat seen per peer,
        # and an abort latched by the background heartbeat monitor for
        # the main thread to surface at its next group operation
        self._collective_site: str = ""
        self._hb_last: dict = {}
        self._pending_abort: Optional[ClusterAbort] = None

    @property
    def num_hosts(self) -> int:
        return self._num_hosts

    @abc.abstractmethod
    def connection(self, peer: int) -> Connection: ...

    def send_to(self, peer: int, obj: Any) -> Optional[int]:
        self._check_pending_abort()
        return self.connection(peer).send(obj)

    @contextlib.contextmanager
    def _at(self, site: str):
        """Name the collective in flight so a hang-abort cause can say
        WHERE the group wedged, not just that it did."""
        prev = self._collective_site
        self._collective_site = site
        try:
            yield
        finally:
            self._collective_site = prev

    def _check_pending_abort(self) -> None:
        ab = self._pending_abort
        if ab is not None:
            raise ab

    def mark_dead(self, peer: int, cause: str) -> None:
        """Failure-detector verdict (net/heartbeat.py): ``peer`` is
        unreachable. Latch an abort for the main thread, poison the
        surviving peers so the whole group converts to fast attributable
        aborts instead of a cascade of timeouts."""
        ab = ClusterAbort(self.my_rank, cause)
        if self._pending_abort is None:
            self._pending_abort = ab
        try:
            self.poison_peers(cause)
        except Exception:
            pass

    def recv_from(self, peer: int) -> Any:
        """Receive one message; a poison control frame surfaces as
        :class:`ClusterAbort` carrying the originator's root cause
        (reference has no analog — a dead peer hangs its job until the
        runtime kills it, api/context.cpp:849-878).

        Collective watchdog: with ``THRILL_TPU_HANG_TIMEOUT_S`` set,
        a recv that sees no frame within the deadline poisons the
        group with a ClusterAbort naming the collective and the silent
        peer rank — a wedged collective becomes a fast, attributable
        abort a supervising re-launch can resume from."""
        self._check_pending_abort()
        deadline = hang_timeout_s()
        # the deadline is ABSOLUTE across heartbeat-filter iterations:
        # liveness chatter proves the peer process is alive but does
        # not excuse a wedged collective (same semantics as the tcp
        # transport's internal filter, TcpConnection._recv_msg)
        deadline_at = (None if deadline is None
                       else time.monotonic() + deadline)
        site = self._collective_site or "recv"
        while True:
            try:
                if faults.REGISTRY.active():
                    try:
                        faults.check(_F_HANG, peer=peer, site=site)
                    except faults.InjectedFault:
                        raise CollectiveHangTimeout(
                            "injected wedge") from None
                conn = self.connection(peer)
                if deadline_at is None:
                    obj = conn.recv()
                else:
                    remaining = deadline_at - time.monotonic()
                    if remaining <= 0:
                        raise CollectiveHangTimeout("deadline spent")
                    obj = conn.recv_deadline(remaining)
            except CollectiveHangTimeout:
                cause = (f"hang at {site}: rank {self.my_rank} "
                         f"received no frame from rank {peer} within "
                         f"{deadline if deadline is not None else 0}s "
                         f"(THRILL_TPU_HANG_TIMEOUT_S)")
                try:
                    self.poison_peers(cause)
                except Exception:
                    pass
                raise ClusterAbort(self.my_rank, cause) from None
            if isinstance(obj, dict) and HEARTBEAT_KEY in obj:
                # liveness chatter from a transport without its own
                # filter (mock queues): note it, keep waiting for the
                # payload on the SAME deadline budget
                self._hb_last[peer] = time.monotonic()
                continue
            break
        if isinstance(obj, dict) and POISON_KEY in obj:
            info = obj[POISON_KEY]
            origin = int(info.get("origin", peer))
            cause = str(info.get("cause", "unknown"))
            if (origin, cause) not in self._poison_relayed:
                # RELAY once before aborting: in tree/hypercube
                # collectives most ranks never recv from the origin
                # directly — without the relay they would block on a
                # healthy partner that already aborted and surface a
                # secondary 'peer closed' instead of the root cause
                try:
                    self.poison_peers(cause, origin=origin)
                except Exception:
                    pass
            raise ClusterAbort(origin, cause)
        return obj

    # ------------------------------------------------------------------
    # any-source receive (MixStream consume-first-arrival)
    # ------------------------------------------------------------------

    @property
    def supports_recv_any(self) -> bool:
        """Whether :meth:`recv_any` can genuinely pick whichever peer's
        frame lands first. Transports without a readiness probe fall
        back to the fixed per-peer schedule (the pre-any-source
        behavior) — callers need no special-casing either way."""
        return False

    def _pick_ready_peer(self, peers: List[int]) -> int:
        """Transport hook: block until SOME peer in ``peers`` has a
        frame pending and return its rank. The default (no readiness
        probe) returns the first peer — recv_any then degrades to the
        fixed schedule. Implementations should bound their wait by
        :func:`hang_timeout_s` and return any peer on expiry so
        ``recv_from``'s own watchdog produces the attributable abort."""
        return peers[0]

    def recv_any(self, peers: List[int]) -> tuple:
        """Receive one message from whichever of ``peers`` delivers
        first; returns ``(peer, obj)``. Poison frames, heartbeat
        filtering and the collective watchdog behave exactly as in
        :meth:`recv_from` (the pick only chooses WHO to drain; the
        actual receive goes through the same guarded path)."""
        self._check_pending_abort()
        peer = self._pick_ready_peer(list(peers))
        return peer, self.recv_from(peer)

    # ------------------------------------------------------------------
    # coordinated abort (poison control frames)
    # ------------------------------------------------------------------

    def poison_peers(self, cause: Any, origin: Optional[int] = None) -> int:
        """Best-effort broadcast of a poison frame to every peer.

        A worker hitting an unrecoverable error calls this before
        re-raising, so every peer blocked in a collective surfaces the
        ROOT CAUSE within its own recv deadline instead of a cascade of
        secondary timeouts; receivers relay once (recv_from), so ranks
        that never recv from the origin directly still get the cause.
        Returns the number of peers notified; failures to notify (the
        cause may be the transport itself) are swallowed — the
        caller's re-raise is the authoritative error. ``origin`` is
        set by relays to preserve the ORIGINATING rank.
        """
        org = self.my_rank if origin is None else origin
        self._poison_relayed.add((org, _cause_str(cause)))
        frame = {POISON_KEY: {"origin": org,
                              "cause": _cause_str(cause)}}
        # bounded send deadline (common/timeouts.py load scaling): a
        # peer that stopped draining its socket (wedged, descheduled,
        # dying) can have a FULL kernel buffer — a blocking send of the
        # poison frame would then hang the aborting worker itself.
        # Past the deadline that peer is skipped; it still learns the
        # cause from another rank's relay or its own recv deadline.
        from ..common.timeouts import scaled
        deadline = min(scaled(1.0), 5.0)
        notified = 0
        for peer in range(self.num_hosts):
            if peer == self.my_rank:
                continue
            try:
                # send only, never flush: a flush would wait on bulk
                # frames already queued to a DEAD peer and hang the
                # abort itself. Dispatcher-attached connections drain
                # the queued poison frame asynchronously; blocking
                # connections wrote it synchronously in send_bounded().
                self.connection(peer).send_bounded(frame, deadline)
                notified += 1
            except Exception:
                continue
        faults.note("abort", origin=self.my_rank, notified=notified,
                    cause=_cause_str(cause))
        return notified

    # ------------------------------------------------------------------
    # collectives (generic over connections; reference net/collective.hpp)
    # ------------------------------------------------------------------

    def prefix_sum(self, value: Any, op: Callable = operator.add) -> Any:
        """Dissemination ("doubling") inclusive prefix sum.

        Reference: PrefixSumDoubling, net/collective.hpp:52. O(log p)
        rounds; each round r exchanges with rank +/- 2^r.
        """
        p = self.num_hosts
        r = self.my_rank
        acc = value        # running sum of [r - 2^k + 1 .. r]
        d = 1
        with self._at("prefix_sum"):
            while d < p:
                if r + d < p:
                    self.send_to(r + d, acc)
                if r - d >= 0:
                    received = self.recv_from(r - d)
                    acc = op(received, acc)
                d <<= 1
        return acc

    def _shift_right(self, incl: Any, op: Callable, initial: Any) -> Any:
        """Turn an inclusive scan result into exclusive by sending the
        inclusive value to rank+1 (ring shift). The result folds in
        ``initial`` like the reference's ExPrefixSum: rank 0 returns
        ``initial``, rank r returns op(initial, incl[r-1])."""
        p = self.num_hosts
        r = self.my_rank
        with self._at("ex_prefix_sum"):
            if r + 1 < p:
                self.send_to(r + 1, incl)
            if r > 0:
                received = self.recv_from(r - 1)
                return received if initial is None \
                    else op(initial, received)
        return initial

    def ex_prefix_sum(self, value: Any, op: Callable = operator.add,
                      initial: Any = 0) -> Any:
        """Exclusive prefix sum (reference: ExPrefixSum, net/collective.hpp:165)."""
        incl = self.prefix_sum(value, op)
        return self._shift_right(incl, op, initial)

    def broadcast(self, value: Any, origin: int = 0) -> Any:
        """Binomial-tree broadcast (reference: BroadcastBinomialTree,
        net/collective.hpp:205)."""
        p = self.num_hosts
        if p == 1:
            return value
        # rotate ranks so origin is 0
        vr = (self.my_rank - origin) % p
        # binomial tree: parent = vr - lowbit(vr); children = vr + d for
        # powers of two d < lowbit(vr) (root: all d < p)
        lowbit = vr & -vr if vr != 0 else p
        with self._at("broadcast"):
            if vr != 0:
                value = self.recv_from(((vr - lowbit) + origin) % p)
            d = 1
            while d < lowbit and vr + d < p:
                self.send_to((vr + d + origin) % p, value)
                d <<= 1
        return value

    def all_gather(self, value: Any) -> List[Any]:
        """Bruck-style all-gather returning the list ordered by rank.

        Reference: AllGatherRecursiveDoublingPowerOfTwo / AllGatherBruck,
        net/collective.hpp:260,279. We implement Bruck (works for any p).
        """
        p = self.num_hosts
        r = self.my_rank
        items: List[Any] = [value]
        d = 1
        with self._at("all_gather"):
            while len(items) < p:
                cnt = min(d, p - len(items))
                self.send_to((r - d) % p, items[:cnt])
                items.extend(self.recv_from((r + d) % p))
                d <<= 1
        # Bruck leaves items rotated: items[i] belongs to rank (r + i) % p.
        out: List[Any] = [None] * p
        for i, it in enumerate(items):
            out[(r + i) % p] = it
        return out

    def reduce(self, value: Any, op: Callable = operator.add, root: int = 0) -> Optional[Any]:
        """Binomial-tree reduction to ``root``
        (reference: Reduce, net/collective.hpp:331)."""
        p = self.num_hosts
        vr = (self.my_rank - root) % p
        acc = value
        d = 1
        with self._at("reduce"):
            while d < p:
                if (vr & d) != 0:
                    self.send_to(((vr - d) + root) % p, acc)
                    return None
                if vr + d < p:
                    other = self.recv_from(((vr + d) + root) % p)
                    acc = op(acc, other)
                d <<= 1
        return acc if vr == 0 else None

    def all_reduce(self, value: Any, op: Callable = operator.add) -> Any:
        """All-reduce; hypercube for powers of two, elimination for the
        rest (reference: AllReduceHypercube net/collective.hpp:414 and
        the 3-2 elimination variant :459-548 — here the standard 2-1
        form: extras above the largest power of two fold into a partner
        first, the partners run the hypercube, and the extras get the
        result back: 2 extra rounds instead of a full
        reduce+broadcast)."""
        p = self.num_hosts
        r = self.my_rank
        pp = 1 << (p.bit_length() - 1)      # largest power of two <= p
        with self._at("all_reduce"):
            if pp == p:
                return self._hypercube_all_reduce(value, op, p, r)
            # ADJACENT ranks pair up (2i folds 2i+1), so the virtual-
            # rank order equals the global rank order and non-
            # commutative (associative) ops still combine left-to-right
            extras = p - pp
            if r < 2 * extras:
                if r % 2 == 1:           # eliminated: partner computes
                    self.send_to(r - 1, value)
                    return self.recv_from(r - 1)
                acc = op(value, self.recv_from(r + 1))
                vr = r // 2
            else:
                acc = value
                vr = r - extras

            def to_real(v: int) -> int:
                return 2 * v if v < extras else v + extras

            acc = self._hypercube_all_reduce(acc, op, pp, vr, to_real)
            if r < 2 * extras:               # fan the result back
                self.send_to(r + 1, acc)
        return acc

    def _hypercube_all_reduce(self, acc: Any, op: Callable, p: int,
                              r: int, to_real: Callable = None) -> Any:
        to_real = to_real or (lambda v: v)
        d = 1
        while d < p:
            peer = r ^ d
            # symmetric exchange; deterministic order avoids deadlock
            if r < peer:
                self.send_to(to_real(peer), acc)
                other = self.recv_from(to_real(peer))
            else:
                other = self.recv_from(to_real(peer))
                self.send_to(to_real(peer), acc)
            # keep rank order as operand order for non-commutative ops
            acc = op(acc, other) if r < peer else op(other, acc)
            d <<= 1
        return acc

    def barrier(self) -> None:
        self.all_reduce(0, operator.add)


def _cause_str(cause: Any) -> str:
    if isinstance(cause, BaseException):
        return f"{type(cause).__name__}: {cause}"
    return str(cause)


@contextlib.contextmanager
def poison_on_error(group: Optional[Group], what: str = ""):
    """Run a collective phase under the abort protocol: any error that
    escapes (except an abort we *received* — relaying those would ping-
    pong poison frames) is broadcast to every peer before re-raising.

    The no-op cases (group is None, single-host group) make the guard
    safe to wrap around code that also runs single-controller."""
    try:
        yield
    except ClusterAbort:
        raise
    except BaseException as e:
        if group is not None and group.num_hosts > 1:
            try:
                group.poison_peers(e)
            except Exception:
                pass                 # original error stays authoritative
        raise
