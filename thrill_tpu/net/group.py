"""Abstract point-to-point group and generic collective algorithms.

Equivalent of the reference's net::Group / net::Connection and the
templated collectives implemented generically over connections
(reference: thrill/net/group.hpp:47, net/connection.hpp:49,
net/collective.hpp:52-579). Like the reference, collective algorithms are
implemented *in the framework*, generically over any transport backend
(mock in-process queues now; TCP across hosts later), and auto-select by
group size: dissemination prefix-sum, binomial-tree broadcast,
recursive-doubling all-gather and hypercube all-reduce.

These host-level collectives form the *control plane* — small values,
blocking semantics. The bulk data plane on TPU is XLA collectives inside
jitted programs (see net/xla.py); this layer coordinates the Python hosts
around those device programs (multi-host bootstrap, scalar agreement,
barriers), the role MPI plays for jax.distributed.
"""

from __future__ import annotations

import abc
import contextlib
import operator
from typing import Any, Callable, List, Optional

from ..common import faults

#: magic key of a poison control frame (a plain dict so it passes the
#: non-executing wire codec unauthenticated)
POISON_KEY = "__thrill_tpu_poison__"


class ClusterAbort(ConnectionError):
    """A peer broadcast a poison frame: its ROOT CAUSE, not a local
    secondary symptom. ConnectionError subclass so existing dead-peer
    handling (tests, cleanup paths) treats an abort as fatal transport
    loss — but the retry policy classifies it permanent (never retry
    a coordinated shutdown)."""

    def __init__(self, origin: int, cause: str) -> None:
        super().__init__(
            f"cluster abort from rank {origin}: {cause}")
        self.origin = origin
        self.cause = cause


class Connection(abc.ABC):
    """Reliable ordered duplex message channel to one peer."""

    @abc.abstractmethod
    def send(self, obj: Any) -> None: ...

    @abc.abstractmethod
    def recv(self) -> Any: ...


class Group(abc.ABC):
    """A p-way clique of connections; my_rank in [0, num_hosts)."""

    def __init__(self, my_rank: int, num_hosts: int) -> None:
        self.my_rank = my_rank
        self._num_hosts = num_hosts
        # poison frames relay AT MOST ONCE per (origin, cause)
        # (transitivity without ping-pong, while a LATER unrelated
        # abort on a surviving group still relays): keys added by
        # poison_peers and by received poison frames
        self._poison_relayed: set = set()

    @property
    def num_hosts(self) -> int:
        return self._num_hosts

    @abc.abstractmethod
    def connection(self, peer: int) -> Connection: ...

    def send_to(self, peer: int, obj: Any) -> None:
        self.connection(peer).send(obj)

    def recv_from(self, peer: int) -> Any:
        """Receive one message; a poison control frame surfaces as
        :class:`ClusterAbort` carrying the originator's root cause
        (reference has no analog — a dead peer hangs its job until the
        runtime kills it, api/context.cpp:849-878)."""
        obj = self.connection(peer).recv()
        if isinstance(obj, dict) and POISON_KEY in obj:
            info = obj[POISON_KEY]
            origin = int(info.get("origin", peer))
            cause = str(info.get("cause", "unknown"))
            if (origin, cause) not in self._poison_relayed:
                # RELAY once before aborting: in tree/hypercube
                # collectives most ranks never recv from the origin
                # directly — without the relay they would block on a
                # healthy partner that already aborted and surface a
                # secondary 'peer closed' instead of the root cause
                try:
                    self.poison_peers(cause, origin=origin)
                except Exception:
                    pass
            raise ClusterAbort(origin, cause)
        return obj

    # ------------------------------------------------------------------
    # coordinated abort (poison control frames)
    # ------------------------------------------------------------------

    def poison_peers(self, cause: Any, origin: Optional[int] = None) -> int:
        """Best-effort broadcast of a poison frame to every peer.

        A worker hitting an unrecoverable error calls this before
        re-raising, so every peer blocked in a collective surfaces the
        ROOT CAUSE within its own recv deadline instead of a cascade of
        secondary timeouts; receivers relay once (recv_from), so ranks
        that never recv from the origin directly still get the cause.
        Returns the number of peers notified; failures to notify (the
        cause may be the transport itself) are swallowed — the
        caller's re-raise is the authoritative error. ``origin`` is
        set by relays to preserve the ORIGINATING rank.
        """
        org = self.my_rank if origin is None else origin
        self._poison_relayed.add((org, _cause_str(cause)))
        frame = {POISON_KEY: {"origin": org,
                              "cause": _cause_str(cause)}}
        notified = 0
        for peer in range(self.num_hosts):
            if peer == self.my_rank:
                continue
            try:
                # send only, never flush: a flush would wait on bulk
                # frames already queued to a DEAD peer and hang the
                # abort itself. Dispatcher-attached connections drain
                # the queued poison frame asynchronously; blocking
                # connections wrote it synchronously in send().
                self.connection(peer).send(frame)
                notified += 1
            except Exception:
                continue
        faults.note("abort", origin=self.my_rank, notified=notified,
                    cause=_cause_str(cause))
        return notified

    # ------------------------------------------------------------------
    # collectives (generic over connections; reference net/collective.hpp)
    # ------------------------------------------------------------------

    def prefix_sum(self, value: Any, op: Callable = operator.add) -> Any:
        """Dissemination ("doubling") inclusive prefix sum.

        Reference: PrefixSumDoubling, net/collective.hpp:52. O(log p)
        rounds; each round r exchanges with rank +/- 2^r.
        """
        p = self.num_hosts
        r = self.my_rank
        acc = value        # running sum of [r - 2^k + 1 .. r]
        d = 1
        while d < p:
            if r + d < p:
                self.send_to(r + d, acc)
            if r - d >= 0:
                received = self.recv_from(r - d)
                acc = op(received, acc)
            d <<= 1
        return acc

    def _shift_right(self, incl: Any, op: Callable, initial: Any) -> Any:
        """Turn an inclusive scan result into exclusive by sending the
        inclusive value to rank+1 (ring shift). The result folds in
        ``initial`` like the reference's ExPrefixSum: rank 0 returns
        ``initial``, rank r returns op(initial, incl[r-1])."""
        p = self.num_hosts
        r = self.my_rank
        if r + 1 < p:
            self.send_to(r + 1, incl)
        if r > 0:
            received = self.recv_from(r - 1)
            return received if initial is None else op(initial, received)
        return initial

    def ex_prefix_sum(self, value: Any, op: Callable = operator.add,
                      initial: Any = 0) -> Any:
        """Exclusive prefix sum (reference: ExPrefixSum, net/collective.hpp:165)."""
        incl = self.prefix_sum(value, op)
        return self._shift_right(incl, op, initial)

    def broadcast(self, value: Any, origin: int = 0) -> Any:
        """Binomial-tree broadcast (reference: BroadcastBinomialTree,
        net/collective.hpp:205)."""
        p = self.num_hosts
        if p == 1:
            return value
        # rotate ranks so origin is 0
        vr = (self.my_rank - origin) % p
        # binomial tree: parent = vr - lowbit(vr); children = vr + d for
        # powers of two d < lowbit(vr) (root: all d < p)
        lowbit = vr & -vr if vr != 0 else p
        if vr != 0:
            value = self.recv_from(((vr - lowbit) + origin) % p)
        d = 1
        while d < lowbit and vr + d < p:
            self.send_to((vr + d + origin) % p, value)
            d <<= 1
        return value

    def all_gather(self, value: Any) -> List[Any]:
        """Bruck-style all-gather returning the list ordered by rank.

        Reference: AllGatherRecursiveDoublingPowerOfTwo / AllGatherBruck,
        net/collective.hpp:260,279. We implement Bruck (works for any p).
        """
        p = self.num_hosts
        r = self.my_rank
        items: List[Any] = [value]
        d = 1
        while len(items) < p:
            cnt = min(d, p - len(items))
            self.send_to((r - d) % p, items[:cnt])
            items.extend(self.recv_from((r + d) % p))
            d <<= 1
        # Bruck leaves items rotated: items[i] belongs to rank (r + i) % p.
        out: List[Any] = [None] * p
        for i, it in enumerate(items):
            out[(r + i) % p] = it
        return out

    def reduce(self, value: Any, op: Callable = operator.add, root: int = 0) -> Optional[Any]:
        """Binomial-tree reduction to ``root``
        (reference: Reduce, net/collective.hpp:331)."""
        p = self.num_hosts
        vr = (self.my_rank - root) % p
        acc = value
        d = 1
        while d < p:
            if (vr & d) != 0:
                self.send_to(((vr - d) + root) % p, acc)
                return None
            if vr + d < p:
                other = self.recv_from(((vr + d) + root) % p)
                acc = op(acc, other)
            d <<= 1
        return acc if vr == 0 else None

    def all_reduce(self, value: Any, op: Callable = operator.add) -> Any:
        """All-reduce; hypercube for powers of two, elimination for the
        rest (reference: AllReduceHypercube net/collective.hpp:414 and
        the 3-2 elimination variant :459-548 — here the standard 2-1
        form: extras above the largest power of two fold into a partner
        first, the partners run the hypercube, and the extras get the
        result back: 2 extra rounds instead of a full
        reduce+broadcast)."""
        p = self.num_hosts
        r = self.my_rank
        pp = 1 << (p.bit_length() - 1)      # largest power of two <= p
        if pp == p:
            return self._hypercube_all_reduce(value, op, p, r)
        # ADJACENT ranks pair up (2i folds 2i+1), so the virtual-rank
        # order equals the global rank order and non-commutative
        # (associative) ops still combine left-to-right
        extras = p - pp
        if r < 2 * extras:
            if r % 2 == 1:                   # eliminated: partner computes
                self.send_to(r - 1, value)
                return self.recv_from(r - 1)
            acc = op(value, self.recv_from(r + 1))
            vr = r // 2
        else:
            acc = value
            vr = r - extras

        def to_real(v: int) -> int:
            return 2 * v if v < extras else v + extras

        acc = self._hypercube_all_reduce(acc, op, pp, vr, to_real)
        if r < 2 * extras:                   # fan the result back
            self.send_to(r + 1, acc)
        return acc

    def _hypercube_all_reduce(self, acc: Any, op: Callable, p: int,
                              r: int, to_real: Callable = None) -> Any:
        to_real = to_real or (lambda v: v)
        d = 1
        while d < p:
            peer = r ^ d
            # symmetric exchange; deterministic order avoids deadlock
            if r < peer:
                self.send_to(to_real(peer), acc)
                other = self.recv_from(to_real(peer))
            else:
                other = self.recv_from(to_real(peer))
                self.send_to(to_real(peer), acc)
            # keep rank order as operand order for non-commutative ops
            acc = op(acc, other) if r < peer else op(other, acc)
            d <<= 1
        return acc

    def barrier(self) -> None:
        self.all_reduce(0, operator.add)


def _cause_str(cause: Any) -> str:
    if isinstance(cause, BaseException):
        return f"{type(cause).__name__}: {cause}"
    return str(cause)


@contextlib.contextmanager
def poison_on_error(group: Optional[Group], what: str = ""):
    """Run a collective phase under the abort protocol: any error that
    escapes (except an abort we *received* — relaying those would ping-
    pong poison frames) is broadcast to every peer before re-raising.

    The no-op cases (group is None, single-host group) make the guard
    safe to wrap around code that also runs single-controller."""
    try:
        yield
    except ClusterAbort:
        raise
    except BaseException as e:
        if group is not None and group.num_hosts > 1:
            try:
                group.poison_peers(e)
            except Exception:
                pass                 # original error stays authoritative
        raise
