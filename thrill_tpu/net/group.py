"""Abstract point-to-point group and generic collective algorithms.

Equivalent of the reference's net::Group / net::Connection and the
templated collectives implemented generically over connections
(reference: thrill/net/group.hpp:47, net/connection.hpp:49,
net/collective.hpp:52-579). Like the reference, collective algorithms are
implemented *in the framework*, generically over any transport backend
(mock in-process queues now; TCP across hosts later), and auto-select by
group size: dissemination prefix-sum, binomial-tree broadcast,
recursive-doubling all-gather and hypercube all-reduce.

These host-level collectives form the *control plane* — small values,
blocking semantics. The bulk data plane on TPU is XLA collectives inside
jitted programs (see net/xla.py); this layer coordinates the Python hosts
around those device programs (multi-host bootstrap, scalar agreement,
barriers), the role MPI plays for jax.distributed.
"""

from __future__ import annotations

import abc
import contextlib
import operator
import os
import time
from typing import Any, Callable, List, Optional

from ..common import faults

#: magic key of a poison control frame (a plain dict so it passes the
#: non-executing wire codec unauthenticated)
POISON_KEY = "__thrill_tpu_poison__"

#: magic key of a heartbeat frame (net/heartbeat.py): liveness chatter
#: multiplexed over the same connections — transports discard it before
#: it can reach a collective's payload stream
HEARTBEAT_KEY = "__thrill_tpu_hb__"

#: magic key of a generation-barrier control frame: the marker a
#: healing rank sends each peer when it enters a new failure domain
#: (Context generation). Everything queued BEFORE the marker on the
#: ordered channel belongs to the aborted generation and is drained;
#: the marker itself is the "fresh-generation barrier"
GENERATION_KEY = "__thrill_tpu_gen__"

#: injectable hang: an armed fire at this site makes the next blocking
#: collective recv behave as if its deadline expired with no frame —
#: the watchdog's abort path runs for real, no actual wedged peer needed
_F_HANG = faults.declare("net.group.recv_hang")

#: injectable generation replay: an armed fire makes the next recv see
#: a PRIOR-generation poison frame first (as if a stale frame from an
#: aborted pipeline were still in flight) — the generation filter must
#: drop it and the collective must still complete
_F_STALE = faults.declare("net.group.stale_frame")

#: heartbeat-probe site (checked per heartbeat send, net/heartbeat.py)
F_HEARTBEAT = faults.declare("net.heartbeat",
                             exc=faults.InjectedConnectionError)

#: latency-injection site at every host-collective entry. Checked as
#: the PER-RANK name ``net.group.delay.r<rank>`` so a delay arm
#: (``net.group.delay.r1:delay=50ms:n=0``) slows exactly one rank —
#: the deterministic straggler the doctor's wait attribution pins
#: (common/doctor.py). Armed WITHOUT ``delay=`` it raises at
#: collective entry like any site (nothing sent yet — a clean abort).
_F_DELAY = faults.declare("net.group.delay")

#: elastic-mesh resize handshake (Group.resize / tcp.join_tcp_group):
#: fired before any membership mutation, so an injected failure leaves
#: the old membership intact — the generation settles among the
#: survivors and the NEXT resize attempt starts from a clean group
F_RESIZE = faults.declare("net.group.resize_handshake",
                          exc=faults.InjectedConnectionError)

#: orchestrated process-level relaunch (Context.resize_processes):
#: fired at the relaunch GATE — after the RESIZE epoch sealed, before
#: the resize marker commits and before any membership drains — so an
#: injected failure aborts the whole move with the old-W group fully
#: intact (the sealed W' epoch is inert: an old-W resume rejects it by
#: the workers gate) and a clean retry re-runs the identical move
F_RELAUNCH = faults.declare("net.group.relaunch",
                            exc=faults.InjectedConnectionError)


def resize_enabled() -> bool:
    """Elastic membership changes are on by default;
    ``THRILL_TPU_RESIZE=0`` pins W for the process lifetime (a caller
    asking anyway gets a loud RuntimeError, never a silent no-op)."""
    return os.environ.get("THRILL_TPU_RESIZE", "1") != "0"


def resize_timeout_s() -> float:
    """Budget for one membership change (join handshakes + the
    generation barrier on the new membership):
    ``THRILL_TPU_RESIZE_TIMEOUT_S``, default = the heal budget. Like
    the heal it MUST be bounded — waiting forever on a joiner that
    died mid-handshake is a hang, not patience."""
    v = os.environ.get("THRILL_TPU_RESIZE_TIMEOUT_S", "")
    try:
        t = float(v)
    except ValueError:
        return heal_timeout_s()
    return t if t > 0 else heal_timeout_s()


class CollectiveHangTimeout(TimeoutError):
    """A blocking collective recv exceeded THRILL_TPU_HANG_TIMEOUT_S
    with no frame from the peer: the collective is wedged. Raised by
    transports (tcp/mock); the Group watchdog converts it into a
    ClusterAbort naming the collective and the silent peer rank."""


def hang_timeout_s() -> Optional[float]:
    """Collective-recv watchdog deadline (None = watchdog off — the
    default: a healthy slow peer must never be declared hung unless
    the operator opted into a bound)."""
    v = os.environ.get("THRILL_TPU_HANG_TIMEOUT_S", "")
    try:
        t = float(v)
    except ValueError:
        return None
    return t if t > 0 else None


def heal_timeout_s() -> float:
    """Budget for one generation heal (barrier drain + reconnects):
    THRILL_TPU_HEAL_TIMEOUT_S, default 30s. Past it the heal itself
    fails and the abort escalates to the unrecoverable path. Unlike
    the watchdog knob, the heal MUST be bounded (an unbounded barrier
    against a dead peer is a hang) — a non-positive value is refused
    loudly and the default applies."""
    v = os.environ.get("THRILL_TPU_HEAL_TIMEOUT_S", "")
    try:
        t = float(v)
    except ValueError:
        return 30.0
    if t <= 0:
        global _WARNED_HEAL_TIMEOUT
        if not _WARNED_HEAL_TIMEOUT:
            _WARNED_HEAL_TIMEOUT = True
            import sys
            print("thrill_tpu.net: THRILL_TPU_HEAL_TIMEOUT_S must be "
                  "> 0 (the heal cannot be unbounded); using the "
                  "default 30s", file=sys.stderr)
        return 30.0
    return t


_WARNED_HEAL_TIMEOUT = False


class ClusterAbort(ConnectionError):
    """A peer broadcast a poison frame: its ROOT CAUSE, not a local
    secondary symptom. ConnectionError subclass so existing dead-peer
    handling (tests, cleanup paths) treats an abort as fatal transport
    loss — but the retry policy classifies it permanent (never retry
    a coordinated shutdown).

    ``generation`` scopes the abort to one pipeline run (Context
    failure domain); ``recoverable`` distinguishes pipeline-scoped
    verdicts (poison, hung collective, dropped link — the Context can
    heal and serve the next pipeline) from process-death verdicts
    (heartbeat-confirmed dead peer — only a supervised relaunch +
    resume recovers those)."""

    def __init__(self, origin: int, cause: str, generation: int = -1,
                 recoverable: bool = True) -> None:
        super().__init__(
            f"cluster abort from rank {origin}: {cause}")
        self.origin = origin
        self.cause = cause
        self.generation = generation
        self.recoverable = recoverable


class Connection(abc.ABC):
    """Reliable ordered duplex message channel to one peer."""

    @abc.abstractmethod
    def send(self, obj: Any) -> Optional[int]:
        """Send one message. Transports that serialize the payload
        return the serialized byte count (the wire truth, measured
        ONCE where the frame is encoded — data/multiplexer.py's
        byte accounting reads it instead of re-serializing); queue
        transports that pass objects by reference return None."""

    @abc.abstractmethod
    def recv(self) -> Any: ...

    def recv_deadline(self, deadline_s: float) -> Any:
        """Receive one message, raising :class:`CollectiveHangTimeout`
        after ``deadline_s`` with no complete frame. Transports without
        timed receives fall back to a plain blocking recv (the watchdog
        then covers only transports that implement it)."""
        return self.recv()

    def send_bounded(self, obj: Any, deadline_s: float) -> None:
        """Send with a bounded blocking time, raising TimeoutError on
        expiry. Used by the abort protocol: poisoning a peer whose
        socket buffer is full must not hang the aborting worker. The
        default delegates to plain send (queue-backed transports never
        block)."""
        self.send(obj)


class Group(abc.ABC):
    """A p-way clique of connections; my_rank in [0, num_hosts)."""

    def __init__(self, my_rank: int, num_hosts: int) -> None:
        self.my_rank = my_rank
        self._num_hosts = num_hosts
        # poison frames relay AT MOST ONCE per (origin, cause)
        # (transitivity without ping-pong, while a LATER unrelated
        # abort on a surviving group still relays): keys added by
        # poison_peers and by received poison frames
        self._poison_relayed: set = set()
        # failure detector state: which collective the caller is inside
        # (named in hang-abort causes), last heartbeat seen per peer,
        # and an abort latched by the background heartbeat monitor for
        # the main thread to surface at its next group operation
        self._collective_site: str = ""
        self._hb_last: dict = {}
        self._pending_abort: Optional[ClusterAbort] = None
        # failure-domain scope (Context generation): poison frames and
        # generation barriers carry it; frames tagged with an OLDER
        # generation are stale leftovers of an aborted pipeline and are
        # dropped instead of poisoning the healed group
        self.generation = 0
        self.stats_stale_dropped = 0
        # link repairs performed by _repair_connection (tcp reconnect)
        self.stats_reconnects = 0
        # newest generation-barrier marker seen per peer OUTSIDE a
        # barrier drain (a payload recv may consume one when this rank
        # missed the cluster's abort): the local barrier reads the
        # stash instead of waiting for a frame already consumed
        self._gen_markers: dict = {}
        # tracing spine (common/trace.py), attached by the Context:
        # every collective (_at) and generation heal becomes a span in
        # the "net" lane; None / disabled = no allocation
        self.tracer = None
        # performance doctor (common/doctor.py), attached by the
        # Context: every blocking collective recv records how long
        # this rank was blocked and on WHOM (per-peer arrival deltas
        # -> straggler attribution). None (THRILL_TPU_DOCTOR=0) = one
        # attribute read per recv, zero allocations
        self.doctor = None

    @property
    def num_hosts(self) -> int:
        return self._num_hosts

    @abc.abstractmethod
    def connection(self, peer: int) -> Connection: ...

    def send_to(self, peer: int, obj: Any) -> Optional[int]:
        self._check_pending_abort()
        return self.connection(peer).send(obj)

    @contextlib.contextmanager
    def _at(self, site: str):
        """Name the collective in flight so a hang-abort cause can say
        WHERE the group wedged, not just that it did — and, with the
        tracing spine attached, put every host collective on the "net"
        span lane (one hook covers prefix_sum/broadcast/all_gather/
        all_reduce/barrier and their nested forms)."""
        if self.num_hosts > 1 and faults.REGISTRY.active():
            # latency injection: a delay arm on this rank's site name
            # sleeps HERE, before the collective's first frame — the
            # peers observe the lateness as per-peer recv wait. The
            # detail key is ``at`` (NOT ``site``): detail fields merge
            # into the fault_injected record, and a ``site`` key would
            # clobber the fault-site name in the event stream.
            faults.check(f"net.group.delay.r{self.my_rank}", at=site)
        prev = self._collective_site
        self._collective_site = site
        tr = self.tracer
        sp = (tr.begin("net", site) if tr is not None and tr.enabled
              and self.num_hosts > 1 else None)
        try:
            yield
        except BaseException as e:
            if sp is not None:
                sp.attrs["error"] = repr(e)[:200]
            raise
        finally:
            if sp is not None:
                tr.end(sp)
            self._collective_site = prev

    def _check_pending_abort(self) -> None:
        ab = self._pending_abort
        if ab is not None:
            raise ab

    def mark_dead(self, peer: int, cause: str) -> None:
        """Failure-detector verdict (net/heartbeat.py): ``peer`` is
        unreachable. Latch an abort for the main thread, poison the
        surviving peers so the whole group converts to fast attributable
        aborts instead of a cascade of timeouts.

        The verdict is UNRECOVERABLE: a heartbeat-confirmed dead
        process cannot be healed by a new generation — only the
        supervised relaunch + checkpoint resume path recovers it
        (run-scripts/supervise.sh, api.RunSupervised)."""
        ab = ClusterAbort(self.my_rank, cause,
                          generation=self.generation, recoverable=False)
        if self._pending_abort is None or getattr(
                self._pending_abort, "recoverable", True):
            self._pending_abort = ab
        try:
            self.poison_peers(cause, unrecoverable=True)
        except Exception:
            pass

    def recv_from(self, peer: int) -> Any:
        """Receive one message; a poison control frame surfaces as
        :class:`ClusterAbort` carrying the originator's root cause
        (reference has no analog — a dead peer hangs its job until the
        runtime kills it, api/context.cpp:849-878).

        Collective watchdog: with ``THRILL_TPU_HANG_TIMEOUT_S`` set,
        a recv that sees no frame within the deadline poisons the
        group with a ClusterAbort naming the collective and the silent
        peer rank — a wedged collective becomes a fast, attributable
        abort a supervising re-launch can resume from."""
        self._check_pending_abort()
        deadline = hang_timeout_s()
        # the deadline is ABSOLUTE across heartbeat-filter iterations:
        # liveness chatter proves the peer process is alive but does
        # not excuse a wedged collective (same semantics as the tcp
        # transport's internal filter, TcpConnection._recv_msg)
        deadline_at = (None if deadline is None
                       else time.monotonic() + deadline)
        site = self._collective_site or "recv"
        injected_stale = False
        while True:
            try:
                obj = None
                if faults.REGISTRY.active():
                    try:
                        faults.check(_F_HANG, peer=peer, site=site)
                    except faults.InjectedFault:
                        raise CollectiveHangTimeout(
                            "injected wedge") from None
                    if not injected_stale:
                        try:
                            faults.check(_F_STALE, peer=peer, site=site)
                        except faults.InjectedFault:
                            # replay a prior-generation poison frame as
                            # if it were still in flight from an aborted
                            # pipeline: the filter below must drop it
                            # and the REAL frame arrives on the next
                            # loop pass
                            injected_stale = True
                            obj = {POISON_KEY: {
                                "origin": peer,
                                "cause": "injected stale replay",
                                "gen": self.generation - 1}}
                if obj is None:
                    conn = self.connection(peer)
                    doc = self.doctor
                    if doc is not None:
                        # lock-free attribute reads (benign race): the
                        # background-I/O busy delta across the blocked
                        # window caps the wait's I/O attribution
                        from ..common.iostats import IO as _io
                        t0 = time.perf_counter()
                        io0 = _io.io_busy_s
                    if deadline_at is None:
                        obj = conn.recv()
                    else:
                        remaining = deadline_at - time.monotonic()
                        if remaining <= 0:
                            raise CollectiveHangTimeout("deadline spent")
                        obj = conn.recv_deadline(remaining)
                    if doc is not None:
                        doc.record_wait(site, peer,
                                        time.perf_counter() - t0,
                                        io_s=_io.io_busy_s - io0)
            except CollectiveHangTimeout:
                cause = (f"hang at {site}: rank {self.my_rank} "
                         f"received no frame from rank {peer} within "
                         f"{deadline if deadline is not None else 0}s "
                         f"(THRILL_TPU_HANG_TIMEOUT_S)")
                try:
                    self.poison_peers(cause)
                except Exception:
                    pass
                raise ClusterAbort(self.my_rank, cause,
                                   generation=self.generation) from None
            if isinstance(obj, dict) and HEARTBEAT_KEY in obj:
                # liveness chatter from a transport without its own
                # filter (mock queues): note it, keep waiting for the
                # payload on the SAME deadline budget
                self._hb_last[peer] = time.monotonic()
                continue
            if isinstance(obj, dict) and GENERATION_KEY in obj:
                info = obj[GENERATION_KEY]
                g = int(info.get("gen", 0))
                if g > self.generation:
                    # the peer healed into a NEWER failure domain:
                    # this rank MISSED the cluster's abort (its poison
                    # frame was lost and the watchdog is off). Stash
                    # the marker — our own barrier must not wait for a
                    # frame we just consumed — and abort the current
                    # collective so the pipeline handler heals and
                    # meets the peer at the barrier.
                    self._gen_markers[peer] = max(
                        self._gen_markers.get(peer, 0), g)
                    origin = int(info.get("rank", peer))
                    raise ClusterAbort(
                        origin,
                        f"peer rank {origin} healed to generation "
                        f"{g} while this rank was still in generation "
                        f"{self.generation} — the cluster aborted "
                        f"without local notice",
                        generation=self.generation)
                # a LATE marker from a heal this rank already
                # completed: control chatter, never payload — drop it
                self._drop_stale(peer, obj)
                continue
            if isinstance(obj, dict) and POISON_KEY in obj:
                info = obj[POISON_KEY]
                gen = int(info.get("gen", self.generation))
                if gen < self.generation:
                    # stale poison of an ALREADY-HEALED generation (a
                    # slow peer's abort frame, or a replayed frame):
                    # the failure domain it belongs to is gone — drop
                    # it instead of killing the healed group
                    self._drop_stale(peer, obj)
                    continue
                origin = int(info.get("origin", peer))
                cause = str(info.get("cause", "unknown"))
                recoverable = not bool(info.get("unrecoverable", False))
                if (origin, cause) not in self._poison_relayed:
                    # RELAY once before aborting: in tree/hypercube
                    # collectives most ranks never recv from the origin
                    # directly — without the relay they would block on a
                    # healthy partner that already aborted and surface a
                    # secondary 'peer closed' instead of the root cause
                    try:
                        self.poison_peers(cause, origin=origin,
                                          unrecoverable=not recoverable)
                    except Exception:
                        pass
                raise ClusterAbort(origin, cause, generation=gen,
                                   recoverable=recoverable)
            return obj

    def _drop_stale(self, peer: int, obj: Any) -> None:
        """Count + log one dropped prior-generation frame."""
        self.stats_stale_dropped += 1
        info = next(iter(obj.values())) if obj else {}
        faults.note("recovery", what="net.stale_frame_dropped",
                    _quiet=self.stats_stale_dropped > 8,
                    peer=peer, gen=self.generation,
                    frame_gen=(info or {}).get("gen"))

    # ------------------------------------------------------------------
    # any-source receive (MixStream consume-first-arrival)
    # ------------------------------------------------------------------

    @property
    def supports_recv_any(self) -> bool:
        """Whether :meth:`recv_any` can genuinely pick whichever peer's
        frame lands first. Transports without a readiness probe fall
        back to the fixed per-peer schedule (the pre-any-source
        behavior) — callers need no special-casing either way."""
        return False

    def _pick_ready_peer(self, peers: List[int]) -> int:
        """Transport hook: block until SOME peer in ``peers`` has a
        frame pending and return its rank. The default (no readiness
        probe) returns the first peer — recv_any then degrades to the
        fixed schedule. Implementations should bound their wait by
        :func:`hang_timeout_s` and return any peer on expiry so
        ``recv_from``'s own watchdog produces the attributable abort."""
        return peers[0]

    def recv_any(self, peers: List[int]) -> tuple:
        """Receive one message from whichever of ``peers`` delivers
        first; returns ``(peer, obj)``. Poison frames, heartbeat
        filtering and the collective watchdog behave exactly as in
        :meth:`recv_from` (the pick only chooses WHO to drain; the
        actual receive goes through the same guarded path)."""
        self._check_pending_abort()
        peer = self._pick_ready_peer(list(peers))
        return peer, self.recv_from(peer)

    # ------------------------------------------------------------------
    # coordinated abort (poison control frames)
    # ------------------------------------------------------------------

    def poison_peers(self, cause: Any, origin: Optional[int] = None,
                     unrecoverable: bool = False) -> int:
        """Best-effort broadcast of a poison frame to every peer.

        A worker hitting an unrecoverable error calls this before
        re-raising, so every peer blocked in a collective surfaces the
        ROOT CAUSE within its own recv deadline instead of a cascade of
        secondary timeouts; receivers relay once (recv_from), so ranks
        that never recv from the origin directly still get the cause.
        Returns the number of peers notified; failures to notify (the
        cause may be the transport itself) are swallowed — the
        caller's re-raise is the authoritative error. ``origin`` is
        set by relays to preserve the ORIGINATING rank.

        The frame is tagged with the CURRENT generation so a healed
        group drops it if it arrives after the failure domain it
        belongs to was torn down; ``unrecoverable`` marks process-death
        verdicts (mark_dead) that no heal may clear.
        """
        org = self.my_rank if origin is None else origin
        self._poison_relayed.add((org, _cause_str(cause)))
        frame = {POISON_KEY: {"origin": org,
                              "cause": _cause_str(cause),
                              "gen": self.generation,
                              **({"unrecoverable": True}
                                 if unrecoverable else {})}}
        # bounded send deadline (common/timeouts.py load scaling): a
        # peer that stopped draining its socket (wedged, descheduled,
        # dying) can have a FULL kernel buffer — a blocking send of the
        # poison frame would then hang the aborting worker itself.
        # Past the deadline that peer is skipped; it still learns the
        # cause from another rank's relay or its own recv deadline.
        from ..common.timeouts import scaled
        deadline = min(scaled(1.0), 5.0)
        notified = 0
        for peer in range(self.num_hosts):
            if peer == self.my_rank:
                continue
            try:
                # send only, never flush: a flush would wait on bulk
                # frames already queued to a DEAD peer and hang the
                # abort itself. Dispatcher-attached connections drain
                # the queued poison frame asynchronously; blocking
                # connections wrote it synchronously in send_bounded().
                self.connection(peer).send_bounded(frame, deadline)
                notified += 1
            except Exception:
                continue
        faults.note("abort", origin=self.my_rank, notified=notified,
                    cause=_cause_str(cause))
        return notified

    # ------------------------------------------------------------------
    # generation-scoped failure domains (heal after a pipeline abort)
    # ------------------------------------------------------------------

    def _heal_transport(self, deadline_at: float) -> None:
        """Proactively repair links already KNOWN broken before the
        generation barrier runs (tcp overrides: reconnect + session
        handshake). Base transports have nothing to repair."""

    def _repair_connection(self, peer: int, deadline_at: float,
                           cause: Optional[BaseException] = None) -> bool:
        """Transport hook: try to re-establish the link to ``peer``
        after a transport error surfaced mid-barrier. Returns True when
        the link is usable again (the barrier retries), False when this
        transport cannot reconnect (the heal fails and the abort
        escalates to the unrecoverable path)."""
        return False

    def link_repairable(self, peer: int) -> bool:
        """Is the link to ``peer`` in a DOWN-BUT-REPAIRABLE state (a
        dropped stream a generation heal could reconnect)? The
        heartbeat monitor consults this before ruling a peer dead: a
        repairable link drop is a PIPELINE-scoped event owned by the
        heal (whose dial budget still produces the dead-process verdict
        when nobody answers) — declaring it a dead process here would
        defeat the heal. Base transports have no repair path."""
        return False

    def begin_generation(self, gen: int) -> int:
        """Enter failure domain ``gen`` after a pipeline abort: clear
        the pipeline-scoped abort latch, repair dropped links (tcp),
        send every peer a generation-barrier marker and DRAIN each
        inbound channel up to the peer's marker — everything queued
        before it (bulk frames of the aborted exchange, late poison,
        stray collective payloads) belongs to the dead generation and
        is discarded. On return the group is exactly as quiet as a
        freshly bootstrapped one.

        Raises the latched abort when it is unrecoverable (heartbeat
        dead-peer verdict), :class:`CollectiveHangTimeout` when a peer
        never delivers its marker within THRILL_TPU_HEAL_TIMEOUT_S,
        and :class:`ClusterAbort` when a CURRENT-generation poison
        arrives mid-drain (a new failure during the heal itself).
        Returns the number of stale frames dropped."""
        tr = self.tracer
        if tr is None or not tr.enabled:
            return self._begin_generation(gen)
        with tr.span("net", "heal", gen=int(gen)) as sp:
            dropped = self._begin_generation(gen)
            sp.attrs["settled_gen"] = self.generation
            sp.attrs["stale_dropped"] = dropped
            sp.attrs["reconnects"] = self.stats_reconnects
            return dropped

    def _begin_generation(self, gen: int) -> int:
        gen = int(gen)
        if self._gen_markers:
            # ADOPT a newer generation announced by peers whose heal
            # this rank missed: the barrier only completes when every
            # rank targets the same id
            gen = max(gen, max(self._gen_markers.values()))
        ab = self._pending_abort
        if ab is not None:
            if (getattr(ab, "recoverable", True)
                    and getattr(ab, "generation", -1) < gen):
                # pipeline-scoped verdict of the aborted generation:
                # the new domain starts clean
                self._pending_abort = None
            else:
                raise ab
        self._poison_relayed.clear()
        self.generation = gen
        dropped = 0
        if self.num_hosts > 1:
            deadline_at = time.monotonic() + heal_timeout_s()
            self._heal_transport(deadline_at)
            frame = {GENERATION_KEY: {"gen": self.generation,
                                      "rank": self.my_rank}}
            for peer in range(self.num_hosts):
                if peer == self.my_rank:
                    continue
                while True:
                    try:
                        dropped += self._gen_barrier_peer(
                            peer, frame, deadline_at)
                        break
                    except (ClusterAbort, CollectiveHangTimeout):
                        raise
                    except (ConnectionError, OSError) as e:
                        if (isinstance(e, TimeoutError)
                                and not isinstance(e, ConnectionError)):
                            # bounded-send expiry with nothing written:
                            # the stream is INTACT (the peer is just
                            # slow to drain) — retry the barrier within
                            # the heal deadline instead of dropping a
                            # healthy authenticated link (duplicate
                            # markers are filtered on receipt)
                            if time.monotonic() >= deadline_at:
                                raise
                            continue
                        # the link itself died (or was already dead on
                        # this side): give the transport one repair
                        # attempt per error, bounded by the heal
                        # deadline
                        if (time.monotonic() >= deadline_at
                                or not self._repair_connection(
                                    peer, deadline_at, e)):
                            raise
        # markers at or below the settled generation are used up; only
        # evidence of an even NEWER domain (a concurrent further heal)
        # survives for the next barrier
        self._gen_markers = {p: g for p, g in self._gen_markers.items()
                             if g > self.generation}
        self.stats_stale_dropped += dropped
        if dropped:
            faults.note("recovery", what="net.generation_drain",
                        gen=self.generation, dropped=dropped)
        return dropped

    def _gen_barrier_peer(self, peer: int, frame: dict,
                          deadline_at: float) -> int:
        """Send ``peer`` the generation marker, then drain its channel
        up to the peer's own marker. Returns stale frames dropped."""
        conn = self.connection(peer)
        conn.send_bounded(frame,
                          min(max(deadline_at - time.monotonic(), 0.1),
                              5.0))
        if self._gen_markers.get(peer, 0) >= self.generation:
            # the peer's marker was already consumed by a payload recv
            # (the missed-abort path): the barrier is satisfied
            return 0
        dropped = 0
        while True:
            remaining = deadline_at - time.monotonic()
            if remaining <= 0:
                raise CollectiveHangTimeout(
                    f"generation barrier: no gen-{self.generation} "
                    f"marker from rank {peer} within "
                    f"{heal_timeout_s()}s (THRILL_TPU_HEAL_TIMEOUT_S)")
            obj = conn.recv_deadline(remaining)
            if isinstance(obj, dict) and HEARTBEAT_KEY in obj:
                self._hb_last[peer] = time.monotonic()
                continue
            if isinstance(obj, dict) and GENERATION_KEY in obj:
                g = int(obj[GENERATION_KEY].get("gen", 0))
                if g >= self.generation:
                    return dropped          # barrier reached
                dropped += 1                # stale marker of an older heal
                continue
            if isinstance(obj, dict) and POISON_KEY in obj:
                info = obj[POISON_KEY]
                g = int(info.get("gen", self.generation))
                if g >= self.generation:
                    # a NEW failure arrived during the heal itself
                    raise ClusterAbort(
                        int(info.get("origin", peer)),
                        str(info.get("cause", "unknown")),
                        generation=g,
                        recoverable=not bool(info.get("unrecoverable",
                                                      False)))
                dropped += 1
                continue
            dropped += 1                    # pre-abort payload frame

    # ------------------------------------------------------------------
    # elastic membership (resize at a generation boundary)
    # ------------------------------------------------------------------

    def _grow_transport(self, new_num_hosts: int, gen: int,
                        deadline_at: float) -> None:
        """Admit ranks ``[num_hosts, new_num_hosts)``: establish an
        authenticated connection to each joiner (transport-specific;
        tcp accepts the joiner's dial on this rank's own hostlist
        port, mock extends the queue matrix). Must NOT mutate
        ``_num_hosts`` — the caller commits membership only after
        every joiner connected."""
        raise NotImplementedError(
            f"{type(self).__name__} cannot admit new ranks")

    def _shrink_transport(self, new_num_hosts: int) -> None:
        """Drop connections to ranks ``>= new_num_hosts`` (they have
        drained and left, or were already dead). Default: nothing —
        queue-backed transports just stop addressing them."""

    def resize(self, new_num_hosts: int, gen: int) -> None:
        """Collective membership change: every CURRENT rank (including
        the ranks about to leave) calls this in lockstep with the same
        ``(new_num_hosts, gen)``. A JOINING rank does not call it — it
        enters through the transport's join constructor
        (``tcp.join_tcp_group`` / ``MockNetwork.grow``) and then runs
        ``begin_generation(gen)`` like everyone else.

        Grow: admit the joiners, commit the new membership, then
        barrier on it — the joiners' first collective is the
        generation barrier itself. A failed admission (joiner died
        mid-handshake, injected ``net.group.resize_handshake``) rolls
        the membership back and settles ``gen`` among the old ranks,
        so the group is healed and the next resize attempt starts
        clean.

        Shrink: barrier on the OLD membership first — the departing
        rank's in-flight frames drain behind the existing generation
        barrier — then the survivors drop the departed links. A
        departing peer that is ALREADY DEAD is skipped with a note:
        scale-down of a dead peer is the graceful form of the
        dead-peer verdict (it was leaving anyway). A departing rank
        returns with its frames drained; the caller closes the group.
        """
        new_w = int(new_num_hosts)
        old_w = self.num_hosts
        gen = int(gen)
        if not resize_enabled():
            raise RuntimeError(
                "elastic resize is disabled (THRILL_TPU_RESIZE=0); "
                "the worker count is pinned for the process lifetime")
        if new_w < 1:
            raise ValueError(f"cannot resize to {new_w} hosts")
        faults.check(F_RESIZE, old=old_w, new=new_w, gen=gen,
                     rank=self.my_rank)
        if new_w == old_w:
            self.begin_generation(gen)
            return
        if new_w > old_w:
            deadline_at = time.monotonic() + resize_timeout_s()
            try:
                self._grow_transport(new_w, gen, deadline_at)
                self._num_hosts = new_w
                self.begin_generation(gen)
            except (ConnectionError, OSError, TimeoutError):
                # roll back: drop whatever joiner links landed, settle
                # the generation among the OLD membership so a retry
                # (or plain continued traffic) starts from a healed
                # group instead of a half-admitted one
                self._num_hosts = old_w
                self._shrink_transport(old_w)
                faults.note("recovery", what="net.resize_rollback",
                            old=old_w, new=new_w, gen=gen)
                self.begin_generation(gen)
                raise
            faults.note("recovery", what="net.resize", old=old_w,
                        new=new_w, gen=gen, _quiet=True)
            return
        # -- shrink --------------------------------------------------
        departing = frozenset(range(new_w, old_w))
        self._resize_barrier(gen, lenient=departing)
        if self.my_rank in departing:
            return                  # drained; caller closes the group
        self._num_hosts = new_w
        self._shrink_transport(new_w)
        self._hb_last = {p: t for p, t in self._hb_last.items()
                         if p < new_w}
        faults.note("recovery", what="net.resize", old=old_w,
                    new=new_w, gen=gen, _quiet=True)

    def prepare_relaunch(self, new_num_hosts: int, gen: int) -> None:
        """The net-layer step of an orchestrated process-level resize
        (``Context.resize_processes``): agree the group is ready to be
        torn down and relaunched at ``new_num_hosts``.

        Collective over the CURRENT membership, and deliberately
        mutation-free: every process — survivor, departing, and (for
        a grow) the current ranks the joiners will meet again — exits
        for the supervised relaunch right after the move commits, so
        the only job here is agreement that every current rank
        reached the relaunch point. Shrink settles the generation
        through the PR-16 lenient departing-peer barrier (an
        already-dead departing rank must not wedge the survivors'
        move); grow is a plain generation barrier (the joiners do not
        exist until the supervisor spawns them — admission happens in
        the relaunched processes' authenticated bootstrap). Because
        nothing mutates, the marker commit that follows still runs
        its cross-rank barrier over the intact old membership, and an
        injected failure at ANY point before the marker leaves the
        old-W group exactly as it was. The ``net.group.relaunch``
        fault site fires FIRST — the nothing-mutated proof for this
        step."""
        new_w = int(new_num_hosts)
        old_w = self.num_hosts
        gen = int(gen)
        if not resize_enabled():
            raise RuntimeError(
                "elastic resize is disabled (THRILL_TPU_RESIZE=0); "
                "the worker count is pinned for the process lifetime")
        faults.check(F_RELAUNCH, old=old_w, new=new_w,
                     gen=gen, rank=self.my_rank)
        if new_w < old_w:
            departing = frozenset(range(new_w, old_w))
            self._resize_barrier(gen, lenient=departing)
        else:
            self.begin_generation(gen)
        faults.note("recovery", what="net.relaunch_ready",
                    old=old_w, new=new_w, gen=gen, _quiet=True)

    def _resize_barrier(self, gen: int, lenient: frozenset) -> int:
        """Generation barrier over the CURRENT membership in which a
        barrier failure against a ``lenient`` peer (the departing set)
        is skipped instead of escalated — an unreachable peer that is
        leaving anyway must not wedge the survivors. Mirrors
        :meth:`_begin_generation` otherwise (marker exchange, stale
        drain, pending-abort latch, repair-retry for survivors)."""
        gen = int(gen)
        if self._gen_markers:
            gen = max(gen, max(self._gen_markers.values()))
        ab = self._pending_abort
        if ab is not None:
            if (getattr(ab, "recoverable", True)
                    and getattr(ab, "generation", -1) < gen):
                self._pending_abort = None
            else:
                raise ab
        self._poison_relayed.clear()
        self.generation = gen
        dropped = 0
        if self.num_hosts > 1:
            deadline_at = time.monotonic() + resize_timeout_s()
            frame = {GENERATION_KEY: {"gen": self.generation,
                                      "rank": self.my_rank}}
            for peer in range(self.num_hosts):
                if peer == self.my_rank:
                    continue
                while True:
                    try:
                        dropped += self._gen_barrier_peer(
                            peer, frame, deadline_at)
                        break
                    except ClusterAbort:
                        raise
                    except (CollectiveHangTimeout, ConnectionError,
                            OSError) as e:
                        if peer in lenient:
                            # already-dead departing peer: the
                            # graceful form of the dead-peer verdict
                            faults.note("recovery",
                                        what="net.resize_skip_dead",
                                        peer=peer, gen=gen,
                                        error=repr(e)[:200])
                            break
                        if isinstance(e, CollectiveHangTimeout):
                            raise
                        if (time.monotonic() >= deadline_at
                                or not self._repair_connection(
                                    peer, deadline_at, e)):
                            raise
        self._gen_markers = {p: g for p, g in self._gen_markers.items()
                             if g > self.generation}
        self.stats_stale_dropped += dropped
        if dropped:
            faults.note("recovery", what="net.generation_drain",
                        gen=self.generation, dropped=dropped)
        return dropped

    # ------------------------------------------------------------------
    # collectives (generic over connections; reference net/collective.hpp)
    # ------------------------------------------------------------------

    def prefix_sum(self, value: Any, op: Callable = operator.add) -> Any:
        """Dissemination ("doubling") inclusive prefix sum.

        Reference: PrefixSumDoubling, net/collective.hpp:52. O(log p)
        rounds; each round r exchanges with rank +/- 2^r.
        """
        p = self.num_hosts
        r = self.my_rank
        acc = value        # running sum of [r - 2^k + 1 .. r]
        d = 1
        with self._at("prefix_sum"):
            while d < p:
                if r + d < p:
                    self.send_to(r + d, acc)
                if r - d >= 0:
                    received = self.recv_from(r - d)
                    acc = op(received, acc)
                d <<= 1
        return acc

    def _shift_right(self, incl: Any, op: Callable, initial: Any) -> Any:
        """Turn an inclusive scan result into exclusive by sending the
        inclusive value to rank+1 (ring shift). The result folds in
        ``initial`` like the reference's ExPrefixSum: rank 0 returns
        ``initial``, rank r returns op(initial, incl[r-1])."""
        p = self.num_hosts
        r = self.my_rank
        with self._at("ex_prefix_sum"):
            if r + 1 < p:
                self.send_to(r + 1, incl)
            if r > 0:
                received = self.recv_from(r - 1)
                return received if initial is None \
                    else op(initial, received)
        return initial

    def ex_prefix_sum(self, value: Any, op: Callable = operator.add,
                      initial: Any = 0) -> Any:
        """Exclusive prefix sum (reference: ExPrefixSum, net/collective.hpp:165)."""
        incl = self.prefix_sum(value, op)
        return self._shift_right(incl, op, initial)

    def broadcast(self, value: Any, origin: int = 0) -> Any:
        """Binomial-tree broadcast (reference: BroadcastBinomialTree,
        net/collective.hpp:205)."""
        p = self.num_hosts
        if p == 1:
            return value
        # rotate ranks so origin is 0
        vr = (self.my_rank - origin) % p
        # binomial tree: parent = vr - lowbit(vr); children = vr + d for
        # powers of two d < lowbit(vr) (root: all d < p)
        lowbit = vr & -vr if vr != 0 else p
        with self._at("broadcast"):
            if vr != 0:
                value = self.recv_from(((vr - lowbit) + origin) % p)
            d = 1
            while d < lowbit and vr + d < p:
                self.send_to((vr + d + origin) % p, value)
                d <<= 1
        return value

    def all_gather(self, value: Any) -> List[Any]:
        """Bruck-style all-gather returning the list ordered by rank.

        Reference: AllGatherRecursiveDoublingPowerOfTwo / AllGatherBruck,
        net/collective.hpp:260,279. We implement Bruck (works for any p).
        """
        p = self.num_hosts
        r = self.my_rank
        items: List[Any] = [value]
        d = 1
        with self._at("all_gather"):
            while len(items) < p:
                cnt = min(d, p - len(items))
                self.send_to((r - d) % p, items[:cnt])
                items.extend(self.recv_from((r + d) % p))
                d <<= 1
        # Bruck leaves items rotated: items[i] belongs to rank (r + i) % p.
        out: List[Any] = [None] * p
        for i, it in enumerate(items):
            out[(r + i) % p] = it
        return out

    def reduce(self, value: Any, op: Callable = operator.add, root: int = 0) -> Optional[Any]:
        """Binomial-tree reduction to ``root``
        (reference: Reduce, net/collective.hpp:331)."""
        p = self.num_hosts
        vr = (self.my_rank - root) % p
        acc = value
        d = 1
        with self._at("reduce"):
            while d < p:
                if (vr & d) != 0:
                    self.send_to(((vr - d) + root) % p, acc)
                    return None
                if vr + d < p:
                    other = self.recv_from(((vr + d) + root) % p)
                    acc = op(acc, other)
                d <<= 1
        return acc if vr == 0 else None

    def all_reduce(self, value: Any, op: Callable = operator.add) -> Any:
        """All-reduce; hypercube for powers of two, elimination for the
        rest (reference: AllReduceHypercube net/collective.hpp:414 and
        the 3-2 elimination variant :459-548 — here the standard 2-1
        form: extras above the largest power of two fold into a partner
        first, the partners run the hypercube, and the extras get the
        result back: 2 extra rounds instead of a full
        reduce+broadcast)."""
        p = self.num_hosts
        r = self.my_rank
        pp = 1 << (p.bit_length() - 1)      # largest power of two <= p
        with self._at("all_reduce"):
            if pp == p:
                return self._hypercube_all_reduce(value, op, p, r)
            # ADJACENT ranks pair up (2i folds 2i+1), so the virtual-
            # rank order equals the global rank order and non-
            # commutative (associative) ops still combine left-to-right
            extras = p - pp
            if r < 2 * extras:
                if r % 2 == 1:           # eliminated: partner computes
                    self.send_to(r - 1, value)
                    return self.recv_from(r - 1)
                acc = op(value, self.recv_from(r + 1))
                vr = r // 2
            else:
                acc = value
                vr = r - extras

            def to_real(v: int) -> int:
                return 2 * v if v < extras else v + extras

            acc = self._hypercube_all_reduce(acc, op, pp, vr, to_real)
            if r < 2 * extras:               # fan the result back
                self.send_to(r + 1, acc)
        return acc

    def _hypercube_all_reduce(self, acc: Any, op: Callable, p: int,
                              r: int, to_real: Callable = None) -> Any:
        to_real = to_real or (lambda v: v)
        d = 1
        while d < p:
            peer = r ^ d
            # symmetric exchange; deterministic order avoids deadlock
            if r < peer:
                self.send_to(to_real(peer), acc)
                other = self.recv_from(to_real(peer))
            else:
                other = self.recv_from(to_real(peer))
                self.send_to(to_real(peer), acc)
            # keep rank order as operand order for non-commutative ops
            acc = op(acc, other) if r < peer else op(other, acc)
            d <<= 1
        return acc

    def barrier(self) -> None:
        self.all_reduce(0, operator.add)


def _cause_str(cause: Any) -> str:
    if isinstance(cause, BaseException):
        return f"{type(cause).__name__}: {cause}"
    return str(cause)


@contextlib.contextmanager
def poison_on_error(group: Optional[Group], what: str = ""):
    """Run a collective phase under the abort protocol: any error that
    escapes (except an abort we *received* — relaying those would ping-
    pong poison frames) is broadcast to every peer before re-raising.

    The no-op cases (group is None, single-host group) make the guard
    safe to wrap around code that also runs single-controller."""
    try:
        yield
    except ClusterAbort:
        raise
    except BaseException as e:
        if group is not None and group.num_hosts > 1:
            try:
                group.poison_peers(e)
            except Exception:
                pass                 # original error stays authoritative
        raise
