"""Worker-level collectives: the FlowControlChannel equivalent.

The reference exposes worker-thread-level collectives as ``ctx.net``
(reference: thrill/net/flow_control_channel.hpp:48 — PrefixSum :308,
ExPrefixSum :329, ExPrefixSumTotal :351, Broadcast :424, AllGather :477,
Reduce :543, AllReduce :599, Predecessor :653, Barrier :780).

Here there are two implementations behind one concept:

* ``FlowControlChannel`` — true SPMD: one instance per worker thread,
  collectives run over a net.Group backend (mock queues in-process, TCP
  across hosts). Used by the threaded test harness and by host-side
  coordination in multi-controller deployments.

* ``LocalFlowControl`` — single-controller: the driver holds all
  per-worker values in a list and computes the collective result
  directly. This is what the host execution path of DIA operators uses;
  on the device path the same operations lower to XLA collectives
  (psum / cumulative sums / ppermute) inside jitted programs instead.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, List, Sequence

from .group import Group


class FlowControlChannel:
    """Per-worker collectives over a Group (SPMD flavor)."""

    def __init__(self, group: Group) -> None:
        self.group = group

    @property
    def my_rank(self) -> int:
        return self.group.my_rank

    @property
    def num_workers(self) -> int:
        return self.group.num_hosts

    def prefix_sum(self, value: Any, op: Callable = operator.add) -> Any:
        return self.group.prefix_sum(value, op)

    def ex_prefix_sum(self, value: Any, op: Callable = operator.add,
                      initial: Any = 0) -> Any:
        return self.group.ex_prefix_sum(value, op, initial)

    def ex_prefix_sum_total(self, value: Any, op: Callable = operator.add,
                            initial: Any = 0):
        """Exclusive prefix sum plus the global total, in one pass.

        Reference: ExPrefixSumTotal, net/flow_control_channel.hpp:351 —
        the workhorse of Sort/Zip size negotiation.
        """
        excl = self.group.ex_prefix_sum(value, op, initial)
        incl = op(excl, value)
        total = self.group.broadcast(incl, origin=self.num_workers - 1)
        return excl, total

    def broadcast(self, value: Any, origin: int = 0) -> Any:
        return self.group.broadcast(value, origin)

    def all_gather(self, value: Any) -> List[Any]:
        return self.group.all_gather(value)

    def reduce(self, value: Any, op: Callable = operator.add, root: int = 0):
        return self.group.reduce(value, op, root)

    def all_reduce(self, value: Any, op: Callable = operator.add) -> Any:
        return self.group.all_reduce(value, op)

    def predecessor(self, k: int, items: Sequence[Any]) -> List[Any]:
        """Receive the last <= k items of the preceding workers.

        Sequential ring pass like the reference's Predecessor
        (net/flow_control_channel.hpp:653), used by Window to fetch the
        k-1 items preceding each worker's range.
        """
        r = self.my_rank
        p = self.num_workers
        received: List[Any] = []
        if r > 0:
            received = self.group.recv_from(r - 1)
        if r + 1 < p:
            chain = received + list(items)
            self.group.send_to(r + 1, chain[-k:] if k > 0 else [])
        return received

    def barrier(self) -> None:
        self.group.barrier()


class LocalFlowControl:
    """Single-controller implementation with a global view.

    Every method takes the per-worker values as a list of length W and
    returns per-worker results, so host-path DIA operators can express
    the same communication structure as the reference without threads.
    """

    def __init__(self, num_workers: int) -> None:
        self.num_workers = num_workers

    def prefix_sum(self, values: Sequence[Any], op: Callable = operator.add) -> List[Any]:
        out: List[Any] = []
        acc = None
        for v in values:
            acc = v if acc is None else op(acc, v)
            out.append(acc)
        return out

    def ex_prefix_sum(self, values: Sequence[Any], op: Callable = operator.add,
                      initial: Any = 0) -> List[Any]:
        out: List[Any] = []
        acc = initial
        for v in values:
            out.append(acc)
            acc = op(acc, v)
        return out

    def ex_prefix_sum_total(self, values: Sequence[Any],
                            op: Callable = operator.add, initial: Any = 0):
        excl = self.ex_prefix_sum(values, op, initial)
        total = op(excl[-1], values[-1]) if values else initial
        return excl, total

    def all_gather(self, values: Sequence[Any]) -> List[Any]:
        return list(values)

    def all_reduce(self, values: Sequence[Any], op: Callable = operator.add,
                   initial: Any = None) -> Any:
        if not values:
            if initial is None:
                raise ValueError("all_reduce over zero workers needs initial")
            return initial
        acc = values[0] if initial is None else op(initial, values[0])
        for v in values[1:]:
            acc = op(acc, v)
        return acc

    def predecessor(self, k: int, per_worker_items: Sequence[Sequence[Any]]) -> List[List[Any]]:
        """For each worker, the <= k items immediately preceding its range."""
        out: List[List[Any]] = []
        flat_prev: List[Any] = []
        for items in per_worker_items:
            out.append(flat_prev[-k:] if k > 0 else [])
            flat_prev = (flat_prev + list(items))[-k:] if k > 0 else []
        return out
