"""In-process mock network backend.

Equivalent of the reference's net/mock backend
(reference: thrill/net/mock/group.hpp:41,116,171): connections enqueue
messages directly into the peer's queue — always available, no sockets,
used by the in-process virtual-cluster test harness the same way the
reference uses mock groups for RunLocalTests on platforms without
socketpairs.
"""

from __future__ import annotations

import queue
import time
from typing import Any, List, Optional

from .group import (CollectiveHangTimeout, Connection, Group,
                    hang_timeout_s)


class _MockConnection(Connection):
    def __init__(self, out_q: "queue.Queue[Any]", in_q: "queue.Queue[Any]") -> None:
        self._out = out_q
        self._in = in_q
        # simulated link drop (MockGroup.drop_link): a broken mock
        # connection refuses traffic like a closed socket, so the
        # Context heal / generation-barrier repair path is testable
        # without real sockets
        self.broken = False

    def _check_link(self) -> None:
        if self.broken:
            raise ConnectionError("mock link dropped")

    def send(self, obj: Any) -> Optional[int]:
        # objects pass by reference — nothing is serialized, so there
        # is no wire byte count to report (callers measuring frame
        # bytes fall back to an explicit wire.dumps)
        self._check_link()
        self._out.put(obj)
        return None

    def recv(self) -> Any:
        self._check_link()
        return self._in.get()

    def recv_deadline(self, deadline_s: float) -> Any:
        """Timed receive for the collective watchdog (net/group.py) —
        the mock transport honors THRILL_TPU_HANG_TIMEOUT_S too, so
        the hang-abort protocol is testable without sockets."""
        self._check_link()
        try:
            return self._in.get(timeout=deadline_s)
        except queue.Empty:
            raise CollectiveHangTimeout(
                "no frame within the recv deadline") from None


class MockGroup(Group):
    def __init__(self, my_rank: int, num_hosts: int,
                 queues: List[List["queue.Queue[Any]"]]) -> None:
        super().__init__(my_rank, num_hosts)
        # queues[src][dst] carries messages src -> dst; the matrix is
        # kept so an elastic grow can wire connections to ranks added
        # by MockNetwork.grow after this group was built
        self._queues = queues
        self._conns = [
            _MockConnection(queues[my_rank][peer], queues[peer][my_rank])
            for peer in range(num_hosts)
        ]

    def connection(self, peer: int) -> Connection:
        if peer == self.my_rank:
            raise ValueError("no connection to self")
        return self._conns[peer]

    def _grow_transport(self, new_num_hosts: int, gen: int,
                        deadline_at: float) -> None:
        """Wire connections to ranks the shared MockNetwork already
        grew (MockNetwork.grow extends the queue matrix in place, so
        every live group sees the new rows)."""
        if len(self._queues) < new_num_hosts:
            raise ConnectionError(
                f"mock network has {len(self._queues)} ranks; grow the "
                f"MockNetwork to {new_num_hosts} before resizing")
        for peer in range(len(self._conns), new_num_hosts):
            self._conns.append(_MockConnection(
                self._queues[self.my_rank][peer],
                self._queues[peer][self.my_rank]))

    def _shrink_transport(self, new_num_hosts: int) -> None:
        del self._conns[new_num_hosts:]

    def drop_link(self, peer: int) -> None:
        """Simulate a dropped link to ``peer`` (tests): traffic raises
        ConnectionError until a generation heal repairs it."""
        self._conns[peer].broken = True

    def _repair_connection(self, peer, deadline_at, cause=None) -> bool:
        """Mock links 'reconnect' by clearing the broken flag — the
        queues never actually died. In-flight frames queued before the
        drop survive (like kernel-buffered bytes on a real socket) and
        are discarded by the generation-barrier drain."""
        conn = self._conns[peer]
        if not conn.broken:
            return False
        conn.broken = False
        self.stats_reconnects += 1
        from ..common import faults
        faults.note("recovery", what="net.reconnect", peer=peer,
                    gen=self.generation, transport="mock")
        return True

    def _heal_transport(self, deadline_at: float) -> None:
        for peer in range(self.num_hosts):
            if peer != self.my_rank and self._conns[peer].broken:
                self._repair_connection(peer, deadline_at)

    def link_repairable(self, peer: int) -> bool:
        return self._conns[peer].broken

    @property
    def supports_recv_any(self) -> bool:
        return True

    def _pick_ready_peer(self, peers: List[int]) -> int:
        """Poll the incoming queues (non-destructively) and return the
        first peer with a frame pending — the mock transport's
        any-source readiness probe. Bounded by the collective-watchdog
        deadline; on expiry returns the first peer so recv_from's own
        watchdog raises the attributable abort."""
        deadline = hang_timeout_s()
        deadline_at = (None if deadline is None
                       else time.monotonic() + deadline)
        while True:
            for p in peers:
                if not self._conns[p]._in.empty():
                    return p
            if (deadline_at is not None
                    and time.monotonic() >= deadline_at):
                return peers[0]
            time.sleep(0.0005)


class MockNetwork:
    """Factory building a full mesh of MockGroups for p in-process hosts.

    Reference analog: mock::Group::ConstructLoopbackMesh
    (thrill/net/mock/group.hpp) used by ConstructLoopbackHostContexts
    (thrill/api/context.cpp:92-131).
    """

    def __init__(self, num_hosts: int) -> None:
        self.num_hosts = num_hosts
        self._queues = [[queue.Queue() for _ in range(num_hosts)]
                        for _ in range(num_hosts)]

    def group(self, rank: int) -> MockGroup:
        return MockGroup(rank, self.num_hosts, self._queues)

    def grow(self, new_num_hosts: int,
             from_hosts: Optional[int] = None) -> List[MockGroup]:
        """Extend the queue matrix in place to ``new_num_hosts`` ranks
        and return groups for the NEW ranks (the mock analog of
        ``tcp.join_tcp_group``). Live groups built from this network
        pick the new rows up through ``Group.resize``; each returned
        joiner group still owes a ``begin_generation`` to enter the
        membership.

        ``from_hosts`` is the LIVE membership width the grow starts
        from; it defaults to the matrix high-water mark (a first
        grow). A RE-grow after a shrink must pass the live width:
        dormant rank slots inside the matrix are re-activated with
        FRESH queues — the mock analog of a joiner's fresh sockets, so
        nothing a departed tenant of the slot left behind can leak
        into the new rank's inbox."""
        old_matrix = len(self._queues)
        live = old_matrix if from_hosts is None else int(from_hosts)
        if not (0 < live <= old_matrix):
            raise ValueError(
                f"from_hosts={live} outside the {old_matrix}-rank "
                f"matrix")
        if new_num_hosts < live:
            raise ValueError(
                f"grow to {new_num_hosts} < live {live}; shrink "
                f"happens through Group.resize, not the network")
        width = max(old_matrix, new_num_hosts)
        for row in self._queues:
            row.extend(queue.Queue()
                       for _ in range(len(row), width))
        self._queues.extend(
            [queue.Queue() for _ in range(width)]
            for _ in range(old_matrix, width))
        self.num_hosts = width
        for r in range(live, min(new_num_hosts, old_matrix)):
            for p in range(width):
                self._queues[r][p] = queue.Queue()
                self._queues[p][r] = queue.Queue()
        return [MockGroup(r, new_num_hosts, self._queues)
                for r in range(live, new_num_hosts)]

    @staticmethod
    def construct(num_hosts: int) -> List[MockGroup]:
        net = MockNetwork(num_hosts)
        return [net.group(r) for r in range(num_hosts)]
