"""In-process mock network backend.

Equivalent of the reference's net/mock backend
(reference: thrill/net/mock/group.hpp:41,116,171): connections enqueue
messages directly into the peer's queue — always available, no sockets,
used by the in-process virtual-cluster test harness the same way the
reference uses mock groups for RunLocalTests on platforms without
socketpairs.
"""

from __future__ import annotations

import queue
from typing import Any, List

from .group import CollectiveHangTimeout, Connection, Group


class _MockConnection(Connection):
    def __init__(self, out_q: "queue.Queue[Any]", in_q: "queue.Queue[Any]") -> None:
        self._out = out_q
        self._in = in_q

    def send(self, obj: Any) -> None:
        self._out.put(obj)

    def recv(self) -> Any:
        return self._in.get()

    def recv_deadline(self, deadline_s: float) -> Any:
        """Timed receive for the collective watchdog (net/group.py) —
        the mock transport honors THRILL_TPU_HANG_TIMEOUT_S too, so
        the hang-abort protocol is testable without sockets."""
        try:
            return self._in.get(timeout=deadline_s)
        except queue.Empty:
            raise CollectiveHangTimeout(
                "no frame within the recv deadline") from None


class MockGroup(Group):
    def __init__(self, my_rank: int, num_hosts: int,
                 queues: List[List["queue.Queue[Any]"]]) -> None:
        super().__init__(my_rank, num_hosts)
        # queues[src][dst] carries messages src -> dst
        self._conns = [
            _MockConnection(queues[my_rank][peer], queues[peer][my_rank])
            for peer in range(num_hosts)
        ]

    def connection(self, peer: int) -> Connection:
        if peer == self.my_rank:
            raise ValueError("no connection to self")
        return self._conns[peer]


class MockNetwork:
    """Factory building a full mesh of MockGroups for p in-process hosts.

    Reference analog: mock::Group::ConstructLoopbackMesh
    (thrill/net/mock/group.hpp) used by ConstructLoopbackHostContexts
    (thrill/api/context.cpp:92-131).
    """

    def __init__(self, num_hosts: int) -> None:
        self.num_hosts = num_hosts
        self._queues = [[queue.Queue() for _ in range(num_hosts)]
                        for _ in range(num_hosts)]

    def group(self, rank: int) -> MockGroup:
        return MockGroup(rank, self.num_hosts, self._queues)

    @staticmethod
    def construct(num_hosts: int) -> List[MockGroup]:
        net = MockNetwork(num_hosts)
        return [net.group(r) for r in range(num_hosts)]
