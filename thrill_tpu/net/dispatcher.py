"""Async I/O engine: the net layer's dispatcher + dispatcher thread.

Equivalent of the reference's net::Dispatcher / DispatcherThread
(reference: thrill/net/dispatcher.hpp:510 — per-connection queues of
AsyncRead/AsyncWrite buffers driven by an event loop on a dedicated
thread; dispatcher_thread.hpp:60). The engine itself is native C++
(native/dispatcher.cpp, epoll + dedicated thread, built from source on
first use like the block store); this wrapper exposes request handles
Python can wait on, and a pure-Python ``selectors`` fallback keeps the
API available without a compiler.

Semantics shared by both engines:
  * ``async_write(sock, bytes)`` copies the buffer in and returns a
    request id immediately; the engine writes when the socket is
    writable. Per-fd writes retire FIFO, so framing order is preserved.
  * ``async_read(sock, n)`` completes once exactly n bytes arrived.
  * ``wait(id)`` blocks until completion; ``fetch(id)`` returns a
    read's payload (b"" for writes) and frees the slot.
Registered fds are switched to non-blocking and owned by the engine —
all traffic on them must flow through it until ``unregister``.
"""

from __future__ import annotations

import ctypes
import os
import selectors
import socket
import threading
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ..common import faults

# fires inside the periodic-callback dispatch: a transient fault skips
# ONE tick and keeps the timer armed (a heartbeat must survive a flaky
# beat); any other exception still disarms loudly below
_F_TIMER = faults.declare("net.dispatcher.timer",
                          exc=faults.InjectedIOError)

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Build-from-source-only loader (hash-named artifact; shared
    lifecycle in common/native_build.py)."""
    global _LIB, _LIB_FAILED
    with _LIB_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        from ..common.native_build import build_and_load
        lib = build_and_load("dispatcher.cpp")
        if lib is None:
            _LIB_FAILED = True
            return None
        try:
            lib.disp_create.restype = ctypes.c_void_p
            lib.disp_destroy.argtypes = [ctypes.c_void_p]
            lib.disp_register.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.disp_register.restype = ctypes.c_int
            lib.disp_unregister.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.disp_unregister.restype = ctypes.c_int
            lib.disp_async_write.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                ctypes.c_int64]
            lib.disp_async_write.restype = ctypes.c_int64
            lib.disp_async_read.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int64]
            lib.disp_async_read.restype = ctypes.c_int64
            lib.disp_poll.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.disp_poll.restype = ctypes.c_int64
            lib.disp_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_double]
            lib.disp_wait.restype = ctypes.c_int64
            lib.disp_fetch.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.c_char_p, ctypes.c_int64]
            lib.disp_fetch.restype = ctypes.c_int64
            lib.disp_pending.argtypes = [ctypes.c_void_p]
            lib.disp_pending.restype = ctypes.c_int64
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
    return _LIB


class _TimerFacility:
    """Timer service shared by both engines (reference:
    net/dispatcher.hpp:42-62 ``AddTimer``): ``add_timer(period,
    callback)`` fires ``callback()`` every ``period`` seconds for as
    long as it returns True; returning False (or ``cancel_timer``)
    drops it. The reference dispatches timer callbacks on its event
    loop thread; the native engine's loop is C++, so callbacks here run
    on ONE dedicated daemon thread per dispatcher — the same
    serialization guarantee (no two callbacks of one dispatcher run
    concurrently), started lazily on the first add_timer."""

    def _timer_init(self) -> None:
        self._tlock = threading.Lock()
        self._tcv = threading.Condition(self._tlock)
        self._theap: list = []            # (deadline, tid)
        self._tcb: Dict[int, Tuple[float, object]] = {}
        self._tnext = 0
        self._tstop = False
        self._tthread: Optional[threading.Thread] = None

    def add_timer(self, period_s: float, callback) -> int:
        """Schedule ``callback`` every ``period_s`` seconds; returns a
        timer id for cancel_timer. Re-arms while callback() is true."""
        import heapq
        import time
        with self._tcv:
            if self._tstop:
                raise DispatcherError("add_timer on closed dispatcher")
            tid = self._tnext
            self._tnext += 1
            self._tcb[tid] = (float(period_s), callback)
            heapq.heappush(self._theap,
                           (time.monotonic() + period_s, tid))
            if self._tthread is None:
                self._tthread = threading.Thread(
                    target=self._timer_run, daemon=True,
                    name="thrill-tpu-timers")
                self._tthread.start()
            self._tcv.notify()
        return tid

    def cancel_timer(self, tid: int) -> None:
        with self._tcv:
            self._tcb.pop(tid, None)
            self._tcv.notify()

    def _timer_run(self) -> None:
        import heapq
        import time
        while True:
            with self._tcv:
                while True:
                    if self._tstop:
                        return
                    now = time.monotonic()
                    # drop heap entries for cancelled timers
                    while self._theap and \
                            self._theap[0][1] not in self._tcb:
                        heapq.heappop(self._theap)
                    if self._theap and self._theap[0][0] <= now:
                        _, tid = heapq.heappop(self._theap)
                        period, cb = self._tcb[tid]
                        break
                    delay = (self._theap[0][0] - now
                             if self._theap else None)
                    self._tcv.wait(timeout=delay)
            # fire OUTSIDE the lock: callbacks may add/cancel timers
            try:
                faults.check(_F_TIMER, timer=tid)
                again = bool(cb())
            except Exception as exc:
                if (isinstance(exc, faults.InjectedFault)
                        and exc.kind == faults.TRANSIENT):
                    # skip this tick, stay armed: periodic services
                    # (heartbeats, spill flushes) ride out one glitch
                    faults.note("recovery", what="dispatcher.timer",
                                timer=tid)
                    again = True
                else:
                    # any other raising timer disarms — LOUDLY, or a
                    # dead periodic task (heartbeat, flush) degrades
                    # the system silently
                    import sys
                    import traceback
                    print(f"thrill_tpu: timer {tid} raised and was "
                          f"disarmed:\n{traceback.format_exc()}",
                          file=sys.stderr)
                    again = False
            with self._tcv:
                if tid not in self._tcb:
                    continue              # cancelled while firing
                if again:
                    heapq.heappush(self._theap,
                                   (time.monotonic() + period, tid))
                else:
                    del self._tcb[tid]

    def _timer_close(self) -> None:
        with self._tcv:
            self._tstop = True
            self._tcv.notify_all()
        if (self._tthread is not None
                and self._tthread is not threading.current_thread()):
            # join, unless close() was called FROM a timer callback
            # (the run loop sees _tstop and exits on its own)
            self._tthread.join(timeout=5)


class DispatcherError(ConnectionError):
    pass


class _NativeDispatcher(_TimerFacility):
    """ctypes front for the epoll engine."""

    def __init__(self, lib: ctypes.CDLL) -> None:
        self._timer_init()
        self._lib = lib
        self._h = lib.disp_create()
        if not self._h:
            raise OSError("disp_create failed")
        self._sizes: Dict[int, int] = {}   # read req id -> want bytes
        # write buffers are BORROWED by the engine (zero-copy enqueue):
        # pin the buffer objects here until the request is fetched.
        # Callers must not mutate a pinned buffer before completion.
        self._pins: Dict[int, tuple] = {}
        self._by_fd: Dict[int, set] = {}   # fd -> outstanding req ids
        self._lock = threading.Lock()

    def register(self, sock: socket.socket) -> None:
        if self._lib.disp_register(self._h, sock.fileno()) != 0:
            raise OSError("disp_register failed")
        with self._lock:
            self._by_fd.setdefault(sock.fileno(), set())

    def unregister(self, sock: socket.socket) -> None:
        fd = sock.fileno()
        # the engine retires every outstanding request with an error
        # status before returning...
        self._lib.disp_unregister(self._h, fd)
        # ...then drain those completions so pins/sizes/native slots
        # don't leak in the group-shared engine
        with self._lock:
            rids = self._by_fd.pop(fd, set())
        for rid in rids:
            self._lib.disp_fetch(self._h, rid, None, 0)
            with self._lock:
                self._pins.pop(rid, None)
                self._sizes.pop(rid, None)

    @staticmethod
    def _pinnable(data):
        """(address, length, pin_objects) for a contiguous read view of
        ``data`` — zero-copy for bytes/memoryview/contiguous buffers."""
        import numpy as np
        mv = memoryview(data)
        if not mv.contiguous:
            mv = memoryview(bytes(mv))
        mv = mv.cast("B")
        if len(mv) == 0:
            return 0, 0, (mv,)
        arr = np.frombuffer(mv, dtype=np.uint8)
        return int(arr.ctypes.data), len(mv), (mv, arr)

    def async_write(self, sock: socket.socket, data) -> int:
        addr, n, pins = self._pinnable(data)
        fd = sock.fileno()
        rid = self._lib.disp_async_write(self._h, fd,
                                         ctypes.c_void_p(addr), n)
        if rid < 0:
            raise DispatcherError("async_write on unregistered/failed fd")
        with self._lock:
            self._pins[rid] = pins    # engine borrows; release at fetch
            self._by_fd.setdefault(fd, set()).add(rid)
        return rid

    def async_read(self, sock: socket.socket, n: int) -> int:
        fd = sock.fileno()
        rid = self._lib.disp_async_read(self._h, fd, n)
        if rid < 0:
            raise DispatcherError("async_read on unregistered/failed fd")
        with self._lock:
            self._sizes[rid] = n
            self._by_fd.setdefault(fd, set()).add(rid)
        return rid

    def poll(self, rid: int) -> int:
        return int(self._lib.disp_poll(self._h, rid))

    def wait(self, rid: int, timeout: Optional[float] = None) -> int:
        return int(self._lib.disp_wait(
            self._h, rid, -1.0 if timeout is None else timeout))

    _NOT_DONE = -(1 << 62)

    def fetch(self, rid: int) -> bytes:
        with self._lock:
            cap = self._sizes.get(rid, 0)
        buf = ctypes.create_string_buffer(cap) if cap else None
        n = self._lib.disp_fetch(self._h, rid, buf, cap)
        if n == self._NOT_DONE:
            # still pending — the engine may still borrow the write
            # buffer, so the pin MUST stay
            raise DispatcherError(
                f"async request {rid} fetched before completion")
        with self._lock:
            self._pins.pop(rid, None)  # request retired: unpin buffer
            self._sizes.pop(rid, None)
            for rids in self._by_fd.values():
                rids.discard(rid)
        if n < 0:
            raise DispatcherError(
                f"async request {rid} failed (status {n})")
        return buf.raw[:n] if buf is not None else b""

    def pending(self) -> int:
        return int(self._lib.disp_pending(self._h))

    def close(self) -> None:
        self._timer_close()
        if self._h:
            self._lib.disp_destroy(self._h)
            self._h = None


class _PyDispatcher(_TimerFacility):
    """Pure-Python fallback: ``selectors`` loop on a daemon thread."""

    def __init__(self) -> None:
        self._timer_init()
        self._sel = selectors.DefaultSelector()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._writes: Dict[int, Deque[Tuple[int, memoryview]]] = {}
        self._reads: Dict[int, Deque[Tuple[int, int, bytearray]]] = {}
        self._socks: Dict[int, socket.socket] = {}
        self._done: Dict[int, Tuple[int, bytes]] = {}  # id -> (status, data)
        self._fd_rids: Dict[int, set] = {}  # fd -> requests ever issued
        self._errored: set = set()  # fds with a failed send/recv
        self._next_id = 1
        self._stop = False
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._sel.register(self._waker_r, selectors.EVENT_READ, None)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="thrill-dispatcher")
        self._thread.start()

    def _wake(self) -> None:
        try:
            self._waker_w.send(b"\x01")
        except OSError:
            pass

    def register(self, sock: socket.socket) -> None:
        sock.setblocking(False)
        with self._lock:
            fd = sock.fileno()
            self._writes[fd] = deque()
            self._reads[fd] = deque()
            self._socks[fd] = sock
            self._errored.discard(fd)   # fd number may be recycled
            # no selector registration yet: selectors reject an empty
            # interest set, so the fd joins the loop on first request

    def unregister(self, sock: socket.socket) -> None:
        with self._cv:
            fd = sock.fileno()
            # queued requests complete with an error so waiters wake;
            # completed-but-unfetched slots are dropped with the fd so
            # nothing outlives the registration (no leak in a shared
            # engine — mirrors the native wrapper's drain)
            pending = ({rid for rid, _ in self._writes.get(fd, ())}
                       | {rid for rid, _, _ in self._reads.get(fd, ())})
            for rid in pending:
                self._done[rid] = (-32, b"")
            self._writes.pop(fd, None)
            self._reads.pop(fd, None)
            for rid in self._fd_rids.pop(fd, set()) - pending:
                self._done.pop(rid, None)
            self._socks.pop(fd, None)
            self._errored.discard(fd)
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError):
                pass
            self._cv.notify_all()
        sock.setblocking(True)

    def async_write(self, sock: socket.socket, data: bytes) -> int:
        with self._cv:
            fd = sock.fileno()
            if fd not in self._writes:
                raise DispatcherError("async_write on unregistered fd")
            if fd in self._errored:
                # match the native engine: once a send/recv failed, the
                # fd stays rejected (no engine-dependent semantics)
                raise DispatcherError("async_write on failed fd")
            rid = self._next_id
            self._next_id += 1
            self._fd_rids.setdefault(fd, set()).add(rid)
            mv = memoryview(data)          # zero-copy for bytes/views
            if not mv.contiguous:
                mv = memoryview(bytes(mv))
            mv = mv.cast("B")
            if not self._writes[fd]:
                # opportunistic inline send while the queue is empty
                # (FIFO-safe); the attempt cap bounds enqueue latency —
                # only the unsent tail rides the loop
                for _ in range(4):
                    if not len(mv):
                        break
                    try:
                        n = sock.send(mv)
                    except (BlockingIOError, InterruptedError):
                        break
                    except OSError:
                        self._done[rid] = (-32, b"")
                        self._fail_fd(fd, -32)
                        return rid
                    mv = mv[n:]
                if not len(mv):
                    self._done[rid] = (1, b"")
                    self._cv.notify_all()
                    return rid
            self._writes[fd].append((rid, mv))
            self._update(fd)
        self._wake()
        return rid

    def async_read(self, sock: socket.socket, n: int) -> int:
        with self._cv:
            fd = sock.fileno()
            if fd not in self._reads:
                raise DispatcherError("async_read on unregistered fd")
            if fd in self._errored:
                raise DispatcherError("async_read on failed fd")
            rid = self._next_id
            self._next_id += 1
            self._fd_rids.setdefault(fd, set()).add(rid)
            if n == 0 and not self._reads[fd]:
                # zero-byte read with nothing queued ahead completes
                # right away (select never fires for it)
                self._done[rid] = (1, b"")
                self._cv.notify_all()
                return rid
            self._reads[fd].append((rid, n, bytearray()))
            self._update(fd)
        self._wake()
        return rid

    def poll(self, rid: int) -> int:
        with self._lock:
            if rid not in self._done:
                return 0
            status, _ = self._done[rid]
            return 1 if status >= 0 else status

    def wait(self, rid: int, timeout: Optional[float] = None) -> int:
        with self._cv:
            ok = self._cv.wait_for(lambda: rid in self._done, timeout)
            if not ok:
                return 0
            status, _ = self._done[rid]
            return 1 if status >= 0 else status

    def fetch(self, rid: int) -> bytes:
        with self._lock:
            entry = self._done.pop(rid, None)
            if entry is not None:
                for rids in self._fd_rids.values():
                    rids.discard(rid)
        if entry is None:
            # still pending (or already drained) — match the native
            # engine's kNotDone semantics, keep state untouched
            raise DispatcherError(
                f"async request {rid} fetched before completion")
        status, data = entry
        if status < 0:
            raise DispatcherError(
                f"async request {rid} failed (status {status})")
        return data

    def pending(self) -> int:
        with self._lock:
            return (sum(len(q) for q in self._writes.values())
                    + sum(len(q) for q in self._reads.values()))

    def close(self) -> None:
        self._timer_close()
        self._stop = True
        self._wake()
        self._thread.join(timeout=5)
        try:
            self._sel.close()
        except OSError:
            pass
        self._waker_r.close()
        self._waker_w.close()

    # -- loop ----------------------------------------------------------
    def _update(self, fd: int) -> None:
        """Recompute the interest set; caller holds the lock."""
        sock = self._socks.get(fd)
        if sock is None:
            return
        ev = 0
        if self._reads.get(fd):
            ev |= selectors.EVENT_READ
        if self._writes.get(fd):
            ev |= selectors.EVENT_WRITE
        try:
            if ev == 0:
                self._sel.unregister(sock)
            else:
                self._sel.modify(sock, ev, fd)
        except KeyError:
            if ev:
                self._sel.register(sock, ev, fd)
        except ValueError:
            pass

    def _fail_fd(self, fd: int, status: int) -> None:
        self._errored.add(fd)
        for rid, _ in self._writes.get(fd, ()):
            self._done[rid] = (status, b"")
        for rid, _, _ in self._reads.get(fd, ()):
            self._done[rid] = (status, b"")
        if fd in self._writes:
            self._writes[fd].clear()
        if fd in self._reads:
            self._reads[fd].clear()
        self._update(fd)
        self._cv.notify_all()

    def _run(self) -> None:
        while not self._stop:
            events = self._sel.select(timeout=0.2)
            with self._cv:
                for key, mask in events:
                    if key.data is None:          # waker
                        try:
                            while self._waker_r.recv(256):
                                pass
                        except OSError:
                            pass
                        continue
                    fd = key.data
                    sock = self._socks.get(fd)
                    if sock is None:
                        continue
                    if mask & selectors.EVENT_WRITE:
                        self._drain_writes(fd, sock)
                    if mask & selectors.EVENT_READ:
                        self._drain_reads(fd, sock)
                    self._update(fd)

    def _drain_writes(self, fd: int, sock: socket.socket) -> None:
        q = self._writes.get(fd)
        while q:
            rid, mv = q[0]
            try:
                n = sock.send(mv)
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._fail_fd(fd, -32)
                return
            if n < len(mv):
                q[0] = (rid, mv[n:])
                return
            q.popleft()
            self._done[rid] = (1, b"")
            self._cv.notify_all()

    def _drain_reads(self, fd: int, sock: socket.socket) -> None:
        q = self._reads.get(fd)
        while q:
            rid, want, buf = q[0]
            if want == 0:
                q.popleft()
                self._done[rid] = (1, b"")
                self._cv.notify_all()
                continue
            try:
                chunk = sock.recv(want - len(buf))
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._fail_fd(fd, -32)
                return
            if not chunk:
                self._fail_fd(fd, -1)
                return
            buf.extend(chunk)
            if len(buf) < want:
                return
            q.popleft()
            self._done[rid] = (1, bytes(buf))
            self._cv.notify_all()


def Dispatcher(force_py: bool = False):
    """Engine factory: native epoll when buildable, selectors fallback.

    THRILL_TPU_NATIVE=0 forces the fallback (mirrors block_pool)."""
    use_native = (not force_py
                  and os.environ.get("THRILL_TPU_NATIVE", "1") != "0")
    if use_native:
        lib = _load_native()
        if lib is not None:
            try:
                return _NativeDispatcher(lib)
            except OSError:
                pass
    return _PyDispatcher()


# NOTE: the length-framed channel over this engine lives in
# tcp.TcpConnection (attach_dispatcher) — one implementation of the
# bounded-in-flight reap/flush logic, in the product path.
