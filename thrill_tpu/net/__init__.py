from .group import Group, Connection  # noqa: F401
from .mock import MockNetwork  # noqa: F401
from .flow import FlowControlChannel, LocalFlowControl  # noqa: F401
