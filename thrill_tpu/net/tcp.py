"""TCP network backend: real-socket Group with full-mesh bootstrap.

Equivalent of the reference's net/tcp backend
(reference: thrill/net/tcp/construct.cpp full-mesh bootstrap with retry
rounds, socket.hpp:50, group.hpp) — the control plane between Python
hosts in a multi-controller deployment. The bulk data plane stays on
XLA collectives over ICI/DCN (jax.distributed); this layer carries the
small coordination values (size agreements, splitters, barriers) the
way the reference's flow-control group does, and is what host-path
operators use across machines.

Wire format: 4-byte little-endian length + a non-executing typed codec
(net/wire.py) per message — decoding never runs code. When a shared
secret is configured (THRILL_TPU_SECRET), every connection runs a
mutual HMAC-SHA256 challenge-response at bootstrap and, once
authenticated, may additionally carry pickled payloads for exotic
types; without a secret, pickle frames are refused in both directions.
Bootstrap: rank j connects to every rank i < j (i listens); each side
announces its rank (validated: in-range, not self, not yet taken).
Retries cover staggered process starts.

Env (reference: THRILL_RANK/THRILL_HOSTLIST, api/context.cpp:204-272):
THRILL_TPU_RANK, THRILL_TPU_HOSTLIST="host0:port0 host1:port1 ...",
THRILL_TPU_SECRET=<shared cluster secret>.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import wire
from .group import Connection, Group


class TcpConnection(Connection):
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # e.g. AF_UNIX socketpair in tests
        self.authenticated = False
        self._session_key: Optional[bytes] = None
        self._send_dir = b""
        self._recv_dir = b""
        self._send_seq = 0
        self._recv_seq = 0
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        # async engine (net/dispatcher.py); None = blocking socket ops
        self._disp = None
        self._disp_inflight: "deque" = None
        self._max_inflight = 64

    def attach_dispatcher(self, disp, max_inflight: int = 64) -> None:
        """Route all traffic through the async engine from now on:
        sends enqueue and return (bounded in-flight, the reference's
        send-semaphore analog), receives complete on the dispatcher
        thread. Must be called between messages (e.g. right after
        bootstrap), never mid-frame."""
        from collections import deque
        with self._send_lock, self._recv_lock:
            disp.register(self.sock)
            self._disp = disp
            self._disp_inflight = deque()
            self._max_inflight = max_inflight

    def _reap_sends(self, block: bool) -> None:
        """Caller holds _send_lock. Retire completed async sends; when
        ``block``, wait until back under the in-flight cap."""
        q = self._disp_inflight
        while q:
            rid = q[0]
            if block and len(q) >= self._max_inflight:
                self._disp.wait(rid)
            elif self._disp.poll(rid) == 0:
                return
            q.popleft()
            self._disp.fetch(rid)     # raises if the write failed

    def flush(self) -> None:
        """Block until every queued async send has hit the socket."""
        if self._disp is None:
            return
        with self._send_lock:
            q = self._disp_inflight
            while q:
                rid = q.popleft()
                self._disp.wait(rid)
                self._disp.fetch(rid)

    def send(self, obj: Any) -> None:
        payload = wire.dumps(obj, allow_pickle=self.authenticated)
        msg = struct.pack("<I", len(payload)) + payload
        with self._send_lock:
            if self._session_key is not None:
                # per-frame MAC: the handshake alone does not protect
                # the stream from on-path frame injection
                msg += wire.frame_mac(self._session_key, self._send_dir,
                                      self._send_seq, payload)
                self._send_seq += 1
            if self._disp is not None:
                self._reap_sends(block=True)
                self._disp_inflight.append(
                    self._disp.async_write(self.sock, msg))
            else:
                self.sock.sendall(msg)

    def recv(self) -> Any:
        with self._recv_lock:
            header = self._recv_exact(4)
            (size,) = struct.unpack("<I", header)
            payload = self._recv_exact(size)
            if self._session_key is not None:
                mac = self._recv_exact(wire._MAC_LEN)
                want = wire.frame_mac(self._session_key, self._recv_dir,
                                      self._recv_seq, payload)
                import hmac as _hmac
                if not _hmac.compare_digest(mac, want):
                    raise wire.AuthError("wire: frame MAC mismatch")
                self._recv_seq += 1
            return wire.loads(payload, allow_pickle=self.authenticated)

    def authenticate(self, secret: bytes, role: str) -> None:
        """Mutual role-bound HMAC challenge-response; raises on
        mismatch. ``role`` is "client" for the dialing side, "server"
        for the accepting side. On success every subsequent frame is
        MACed under the derived session key."""
        with self._send_lock, self._recv_lock:
            key = wire.mutual_auth(secret, role, self.sock.sendall,
                                   self._recv_exact)
            self._session_key = key
            self._send_dir = b"c>" if role == "client" else b"s>"
            self._recv_dir = b"s>" if role == "client" else b"c>"
        self.authenticated = True

    def _recv_exact(self, n: int) -> bytes:
        if self._disp is not None:
            rid = self._disp.async_read(self.sock, n)
            self._disp.wait(rid)
            return self._disp.fetch(rid)
        chunks = []
        while n > 0:
            b = self.sock.recv(n)
            if not b:
                raise ConnectionError("peer closed connection")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.flush()
        except (ConnectionError, OSError) as e:
            # close() must not raise in cleanup paths, but a deferred
            # async-send failure means queued messages were LOST — make
            # that visible (callers needing a guarantee call flush()
            # themselves and get the exception at the call site)
            import sys
            print(f"thrill_tpu.net.tcp: async sends lost at close: {e}",
                  file=sys.stderr)
        if self._disp is not None:
            try:
                self._disp.unregister(self.sock)
            except OSError:
                pass
            self._disp = None
        try:
            self.sock.close()
        except OSError:
            pass


class TcpGroup(Group):
    def __init__(self, my_rank: int, num_hosts: int,
                 conns: Dict[int, TcpConnection]) -> None:
        super().__init__(my_rank, num_hosts)
        self._conns = conns
        self._disp = None

    def connection(self, peer: int) -> TcpConnection:
        if peer == self.my_rank:
            raise ValueError("no connection to self")
        return self._conns[peer]

    def attach_dispatcher(self, disp=None) -> None:
        """Drive every connection through one async engine (a dedicated
        DispatcherThread per host, reference:
        thrill/net/dispatcher_thread.hpp:60) — fan-out sends to many
        peers then progress concurrently instead of serializing on
        sendall. The group owns the engine and closes it."""
        if disp is None:
            from .dispatcher import Dispatcher
            disp = Dispatcher()
        self._disp = disp
        for c in self._conns.values():
            c.attach_dispatcher(disp)

    def flush(self) -> None:
        for c in self._conns.values():
            c.flush()

    def close(self) -> None:
        for c in self._conns.values():
            c.close()
        if self._disp is not None:
            self._disp.close()
            self._disp = None


def _exchange_auth_flag(conn: TcpConnection, have_secret: bool) -> None:
    """1-byte preamble so an asymmetric THRILL_TPU_SECRET configuration
    fails fast with the real cause instead of a generic bootstrap
    timeout (one side waiting for a challenge that never comes)."""
    conn.sock.sendall(b"\x01" if have_secret else b"\x00")
    peer = conn._recv_exact(1)
    if peer not in (b"\x00", b"\x01"):
        raise ConnectionError(f"tcp: bad auth preamble {peer!r}")
    if (peer == b"\x01") != have_secret:
        raise wire.AuthError(
            "tcp: THRILL_TPU_SECRET is configured on one side of the "
            "connection but not the other — set the same secret on "
            "every host (or on none)")


def parse_hostlist(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in s.replace(",", " ").split():
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def construct_tcp_group(rank: int, hosts: List[Tuple[str, int]],
                        timeout: float = 30.0,
                        secret: Optional[bytes] = None) -> TcpGroup:
    """Full-mesh bootstrap: rank j dials every i < j; i accepts j..p-1.

    With ``secret`` every connection is mutually HMAC-authenticated
    before the rank announcement is trusted (and pickled payloads are
    enabled); without it the non-executing codec is the only format.
    """
    p = len(hosts)
    if p == 1:
        return TcpGroup(0, 1, {})
    conns: Dict[int, TcpConnection] = {}
    lock = threading.Lock()
    errors: List[BaseException] = []

    def accept_side():
        try:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((hosts[rank][0] if hosts[rank][0] != "localhost"
                      else "127.0.0.1", hosts[rank][1]))
            srv.listen(p)
            srv.settimeout(timeout)
            expected = p - 1 - rank          # ranks > mine dial in
            accepted = 0
            accept_deadline = time.time() + timeout
            while accepted < expected:
                if time.time() > accept_deadline:
                    raise TimeoutError(
                        f"rank {rank}: bootstrap accept timed out")
                s, addr = srv.accept()
                # accepted sockets do NOT inherit the listener timeout;
                # without one, a silent connection would park this
                # thread in recv forever and wedge the whole bootstrap
                s.settimeout(min(10.0, timeout))
                conn = TcpConnection(s)
                try:
                    _exchange_auth_flag(conn, secret is not None)
                    if secret is not None:
                        conn.authenticate(secret, role="server")
                    peer = conn.recv()       # rank announcement
                    with lock:
                        if (type(peer) is not int or not rank < peer < p
                                or peer in conns):
                            raise ConnectionError(
                                f"invalid rank announcement {peer!r}")
                        conns[peer] = conn
                except Exception as e:
                    # reject the rogue/failed peer, keep accepting
                    conn.close()
                    import sys
                    print(f"thrill_tpu.net.tcp: rank {rank} rejected "
                          f"peer {addr}: {e}", file=sys.stderr)
                    continue
                s.settimeout(None)           # handshake done: blocking
                accepted += 1
            srv.close()
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    acceptor = threading.Thread(target=accept_side, daemon=True)
    acceptor.start()

    deadline = time.time() + timeout
    for peer in range(rank):                 # dial every lower rank
        while True:
            try:
                s = socket.create_connection(hosts[peer], timeout=2.0)
                s.settimeout(min(10.0, timeout))
                conn = TcpConnection(s)
                _exchange_auth_flag(conn, secret is not None)
                if secret is not None:
                    conn.authenticate(secret, role="client")
                conn.send(rank)
                s.settimeout(None)           # handshake done: blocking
                with lock:
                    conns[peer] = conn
                break
            except wire.AuthError:
                # auth failure is definitive (secret mismatch), not a
                # transient dial error — fail fast with the real cause
                raise
            except OSError:
                if time.time() > deadline:
                    raise TimeoutError(
                        f"rank {rank}: cannot reach rank {peer} at "
                        f"{hosts[peer]}")
                time.sleep(0.05)

    acceptor.join(timeout=timeout)
    if acceptor.is_alive():
        raise TimeoutError(f"rank {rank}: bootstrap accept timed out")
    if errors:
        raise errors[0]
    assert len(conns) == p - 1
    group = TcpGroup(rank, p, conns)
    # async engine on by default: collectives' fan-out sends overlap
    # (reference always runs its Dispatcher; THRILL_TPU_ASYNC_NET=0
    # falls back to blocking sockets)
    if os.environ.get("THRILL_TPU_ASYNC_NET", "1") != "0":
        group.attach_dispatcher()
    return group


def construct_from_env() -> Optional[TcpGroup]:
    """THRILL_TPU_RANK/HOSTLIST -> TcpGroup (None when unset)."""
    hostlist = os.environ.get("THRILL_TPU_HOSTLIST")
    if not hostlist:
        return None
    rank = int(os.environ.get("THRILL_TPU_RANK", "0"))
    secret = wire.secret_from_env()
    if secret is None:
        import sys
        print("thrill_tpu.net.tcp: THRILL_TPU_SECRET unset — "
              "connections are unauthenticated and restricted to the "
              "non-executing wire codec", file=sys.stderr)
    return construct_tcp_group(rank, parse_hostlist(hostlist),
                               secret=secret)
