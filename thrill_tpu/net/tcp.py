"""TCP network backend: real-socket Group with full-mesh bootstrap.

Equivalent of the reference's net/tcp backend
(reference: thrill/net/tcp/construct.cpp full-mesh bootstrap with retry
rounds, socket.hpp:50, group.hpp) — the control plane between Python
hosts in a multi-controller deployment. The bulk data plane stays on
XLA collectives over ICI/DCN (jax.distributed); this layer carries the
small coordination values (size agreements, splitters, barriers) the
way the reference's flow-control group does, and is what host-path
operators use across machines.

Wire format: 4-byte little-endian length + a non-executing typed codec
(net/wire.py) per message — decoding never runs code. When a shared
secret is configured (THRILL_TPU_SECRET), every connection runs a
mutual HMAC-SHA256 challenge-response at bootstrap and, once
authenticated, may additionally carry pickled payloads for exotic
types; without a secret, pickle frames are refused in both directions.
Bootstrap: rank j connects to every rank i < j (i listens); each side
announces its rank (validated: in-range, not self, not yet taken).
Retries cover staggered process starts.

Env (reference: THRILL_RANK/THRILL_HOSTLIST, api/context.cpp:204-272):
THRILL_TPU_RANK, THRILL_TPU_HOSTLIST="host0:port0 host1:port1 ...",
THRILL_TPU_SECRET=<shared cluster secret>.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..common import faults
from ..common.retry import default_policy
from . import wire
from .group import (F_RESIZE, HEARTBEAT_KEY, CollectiveHangTimeout,
                    Connection, Group, hang_timeout_s, resize_timeout_s)

# Injection sites fire BEFORE any bytes hit the wire, so the internal
# retry (shared backoff policy) is safe: nothing was transmitted. Real
# transport errors on an established stream classify PERMANENT at this
# layer — a partially sent frame leaves the stream unrecoverable, and
# resynchronizing would accept corrupt framing.
_F_CONNECT = faults.declare("net.tcp.connect",
                            exc=faults.InjectedConnectionError)
_F_SEND = faults.declare("net.tcp.send",
                         exc=faults.InjectedConnectionError)
_F_FLUSH = faults.declare("net.tcp.flush",
                          exc=faults.InjectedConnectionError)
_FRAME_TRANSIENT = (faults.InjectedConnectionError,)

# link-drop injection: an armed fire REALLY closes the socket
# mid-exchange (kind="permanent" at the frame layer — nothing can
# resynchronize a torn stream), surfacing as a plain ConnectionError so
# no per-frame retry absorbs it. The current pipeline aborts; the
# generation heal (Group.begin_generation -> _repair_connection)
# reconnects the link for the next one.
_F_DISCONNECT = faults.declare("net.tcp.disconnect", kind="permanent")

# an EXTERNAL client vanishing mid-session (SIGKILL, network
# partition) as seen from the serving edge: fired in the front door's
# per-connection reader (service/front_door.py), an armed fire drops
# exactly that client's connection. Permanent by nature — a vanished
# client cannot be retried INTO existence; its in-flight jobs still
# complete and other tenants never notice.
F_CLIENT_DISCONNECT = faults.declare("net.tcp.client_disconnect",
                                     kind="permanent")


def _reconnect_enabled() -> bool:
    """THRILL_TPU_RECONNECT=0 disables link repair: a dropped socket
    then stays fatal for the Context (pre-reconnect behavior)."""
    return os.environ.get("THRILL_TPU_RECONNECT", "1") != "0"


def _reconnect_tries() -> int:
    """UNANSWERED dial attempts per link repair
    (THRILL_TPU_RECONNECT_TRIES, default 25; backoff rides the shared
    full-jitter policy). Generous by design: during a multi-link heal
    a live peer repairs its links sequentially, so early dials land on
    a port nobody is listening on yet — the budget must outlast that
    window, and the heal deadline stays the hard bound."""
    try:
        return max(1, int(os.environ.get("THRILL_TPU_RECONNECT_TRIES",
                                         "25")))
    except ValueError:
        return 25


def _frame_site_check(site: str) -> None:
    """Per-frame injection gate. Only injected faults are retryable at
    this layer (real stream errors are permanent), so with no
    injection active the policy machinery is skipped entirely — the
    disarmed hot path costs one env read."""
    if faults.REGISTRY.active():
        default_policy(transient=_FRAME_TRANSIENT).run(
            lambda: faults.check(site), what=site)


def _wait_fd(sock: socket.socket, write: bool, timeout: float) -> bool:
    """poll()-based readiness wait. select.select raises ValueError for
    fds >= FD_SETSIZE (1024), which a large full-mesh with many open
    files can hit — poll has no such limit."""
    import select as _select
    p = _select.poll()
    p.register(sock.fileno(),
               _select.POLLOUT if write else _select.POLLIN)
    try:
        return bool(p.poll(timeout * 1000.0))
    finally:
        p.unregister(sock.fileno())


class TcpConnection(Connection):
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        try:
            self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # e.g. AF_UNIX socketpair in tests
        self.authenticated = False
        self._session_key: Optional[bytes] = None
        self._send_dir = b""
        self._recv_dir = b""
        self._send_seq = 0
        self._recv_seq = 0
        self._send_lock = threading.Lock()
        self._recv_lock = threading.Lock()
        # async engine (net/dispatcher.py); None = blocking socket ops.
        # Small control frames stay on the blocking fast path (the
        # reference's flow group is synchronous too); the engine
        # attaches lazily on the first frame >= _async_threshold bytes
        # when a supplier is set (data-plane overlap + symmetric
        # large-message deadlock safety), and owns the fd from then on.
        self._disp = None
        # in-flight async sends: deque of (rid, nbytes, debug_check).
        # Bounded by BYTES, not request count — many small frames are
        # cheap to pin, while a few giant borrowed frames are not.
        self._disp_inflight: "deque" = None
        self._inflight_bytes = 0
        self._max_inflight_bytes = _async_inflight_bytes()
        self._reap_stalled_rid = None
        self._disp_supplier = None
        self._async_threshold = _async_threshold()
        # async send failure observed outside send() (e.g. during the
        # opportunistic reap in recv); surfaced at the next send/flush
        self._send_error = None
        # monotonic timestamp of the last heartbeat frame seen on this
        # connection (net/heartbeat.py liveness chatter)
        self.last_heartbeat = 0.0
        # link verdict: set when the stream died (peer closed, torn
        # frame, injected disconnect). A broken connection refuses
        # traffic fast; the generation heal replaces it via reconnect
        self.broken = False

    def _drop_link(self) -> None:
        """Tear this link down for real: detach from the async engine,
        close the fd, mark broken. The peer sees EOF on its next read."""
        self.broken = True
        if self._disp is not None:
            try:
                self._disp.unregister(self.sock)
            except Exception:
                pass
            self._disp = None
        try:
            self.sock.close()
        except OSError:
            pass

    def _check_link(self) -> None:
        """Fail fast on a known-dead link; fire the injected
        mid-exchange socket drop when armed."""
        if self.broken:
            raise ConnectionError(
                "tcp link is down (awaiting generation heal/reconnect)")
        if faults.REGISTRY.active():
            try:
                faults.check(_F_DISCONNECT)
            except faults.InjectedFault as e:
                self._drop_link()
                raise ConnectionError(
                    "injected link drop (net.tcp.disconnect)") from e

    def _mark_broken(self, exc: BaseException) -> None:
        """A real transport error tore the stream: remember the verdict.
        Injected RETRYABLE faults fire before any byte hits the wire,
        and timeouts (TimeoutError is an OSError subclass — the
        watchdog's CollectiveHangTimeout, send_bounded's nothing-sent
        expiry) leave the stream intact: neither condemns the link."""
        if not isinstance(exc, (faults.InjectedFault, TimeoutError)):
            self.broken = True

    def set_dispatcher_supplier(self, supplier) -> None:
        """Enable lazy attach: ``supplier()`` returns the shared engine
        the first time a large frame needs it."""
        self._disp_supplier = supplier

    def attach_dispatcher(self, disp,
                          max_inflight_bytes: Optional[int] = None) -> None:
        """Route all traffic through the async engine from now on:
        sends enqueue and return (byte-bounded in-flight, the
        reference's send-semaphore analog), receives complete on the
        dispatcher thread. Safe while a blocking recv is in progress on
        another thread: the direct receive path tolerates the fd
        turning non-blocking mid-frame (poll loop), finishes its frame
        with direct reads under _recv_lock, and the NEXT recv routes
        through the engine."""
        with self._send_lock:
            if self._disp is not None:     # already attached
                return
            self._attach_locked(disp, max_inflight_bytes)

    def _attach_locked(self, disp,
                       max_inflight_bytes: Optional[int] = None) -> None:
        disp.register(self.sock)
        self._disp = disp
        from collections import deque
        self._disp_inflight = deque()
        self._inflight_bytes = 0
        if max_inflight_bytes is not None:
            self._max_inflight_bytes = max_inflight_bytes

    # bounded wait when over the in-flight byte cap: a symmetric bulk
    # burst (both peers enqueue past the cap before either reads) makes
    # the head write unretirable until the PEER's reads start draining;
    # waiting forever here would deadlock both sides, so after the
    # timeout we keep queuing past the cap instead (memory over
    # deadlock — the reference's Dispatcher queues writes unbounded)
    _REAP_TIMEOUT_S = 0.5

    def _enqueue_send(self, rid: int, nbytes: int, check=None) -> None:
        self._disp_inflight.append((rid, nbytes, check))
        self._inflight_bytes += nbytes

    def _retire_head(self) -> None:
        """Caller holds _send_lock; head request is complete."""
        rid, nb, check = self._disp_inflight.popleft()
        self._inflight_bytes -= nb
        self._reap_stalled_rid = None
        try:
            self._disp.fetch(rid)     # raises if the write failed
        finally:
            if check is not None:
                check()               # debug: borrowed buffer unchanged?

    def _reap_sends(self, block: bool) -> None:
        """Caller holds _send_lock. Retire completed async sends; when
        ``block``, wait (bounded) until back under the in-flight byte
        cap. A head that already timed out once is not re-waited on
        subsequent sends (the peer is stalled — burn the timeout once,
        not once per frame), so an over-cap burst queues at enqueue
        speed after the first stall."""
        q = self._disp_inflight
        while q:
            rid, nb, _check = q[0]
            if self._disp.poll(rid) == 0:
                if not (block
                        and self._inflight_bytes > self._max_inflight_bytes):
                    return
                if rid == self._reap_stalled_rid:
                    return            # already burned the timeout on it
                if self._disp.wait(rid, self._REAP_TIMEOUT_S) == 0:
                    self._reap_stalled_rid = rid
                    return            # timed out: queue past the cap
            self._retire_head()

    def flush(self) -> None:
        """Block until every queued async send has hit the socket."""
        _frame_site_check(_F_FLUSH)
        if self._disp is None:
            return
        with self._send_lock:
            if self._send_error is not None:
                e, self._send_error = self._send_error, None
                raise e
            q = self._disp_inflight
            while q:
                self._disp.wait(q[0][0])
                self._retire_head()

    def send(self, obj: Any) -> int:
        """Send one message; returns the serialized payload byte count
        (the wire truth, measured here where the frame is encoded —
        the multiplexer's byte accounting reads it instead of paying a
        second serialization). Large bytes/ndarray payloads are
        BORROWED (zero-copy scatter-gather): on a dispatcher-attached
        connection the buffer must not be mutated until the send
        completes — ``flush()`` is the synchronization point.
        Collectives in net/group.py never mutate sent values; callers
        reusing staging arrays across rounds must flush between them."""
        _frame_site_check(_F_SEND)
        self._check_link()
        parts = wire.dumps_parts(obj, allow_pickle=self.authenticated)
        total = sum(len(p) for p in parts)
        bufs = [struct.pack("<I", total), *parts]
        try:
            with self._send_lock:
                if self._send_error is not None:
                    e, self._send_error = self._send_error, None
                    raise e
                if self._session_key is not None:
                    # per-frame MAC: the handshake alone does not protect
                    # the stream from on-path frame injection
                    bufs.append(wire.frame_mac_parts(
                        self._session_key, self._send_dir, self._send_seq,
                        parts))
                    self._send_seq += 1
                if (self._disp is None and self._disp_supplier is not None
                        and total >= self._async_threshold):
                    # first bulk frame: hand the fd to the async engine (no
                    # recv-lock handshake needed — see attach_dispatcher)
                    self._attach_locked(self._disp_supplier())
                if self._disp is not None:
                    self._reap_sends(block=True)
                    for b in bufs:
                        self._enqueue_send(self._disp.async_write(self.sock, b),
                                           len(b), _borrow_check(b))
                else:
                    self._sendall_parts(bufs)
        except (ConnectionError, OSError) as e:
            self._mark_broken(e)
            raise
        return total

    def send_bounded(self, obj: Any, deadline_s: float) -> None:
        """Send one message with a hard bound on blocking time
        (net/group.py poison_peers, net/heartbeat.py probes: writing
        to a peer whose socket buffer is full must not hang the
        caller). Expiry semantics keep the frame stream SAFE for
        callers on healthy groups: a deadline that fires before any
        byte hit the wire raises TimeoutError and leaves the stream
        (and the MAC sequence) exactly as before the call; one that
        fires mid-frame raises ConnectionError — the stream is torn
        and the connection must be treated as lost. A deferred async
        send failure (observed by recv's opportunistic reap) surfaces
        here like in send(), not silently dropped. A wedged sender
        already holding the send lock also counts against the
        deadline."""
        if self.broken:
            raise ConnectionError(
                "tcp link is down (awaiting generation heal/reconnect)")
        deadline_at = time.monotonic() + float(deadline_s)
        if not self._send_lock.acquire(timeout=deadline_s):
            raise TimeoutError("send_bounded: send lock busy past the "
                               "deadline")
        try:
            if self._send_error is not None:
                e, self._send_error = self._send_error, None
                raise e
            parts = wire.dumps_parts(obj,
                                     allow_pickle=self.authenticated)
            total = sum(len(p) for p in parts)
            bufs = [struct.pack("<I", total), *parts]
            if self._session_key is not None:
                # MAC under the CURRENT seq; the counter only advances
                # once the frame is fully written/enqueued, so a
                # nothing-sent timeout leaves the stream resumable
                bufs.append(wire.frame_mac_parts(
                    self._session_key, self._send_dir, self._send_seq,
                    parts))
            if self._disp is not None:
                # engine-attached: reap completed requests first —
                # WITHOUT this, a dead peer's failed async writes would
                # sit unfetched forever (heartbeat probes between
                # collectives are the only traffic, and recv's
                # opportunistic reap isn't running), leaving the
                # failure detector blind and the in-flight queue
                # growing. A prior write failure raises here — exactly
                # the dead-peer verdict the prober needs. Then enqueue
                # only (never block on the in-flight cap — an abort
                # frame must not wait behind bulk traffic).
                self._reap_sends(block=False)
                for b in bufs:
                    self._enqueue_send(
                        self._disp.async_write(self.sock, b), len(b))
                if self._session_key is not None:
                    self._send_seq += 1
                return
            mvs = [memoryview(b).cast("B") for b in bufs]
            frame_bytes = sum(len(m) for m in mvs)
            sent = 0
            self.sock.setblocking(False)
            try:
                while mvs:
                    remaining = deadline_at - time.monotonic()
                    if remaining <= 0:
                        if sent == 0:
                            raise TimeoutError(
                                "send_bounded: peer not draining "
                                "(no bytes written)")
                        raise ConnectionError(
                            f"send_bounded: frame torn mid-write "
                            f"({sent}/{frame_bytes} bytes) — "
                            f"connection unusable")
                    if not _wait_fd(self.sock, write=True,
                                    timeout=min(remaining, 0.5)):
                        continue
                    try:
                        nb = self.sock.sendmsg(mvs)
                    except (BlockingIOError, InterruptedError):
                        continue
                    sent += nb
                    while mvs and nb >= len(mvs[0]):
                        nb -= len(mvs[0])
                        mvs.pop(0)
                    if mvs and nb:
                        mvs[0] = mvs[0][nb:]
                if self._session_key is not None:
                    self._send_seq += 1
            finally:
                if self._disp is None:
                    try:
                        self.sock.setblocking(True)
                    except OSError:
                        pass
        except (ConnectionError, OSError) as e:
            self._mark_broken(e)
            raise
        finally:
            self._send_lock.release()

    # a blocking send making no progress for this long escapes to the
    # async engine (symmetric small-frame exchanges that outgrow both
    # kernel buffers cannot deadlock, whatever the frame size)
    _BLOCKING_SEND_STALL_S = 2.0

    def _sendall_parts(self, bufs) -> None:
        """sendmsg-based sendall over a list of buffers (zero-copy
        scatter-gather; handles partial sends). Caller holds _send_lock.

        With a dispatcher supplier configured, a stalled send (peer not
        draining — e.g. both sides of a pairwise exchange sending
        first) hands the unsent tail to the async engine instead of
        blocking forever on kernel buffers. The socket runs
        NON-blocking under the poll loop for the duration: a blocking
        sendmsg can park inside the kernel mid-frame (partial bytes
        queued, peer not draining) where the stall probe below could
        never run again — exactly the symmetric deadlock this escape
        hatch exists to prevent. The concurrent reader tolerates the
        mode flip (see _recv_exact)."""
        mvs = [memoryview(b).cast("B") for b in bufs]
        can_escape = self._disp_supplier is not None
        if can_escape:
            self.sock.setblocking(False)
        try:
            while mvs:
                if can_escape:
                    if not _wait_fd(self.sock, write=True,
                                    timeout=self._BLOCKING_SEND_STALL_S):
                        # no progress possible: switch this connection
                        # to the engine and enqueue the remaining tail.
                        # The tail is COPIED — this frame was sent
                        # under blocking semantics, so the caller may
                        # reuse its buffer the moment send() returns
                        # (and blocking here for the drain could
                        # deadlock symmetrically)
                        self._attach_locked(self._disp_supplier())
                        for mv in mvs:
                            b = bytes(mv)
                            self._enqueue_send(
                                self._disp.async_write(self.sock, b),
                                len(b))
                        return
                try:
                    n = self.sock.sendmsg(mvs)
                except (BlockingIOError, InterruptedError):
                    continue
                while mvs and n >= len(mvs[0]):
                    n -= len(mvs[0])
                    mvs.pop(0)
                if mvs and n:
                    mvs[0] = mvs[0][n:]
        finally:
            # restore blocking semantics unless the engine took the fd
            # (it owns non-blocking mode from then on)
            if can_escape and self._disp is None:
                self.sock.setblocking(True)

    def recv(self) -> Any:
        return self._recv_msg(None)

    def recv_deadline(self, deadline_s: float) -> Any:
        """Timed receive for the collective watchdog (net/group.py):
        raises :class:`CollectiveHangTimeout` when no complete frame
        lands within ``deadline_s``. The deadline is ABSOLUTE across
        the call — heartbeat chatter proves the peer process is alive
        but does not excuse a wedged collective."""
        return self._recv_msg(time.monotonic() + float(deadline_s))

    def _recv_msg(self, deadline_at: Optional[float]) -> Any:
        self._check_link()
        try:
            return self._recv_msg_inner(deadline_at)
        except (ConnectionError, OSError) as e:
            self._mark_broken(e)
            raise

    def _recv_msg_inner(self, deadline_at: Optional[float]) -> Any:
        while True:   # heartbeat frames are liveness chatter, not data
            with self._recv_lock:
                header = self._recv_exact(4, deadline_at)
                try:
                    (size,) = struct.unpack("<I", header)
                    payload = self._recv_exact(size, deadline_at)
                    if self._session_key is not None:
                        mac = self._recv_exact(wire._MAC_LEN,
                                               deadline_at)
                        want = wire.frame_mac(self._session_key,
                                              self._recv_dir,
                                              self._recv_seq, payload)
                        import hmac as _hmac
                        if not _hmac.compare_digest(mac, want):
                            raise wire.AuthError(
                                "wire: frame MAC mismatch")
                        self._recv_seq += 1
                except CollectiveHangTimeout:
                    # the deadline fired MID-FRAME: the header (and
                    # possibly part of the payload) is already
                    # consumed, so the stream is desynchronized — a
                    # later read would parse payload bytes as a frame
                    # length. Condemn the link; the generation heal
                    # reconnects it instead of reusing garbage.
                    self.broken = True
                    raise
                obj = wire.loads(payload,
                                 allow_pickle=self.authenticated)
            # opportunistic: drop pins of completed async sends (send/
            # recv alternate in every collective, so retention stays
            # bounded by one phase instead of lasting until the next
            # send). A send-side failure discovered here must NOT
            # discard the received message — defer it to the next
            # send()/flush()
            if self._disp is not None and self._send_lock.acquire(
                    blocking=False):
                try:
                    self._reap_sends(block=False)
                except ConnectionError as e:
                    self._send_error = e
                finally:
                    self._send_lock.release()
            if isinstance(obj, dict) and HEARTBEAT_KEY in obj:
                # filtered at the TRANSPORT so every consumer —
                # collectives, multiplexer bulk frames — stays
                # oblivious to liveness chatter
                self.last_heartbeat = time.monotonic()
                continue
            return obj

    def authenticate(self, secret: bytes, role: str) -> None:
        """Mutual role-bound HMAC challenge-response; raises on
        mismatch. ``role`` is "client" for the dialing side, "server"
        for the accepting side. On success every subsequent frame is
        MACed under the derived session key."""
        with self._send_lock, self._recv_lock:
            key = wire.mutual_auth(secret, role, self.sock.sendall,
                                   self._recv_exact)
            self._session_key = key
            self._send_dir = b"c>" if role == "client" else b"s>"
            self._recv_dir = b"s>" if role == "client" else b"c>"
        self.authenticated = True

    def _recv_exact(self, n: int,
                    deadline_at: Optional[float] = None) -> bytes:
        if self._disp is not None:
            rid = self._disp.async_read(self.sock, n)
            if deadline_at is None:
                self._disp.wait(rid)
            else:
                remaining = deadline_at - time.monotonic()
                if remaining <= 0 or self._disp.wait(
                        rid, remaining) == 0:
                    # the orphaned async read stays queued on the
                    # engine and will consume the next arriving bytes
                    # into a fetch nobody reads: the stream cannot be
                    # resynchronized — condemn the link for the heal
                    self.broken = True
                    raise CollectiveHangTimeout(
                        f"no frame within the recv deadline "
                        f"({n} bytes outstanding)")
            return self._disp.fetch(rid)
        chunks = []
        while n > 0:
            if deadline_at is not None:
                remaining = deadline_at - time.monotonic()
                if remaining <= 0:
                    if chunks:
                        # partial read: later reads would misparse the
                        # remaining bytes — the stream is torn
                        self.broken = True
                    raise CollectiveHangTimeout(
                        f"no frame within the recv deadline "
                        f"({n} bytes outstanding)")
                if not _wait_fd(self.sock, write=False,
                                timeout=min(remaining, 0.5)):
                    continue
            try:
                b = self.sock.recv(n)
            except (BlockingIOError, InterruptedError):
                # a concurrent dispatcher attach flipped the fd to
                # non-blocking mid-frame; finish this frame with
                # direct reads (we hold _recv_lock, so the engine has
                # no read requests racing us)
                _wait_fd(self.sock, write=False, timeout=0.2)
                continue
            if not b:
                raise ConnectionError("peer closed connection")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.flush()
        except (ConnectionError, OSError) as e:
            # close() must not raise in cleanup paths, but a deferred
            # async-send failure means queued messages were LOST — make
            # that visible (callers needing a guarantee call flush()
            # themselves and get the exception at the call site)
            import sys
            print(f"thrill_tpu.net.tcp: async sends lost at close: {e}",
                  file=sys.stderr)
        if self._disp is not None:
            try:
                self._disp.unregister(self.sock)
            except OSError:
                pass
            self._disp = None
        try:
            self.sock.close()
        except OSError:
            pass


class TcpGroup(Group):
    def __init__(self, my_rank: int, num_hosts: int,
                 conns: Dict[int, TcpConnection]) -> None:
        super().__init__(my_rank, num_hosts)
        self._conns = conns
        self._disp = None
        self._disp_owned = False
        self._disp_lock = threading.Lock()
        # liveness prober (net/heartbeat.py); None unless
        # THRILL_TPU_HEARTBEAT_S is set
        self._heartbeat = None
        # reconnect endpoints: construct_tcp_group stores the hostlist
        # + shared secret so a generation heal can re-dial a dropped
        # link with the same session-handshake guarantees as bootstrap.
        # None (socketpair-built test groups) = reconnect unavailable.
        self._hosts: Optional[List[Tuple[str, int]]] = None
        self._secret: Optional[bytes] = None

    def connection(self, peer: int) -> TcpConnection:
        if peer == self.my_rank:
            raise ValueError("no connection to self")
        return self._conns[peer]

    @property
    def supports_recv_any(self) -> bool:
        return True

    def _pick_ready_peer(self, peers) -> int:
        """Any-source readiness probe: poll the peer sockets and return
        the first with bytes pending (the connection reads straight
        from the socket, so fd readability == a frame is landing).
        Falls back to the fixed schedule when any candidate's fd is
        owned by the async engine (the engine completes reads on its
        own thread — polling the fd here would race it). Bounded by
        the collective-watchdog deadline; on expiry returns the first
        peer so recv_from's watchdog raises the attributable abort."""
        import select as _select
        conns = [self._conns[p] for p in peers]
        if any(c._disp is not None for c in conns):
            return peers[0]
        deadline = hang_timeout_s()
        deadline_at = (None if deadline is None
                       else time.monotonic() + deadline)
        p = _select.poll()
        by_fd = {}
        try:
            for peer, c in zip(peers, conns):
                fd = c.sock.fileno()
                p.register(fd, _select.POLLIN)
                by_fd[fd] = peer
            while True:
                if deadline_at is not None:
                    remaining = deadline_at - time.monotonic()
                    if remaining <= 0:
                        return peers[0]
                    timeout_ms = min(remaining, 0.5) * 1000.0
                else:
                    timeout_ms = 500.0
                events = p.poll(timeout_ms)
                if events:
                    return by_fd[events[0][0]]
        finally:
            for fd in by_fd:
                try:
                    p.unregister(fd)
                except (KeyError, OSError):
                    pass

    # ------------------------------------------------------------------
    # reconnect-with-backoff (generation heal, net/group.py)
    # ------------------------------------------------------------------

    def _heal_transport(self, deadline_at: float) -> None:
        """Repair every link already KNOWN broken before the generation
        barrier runs; a link that cannot be repaired fails the heal
        (the Context then escalates to the unrecoverable path)."""
        # ASCENDING peer order on every rank: with lower-listens /
        # higher-dials roles this is ordered resource acquisition —
        # concurrent multi-link heals cannot form a cyclic
        # accept/dial wait (dict insertion order is bootstrap accept
        # completion order, which CAN cycle)
        for peer in sorted(self._conns):
            conn = self._conns[peer]
            if getattr(conn, "broken", False):
                if not self._repair_connection(peer, deadline_at):
                    raise ConnectionError(
                        f"rank {self.my_rank}: link to rank {peer} is "
                        f"down and could not be reconnected "
                        f"(THRILL_TPU_RECONNECT/"
                        f"THRILL_TPU_RECONNECT_TRIES)")

    def _repair_connection(self, peer: int, deadline_at: float,
                           cause: Optional[BaseException] = None) -> bool:
        """Re-establish the link to ``peer``: same roles as bootstrap
        (lower rank listens, higher rank dials), mutual auth when a
        secret is configured, then a session handshake exchanging
        (rank, generation, frame seq) so both sides agree which failure
        domain the fresh stream belongs to. Returns False when
        reconnect is disabled/unavailable or the peer never answers
        (a dead PROCESS, not a dropped link — that verdict escalates)."""
        old = self._conns.get(peer)
        if old is not None:
            # idempotent: closes the fd even when an earlier recv
            # error already marked the link broken (a peer-closed
            # socket stays open on OUR side until dropped)
            old._drop_link()
        if self._hosts is None or not _reconnect_enabled():
            return False
        try:
            if peer > self.my_rank:
                conn = self._reconnect_accept(peer, deadline_at)
            else:
                conn = self._reconnect_dial(peer, deadline_at)
        except wire.AuthError:
            raise                   # definitive: never degrade auth
        except (ConnectionError, OSError, TimeoutError) as e:
            faults.note("recovery", what="net.reconnect_failed",
                        peer=peer, gen=self.generation, error=repr(e))
            return False
        if old is not None and old._disp_supplier is not None:
            conn.set_dispatcher_supplier(self._shared_dispatcher)
        self._conns[peer] = conn
        self.stats_reconnects += 1
        faults.note("recovery", what="net.reconnect", peer=peer,
                    gen=self.generation, transport="tcp")
        from ..common.trace import instant_of
        instant_of(getattr(self, "tracer", None), "net", "reconnect",
                   peer=peer, gen=self.generation)
        return True

    def link_repairable(self, peer: int) -> bool:
        conn = self._conns.get(peer)
        return (conn is not None and getattr(conn, "broken", False)
                and self._hosts is not None and _reconnect_enabled())

    def _handshake_frame(self) -> dict:
        # a FRESH stream restarts the MAC sequence: seq announces (and
        # the peer validates) where frame numbering resumes
        return {"reconnect": self.my_rank, "gen": self.generation,
                "seq": 0}

    def _validate_handshake(self, obj: Any, want_rank: int) -> int:
        if not (isinstance(obj, dict) and "reconnect" in obj):
            raise ConnectionError(f"bad reconnect handshake {obj!r}")
        if int(obj["reconnect"]) != want_rank:
            raise ConnectionError(
                f"reconnect handshake from unexpected rank "
                f"{obj['reconnect']!r} (awaiting {want_rank})")
        if int(obj.get("seq", 0)) != 0:
            raise ConnectionError(
                f"reconnect handshake with nonzero frame seq "
                f"{obj.get('seq')!r} — peer expects a resumed stream, "
                f"only fresh sessions are supported")
        gen = int(obj.get("gen", self.generation))
        if gen != self.generation:
            # both sides must be healing the SAME failure domain; a
            # cross-generation stream (one rank aborted again while
            # the other was still dialing) is rejected LOUDLY here —
            # the dialer retries and converges, instead of the
            # mismatch surfacing as an opaque barrier timeout
            raise ConnectionError(
                f"reconnect handshake generation mismatch: peer is "
                f"healing gen {gen}, this rank gen {self.generation}")
        return gen

    def _reconnect_dial(self, peer: int,
                        deadline_at: float) -> TcpConnection:
        import random
        policy = default_policy(max_attempts=1 << 30,
                                base_delay_s=0.05, max_delay_s=1.0)
        rng = random.Random(f"reconnect:{self.my_rank}:{peer}")
        tries = _reconnect_tries()
        attempt = 0             # dead-process budget: UNANSWERED dials
        rounds = 0              # backoff progression across all errors
        while True:
            connected = False
            try:
                s = socket.create_connection(self._hosts[peer],
                                             timeout=2.0)
                connected = True
                s.settimeout(min(10.0, max(
                    deadline_at - time.monotonic(), 1.0)))
                conn = TcpConnection(s)
                try:
                    _exchange_auth_flag(conn, self._secret is not None)
                    if self._secret is not None:
                        conn.authenticate(self._secret, role="client")
                    conn.send(self._handshake_frame())
                    self._validate_handshake(conn.recv(), peer)
                except Exception:
                    conn.close()
                    raise
                s.settimeout(None)
                return conn
            except wire.AuthError:
                raise
            except OSError as e:
                # only UNANSWERED dials spend the dead-process budget
                # (THRILL_TPU_RECONNECT_TRIES): a rejection after the
                # connect succeeded means the peer PROCESS is alive —
                # e.g. its one-port acceptor is mid-repair of another
                # link, or a cross-generation handshake — and must not
                # burn the budget toward a false dead verdict. The
                # heal deadline stays the overall bound.
                rounds += 1
                if not connected:
                    attempt += 1
                if (attempt >= tries
                        or time.monotonic() >= deadline_at):
                    raise ConnectionError(
                        f"rank {self.my_rank}: reconnect to rank "
                        f"{peer} failed after {attempt} unanswered "
                        f"dials / {rounds} rounds") from e
                d = policy.delay(min(rounds, 6), rng)
                faults.note("retry", _quiet=rounds > 3,
                            what="tcp.reconnect_dial", peer=peer,
                            attempt=rounds, delay_s=round(d, 4),
                            error=repr(e))
                time.sleep(min(d, max(
                    deadline_at - time.monotonic(), 0.0)))

    def _reconnect_accept(self, peer: int,
                          deadline_at: float) -> TcpConnection:
        host, port = self._hosts[self.my_rank]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host if host != "localhost" else "127.0.0.1",
                      port))
            srv.listen(4)
            srv.settimeout(0.5)
            while time.monotonic() < deadline_at:
                try:
                    s, addr = srv.accept()
                except socket.timeout:
                    continue
                s.settimeout(min(10.0, max(
                    deadline_at - time.monotonic(), 1.0)))
                conn = TcpConnection(s)
                try:
                    _exchange_auth_flag(conn, self._secret is not None)
                    if self._secret is not None:
                        conn.authenticate(self._secret, role="server")
                    self._validate_handshake(conn.recv(), peer)
                    conn.send(self._handshake_frame())
                except Exception as e:
                    # a different rank's dialer (several links healing
                    # at once) or a rogue connection: reject, keep
                    # listening — the rejected dialer retries
                    conn.close()
                    import sys
                    print(f"thrill_tpu.net.tcp: rank {self.my_rank} "
                          f"rejected reconnect from {addr}: {e}",
                          file=sys.stderr)
                    continue
                s.settimeout(None)
                return conn
            raise ConnectionError(
                f"rank {self.my_rank}: reconnect accept from rank "
                f"{peer} timed out")
        finally:
            srv.close()

    # ------------------------------------------------------------------
    # elastic membership (Group.resize transport hooks)
    # ------------------------------------------------------------------

    def _grow_transport(self, new_num_hosts: int, gen: int,
                        deadline_at: float) -> None:
        """Admit joining ranks ``[num_hosts, new_num_hosts)``: each
        joiner dials this rank's own hostlist port (the same
        lower-listens role as bootstrap and reconnect) and runs the
        authenticated ``resize_join`` handshake — rank, target
        generation, new W — before its link is trusted. The joiner's
        announced endpoint is appended to the hostlist so later link
        repairs can re-dial it."""
        if self._hosts is None:
            raise ConnectionError(
                f"rank {self.my_rank}: no hostlist endpoints (this "
                f"group was not built by construct_tcp_group); cannot "
                f"admit ranks")
        expect = set(range(self.num_hosts, new_num_hosts))
        got = _accept_resize_joins(
            self._hosts[self.my_rank], self.my_rank, expect, gen,
            new_num_hosts, self._secret, deadline_at)
        lazy = any(c._disp_supplier is not None
                   for c in self._conns.values())
        for j in sorted(got):
            conn, endpoint = got[j]
            if lazy:
                conn.set_dispatcher_supplier(self._shared_dispatcher)
            self._conns[j] = conn
            while len(self._hosts) <= j:
                self._hosts.append(("127.0.0.1", 0))
            if endpoint is not None:
                self._hosts[j] = endpoint

    def _shrink_transport(self, new_num_hosts: int) -> None:
        """Close and forget links to ranks ``>= new_num_hosts`` (they
        drained and left, or were dead already)."""
        for peer in sorted(p for p in self._conns
                           if p >= new_num_hosts):
            try:
                self._conns[peer].close()
            except OSError:
                pass
            del self._conns[peer]
        if self._hosts is not None:
            del self._hosts[new_num_hosts:]

    def _shared_dispatcher(self):
        """One async engine per group, created on first bulk frame (a
        dedicated DispatcherThread per host, reference:
        thrill/net/dispatcher_thread.hpp:60)."""
        with self._disp_lock:
            if self._disp is None:
                from .dispatcher import Dispatcher
                self._disp = Dispatcher()
                self._disp_owned = True
            return self._disp

    def enable_lazy_async(self) -> None:
        """Connections keep the blocking fast path for control frames
        and hand their fd to the shared engine on the first frame past
        the async threshold — bulk fan-out overlaps, symmetric large
        exchanges cannot deadlock on kernel buffers, and small-message
        latency is untouched."""
        for c in self._conns.values():
            c.set_dispatcher_supplier(self._shared_dispatcher)

    def attach_dispatcher(self, disp=None) -> None:
        """Eagerly drive EVERY frame through one async engine (used by
        tests and latency-insensitive bulk phases). A caller-provided
        engine stays caller-owned (close() will not close it). Once any
        engine is active for this group it cannot be replaced — attach
        before any bulk traffic, or pass no engine to reuse the
        group's own."""
        if disp is None:
            disp = self._shared_dispatcher()
        else:
            with self._disp_lock:
                if self._disp is not None and self._disp is not disp:
                    # connections may already route through the active
                    # engine; swapping under them would leave them on a
                    # closed/foreign engine — make the misuse loud
                    raise ValueError(
                        "group already has an active dispatcher; "
                        "attach before any bulk traffic or pass no "
                        "engine to reuse the group's own")
                self._disp = disp
                self._disp_owned = False
        for c in self._conns.values():
            c.attach_dispatcher(disp)

    def flush(self) -> None:
        for c in self._conns.values():
            c.flush()

    def close(self) -> None:
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        for c in self._conns.values():
            c.close()
        if self._disp is not None and self._disp_owned:
            self._disp.close()
        self._disp = None


def _async_threshold() -> int:
    """Frame size at which a connection hands its fd to the async
    engine (small control frames keep the blocking fast path — the
    reference's flow group is synchronous, only bulk streams ride the
    Dispatcher)."""
    try:
        return int(os.environ.get("THRILL_TPU_ASYNC_THRESHOLD",
                                  str(1 << 18)))
    except ValueError:
        return 1 << 18


def _async_inflight_bytes() -> int:
    """Byte cap on unretired async sends per connection (beyond it,
    send() waits — bounded — for the engine to drain). Caps pinned
    borrowed-buffer memory; a request-count cap would let ~60 giant
    frames pin unbounded bytes."""
    try:
        return int(os.environ.get("THRILL_TPU_ASYNC_INFLIGHT_BYTES",
                                  str(64 << 20)))
    except ValueError:
        return 64 << 20


def _borrow_check(buf):
    """Debug guard for the zero-copy borrow contract (send() docstring):
    with THRILL_TPU_NET_DEBUG=1, checksum the borrowed buffer at
    enqueue and verify it at retirement, so a caller mutating a staging
    array before flush() fails loudly instead of corrupting frames
    (the MAC is computed before the borrow, so corruption would even be
    authenticated)."""
    if os.environ.get("THRILL_TPU_NET_DEBUG", "0") != "1":
        return None
    import zlib
    want = zlib.crc32(buf)

    def check(buf=buf, want=want):
        if zlib.crc32(buf) != want:
            raise RuntimeError(
                "thrill_tpu.net.tcp: borrowed send buffer was mutated "
                "before the async write retired — callers must not "
                "reuse staging buffers until flush()")
    return check


def _exchange_auth_flag(conn: TcpConnection, have_secret: bool) -> None:
    """1-byte preamble so an asymmetric THRILL_TPU_SECRET configuration
    fails fast with the real cause instead of a generic bootstrap
    timeout (one side waiting for a challenge that never comes)."""
    conn.sock.sendall(b"\x01" if have_secret else b"\x00")
    peer = conn._recv_exact(1)
    if peer not in (b"\x00", b"\x01"):
        raise ConnectionError(f"tcp: bad auth preamble {peer!r}")
    if (peer == b"\x01") != have_secret:
        raise wire.AuthError(
            "tcp: THRILL_TPU_SECRET is configured on one side of the "
            "connection but not the other — set the same secret on "
            "every host (or on none)")


def _resize_frame(rank: int, gen: int, new_w: int,
                  endpoint: Optional[Tuple[str, int]] = None) -> dict:
    """The ``resize_join`` handshake frame: like the reconnect
    handshake (rank, generation, fresh frame seq) plus the NEW group
    width, so both sides prove they are executing the SAME membership
    change, not a reconnect or a different resize."""
    f = {"resize_join": int(rank), "gen": int(gen),
         "num_hosts": int(new_w), "seq": 0}
    if endpoint is not None:
        f["host"], f["port"] = str(endpoint[0]), int(endpoint[1])
    return f


def _validate_resize_frame(obj: Any, gen: int, new_w: int,
                           want_ranks) -> int:
    if not (isinstance(obj, dict) and "resize_join" in obj):
        raise ConnectionError(f"bad resize handshake {obj!r}")
    j = int(obj["resize_join"])
    if j not in want_ranks:
        raise ConnectionError(
            f"resize handshake from unexpected rank {j} "
            f"(awaiting {sorted(want_ranks)})")
    if int(obj.get("seq", 0)) != 0:
        raise ConnectionError(
            f"resize handshake with nonzero frame seq "
            f"{obj.get('seq')!r} — only fresh sessions join a group")
    if int(obj.get("gen", -1)) != int(gen):
        raise ConnectionError(
            f"resize handshake generation mismatch: peer targets gen "
            f"{obj.get('gen')!r}, this rank gen {gen}")
    if int(obj.get("num_hosts", -1)) != int(new_w):
        raise ConnectionError(
            f"resize handshake width mismatch: peer targets W="
            f"{obj.get('num_hosts')!r}, this rank W={new_w}")
    return j


def _accept_resize_joins(endpoint: Tuple[str, int], my_rank: int,
                         expect, gen: int, new_w: int,
                         secret: Optional[bytes],
                         deadline_at: float) -> dict:
    """Accept ``resize_join`` dials from every rank in ``expect`` on
    ``endpoint`` (this rank's own hostlist port — the reconnect
    role). Returns ``{rank: (conn, joiner_endpoint_or_None)}``.
    Rogue/mismatched connections are rejected and the listener keeps
    going, exactly like the reconnect acceptor."""
    expect = set(expect)
    got: dict = {}
    if not expect:
        return got
    host, port = endpoint
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        srv.bind((host if host != "localhost" else "127.0.0.1", port))
        srv.listen(4)
        srv.settimeout(0.5)
        while expect - set(got):
            if time.monotonic() >= deadline_at:
                raise ConnectionError(
                    f"rank {my_rank}: resize accept timed out awaiting "
                    f"ranks {sorted(expect - set(got))} "
                    f"(THRILL_TPU_RESIZE_TIMEOUT_S)")
            try:
                s, addr = srv.accept()
            except socket.timeout:
                continue
            s.settimeout(min(10.0, max(
                deadline_at - time.monotonic(), 1.0)))
            conn = TcpConnection(s)
            try:
                _exchange_auth_flag(conn, secret is not None)
                if secret is not None:
                    conn.authenticate(secret, role="server")
                obj = conn.recv()
                j = _validate_resize_frame(obj, gen, new_w,
                                           expect - set(got))
                conn.send(_resize_frame(my_rank, gen, new_w))
            except wire.AuthError:
                conn.close()
                raise               # definitive: never degrade auth
            except Exception as e:
                conn.close()
                import sys
                print(f"thrill_tpu.net.tcp: rank {my_rank} rejected "
                      f"resize join from {addr}: {e}", file=sys.stderr)
                continue
            s.settimeout(None)
            ep = None
            if obj.get("host") is not None and obj.get("port"):
                ep = (str(obj["host"]), int(obj["port"]))
            got[j] = (conn, ep)
        return got
    finally:
        srv.close()


def _dial_resize_join(endpoint: Tuple[str, int], my_rank: int,
                      peer: int, gen: int, new_w: int,
                      my_endpoint: Tuple[str, int],
                      secret: Optional[bytes],
                      deadline_at: float) -> TcpConnection:
    """One joiner->member dial with the authenticated ``resize_join``
    handshake, retried under the shared full-jitter backoff until the
    resize deadline (the member may still be draining its current
    generation when the joiner starts dialing)."""
    import random
    policy = default_policy(max_attempts=1 << 30,
                            base_delay_s=0.05, max_delay_s=1.0)
    rng = random.Random(f"resize:{my_rank}:{peer}")
    rounds = 0
    while True:
        try:
            s = socket.create_connection(endpoint, timeout=2.0)
            s.settimeout(min(10.0, max(
                deadline_at - time.monotonic(), 1.0)))
            conn = TcpConnection(s)
            try:
                _exchange_auth_flag(conn, secret is not None)
                if secret is not None:
                    conn.authenticate(secret, role="client")
                conn.send(_resize_frame(my_rank, gen, new_w,
                                        my_endpoint))
                _validate_resize_frame(conn.recv(), gen, new_w,
                                       (peer,))
            except Exception:
                conn.close()
                raise
            s.settimeout(None)
            return conn
        except wire.AuthError:
            raise
        except OSError as e:
            rounds += 1
            if time.monotonic() >= deadline_at:
                raise ConnectionError(
                    f"rank {my_rank}: resize join to rank {peer} at "
                    f"{endpoint} failed after {rounds} rounds") from e
            d = policy.delay(min(rounds, 6), rng)
            faults.note("retry", _quiet=rounds > 3,
                        what="tcp.resize_dial", peer=peer,
                        attempt=rounds, delay_s=round(d, 4),
                        error=repr(e))
            time.sleep(min(d, max(
                deadline_at - time.monotonic(), 0.0)))


def join_tcp_group(rank: int, hosts: List[Tuple[str, int]],
                   generation: int,
                   timeout: Optional[float] = None,
                   secret: Optional[bytes] = None) -> TcpGroup:
    """Bootstrap of a JOINING rank into a live group mid-resize.

    ``hosts`` is the NEW full hostlist (width W'); this process takes
    rank ``rank`` (>= the old width). It dials every lower rank — the
    live members, which are inside ``Group.resize`` accepting on their
    own hostlist ports, plus any lower-ranked fellow joiner — and
    accepts dials from higher-ranked fellow joiners, so a multi-rank
    grow wires the same full mesh bootstrap does. The caller then runs
    ``begin_generation(generation)``: the joiner's first collective is
    the generation barrier that commits the new membership everywhere.
    """
    p = len(hosts)
    if not (0 <= rank < p):
        raise ValueError(f"joining rank {rank} outside hostlist "
                         f"of {p}")
    faults.check(F_RESIZE, new=p, gen=int(generation), rank=rank,
                 side="join")
    deadline_at = time.monotonic() + (resize_timeout_s()
                                      if timeout is None
                                      else float(timeout))
    conns: Dict[int, TcpConnection] = {}
    try:
        for peer in range(rank):
            conns[peer] = _dial_resize_join(
                hosts[peer], rank, peer, generation, p, hosts[rank],
                secret, deadline_at)
        for j, (conn, _) in _accept_resize_joins(
                hosts[rank], rank, range(rank + 1, p), generation, p,
                secret, deadline_at).items():
            conns[j] = conn
    except BaseException:
        for c in conns.values():
            try:
                c.close()
            except OSError:
                pass
        raise
    group = TcpGroup(rank, p, conns)
    group._hosts = list(hosts)
    group._secret = secret
    if os.environ.get("THRILL_TPU_ASYNC_NET", "1") != "0":
        group.enable_lazy_async()
    from . import heartbeat
    group._heartbeat = heartbeat.maybe_start(group)
    # orphan-run adoption: a joiner replacing a departed rank claims
    # that rank's committed EM runs (core/em_runs.py) so the first
    # elastic-generation sort reuses them instead of re-forming them.
    # Best-effort and strictly additive — a failed scan only means
    # the runs re-form, exactly as before adoption existed.
    ckpt_dir = os.environ.get("THRILL_TPU_CKPT_DIR", "")
    if ckpt_dir:
        try:
            from ..core.em_runs import adopt_orphan_runs
            adopt_orphan_runs(ckpt_dir, rank)
        except Exception as e:
            faults.note("recovery", what="em_runs.adopt_failed",
                        error=repr(e)[:200])
    return group


def parse_hostlist(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in s.replace(",", " ").split():
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    return out


def construct_tcp_group(rank: int, hosts: List[Tuple[str, int]],
                        timeout: Optional[float] = None,
                        secret: Optional[bytes] = None) -> TcpGroup:
    """Full-mesh bootstrap: rank j dials every i < j; i accepts j..p-1.

    With ``secret`` every connection is mutually HMAC-authenticated
    before the rank announcement is trusted (and pickled payloads are
    enabled); without it the non-executing codec is the only format.
    """
    p = len(hosts)
    if p == 1:
        return TcpGroup(0, 1, {})
    # bootstrap deadline is dead-peer DIAGNOSTIC, load-scaled and
    # RE-evaluated as loops progress (fixed when the caller passed an
    # explicit timeout): under contention peer processes legitimately
    # take minutes to even reach their connect loop (imports + jax
    # init), and a load spike arriving mid-bootstrap must stretch an
    # already-started wait. The per-connection HANDSHAKE cap guards
    # against a silent/rogue connection parking the accept thread —
    # it scales too (a healthy peer can be descheduled >10 s at 6x).
    from ..common.timeouts import budget_fn
    budget = budget_fn(timeout, 60.0)
    hs_cap = (budget_fn(None, 10.0) if timeout is None
              else (lambda: min(10.0, float(timeout))))
    conns: Dict[int, TcpConnection] = {}
    lock = threading.Lock()
    errors: List[BaseException] = []

    def accept_side():
        try:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((hosts[rank][0] if hosts[rank][0] != "localhost"
                      else "127.0.0.1", hosts[rank][1]))
            srv.listen(p)
            srv.settimeout(1.0)              # poll slice; budget below
            expected = p - 1 - rank          # ranks > mine dial in
            accepted = 0
            accept_start = time.time()
            while accepted < expected:
                if time.time() - accept_start > budget():
                    raise TimeoutError(
                        f"rank {rank}: bootstrap accept timed out")
                try:
                    s, addr = srv.accept()
                except socket.timeout:
                    continue
                # accepted sockets do NOT inherit the listener timeout;
                # without one, a silent connection would park this
                # thread in recv forever and wedge the whole bootstrap
                s.settimeout(hs_cap())
                conn = TcpConnection(s)
                try:
                    _exchange_auth_flag(conn, secret is not None)
                    if secret is not None:
                        conn.authenticate(secret, role="server")
                    peer = conn.recv()       # rank announcement
                    with lock:
                        if (type(peer) is not int or not rank < peer < p
                                or peer in conns):
                            raise ConnectionError(
                                f"invalid rank announcement {peer!r}")
                        conns[peer] = conn
                except Exception as e:
                    # reject the rogue/failed peer, keep accepting
                    conn.close()
                    import sys
                    print(f"thrill_tpu.net.tcp: rank {rank} rejected "
                          f"peer {addr}: {e}", file=sys.stderr)
                    continue
                s.settimeout(None)           # handshake done: blocking
                accepted += 1
            srv.close()
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    acceptor = threading.Thread(target=accept_side, daemon=True)
    acceptor.start()

    # dials retry under the shared backoff policy (full jitter spreads
    # a whole cluster's simultaneous restarts instead of herding them);
    # the load-scaled budget stays the overall deadline, so attempts
    # continue until the budget expires, not a fixed count.
    dial_policy = default_policy(max_attempts=1 << 30,
                                 base_delay_s=0.05, max_delay_s=1.0)
    dial_start = time.time()
    for peer in range(rank):                 # dial every lower rank
        attempt = 0
        rng = None
        while True:
            try:
                faults.check(_F_CONNECT, peer=peer)
                s = socket.create_connection(hosts[peer], timeout=2.0)
                s.settimeout(hs_cap())
                conn = TcpConnection(s)
                _exchange_auth_flag(conn, secret is not None)
                if secret is not None:
                    conn.authenticate(secret, role="client")
                conn.send(rank)
                s.settimeout(None)           # handshake done: blocking
                with lock:
                    conns[peer] = conn
                break
            except wire.AuthError:
                # auth failure is definitive (secret mismatch), not a
                # transient dial error — fail fast with the real cause
                raise
            except OSError as e:
                if (isinstance(e, faults.InjectedFault)
                        and os.environ.get("THRILL_TPU_RETRY",
                                           "1") == "0"):
                    # detection-only runs: injected dial faults must
                    # SURFACE. (Plain connection-refused keeps the
                    # budgeted loop — waiting for peers that haven't
                    # started listening is bootstrap, not retry.)
                    raise
                if time.time() - dial_start > budget():
                    raise TimeoutError(
                        f"rank {rank}: cannot reach rank {peer} at "
                        f"{hosts[peer]}") from e
                if rng is None:
                    import random
                    rng = random.Random(f"dial:{rank}:{peer}")
                d = dial_policy.delay(min(attempt, 6), rng)
                # staggered starts make many dial retries NORMAL at
                # bootstrap: count every one, log only sparsely
                faults.note("retry",
                            _quiet=not (attempt < 3
                                        or attempt % 32 == 0),
                            what="tcp.bootstrap_dial",
                            attempt=attempt + 1, peer=peer,
                            delay_s=round(d, 4), error=repr(e))
                attempt += 1
                time.sleep(d)

    join_start = time.time()
    while acceptor.is_alive() and time.time() - join_start <= budget():
        acceptor.join(timeout=1.0)
    if acceptor.is_alive():
        raise TimeoutError(f"rank {rank}: bootstrap accept timed out")
    if errors:
        raise errors[0]
    assert len(conns) == p - 1
    group = TcpGroup(rank, p, conns)
    # remember the endpoints + secret: the generation heal re-dials a
    # dropped link through the same authenticated handshake
    group._hosts = list(hosts)
    group._secret = secret
    # lazy async engine on by default: control frames stay blocking
    # (fast path), bulk frames ride the dispatcher
    # (THRILL_TPU_ASYNC_NET=0 disables; THRILL_TPU_ASYNC_THRESHOLD
    # tunes the cutover)
    if os.environ.get("THRILL_TPU_ASYNC_NET", "1") != "0":
        group.enable_lazy_async()
    # liveness heartbeats (net/heartbeat.py, THRILL_TPU_HEARTBEAT_S):
    # a kill -9'd peer becomes a fast attributable ClusterAbort even
    # between collectives, instead of a hang at the next one
    from . import heartbeat
    group._heartbeat = heartbeat.maybe_start(group)
    return group


def construct_from_env() -> Optional[TcpGroup]:
    """THRILL_TPU_RANK/HOSTLIST -> TcpGroup (None when unset)."""
    hostlist = os.environ.get("THRILL_TPU_HOSTLIST")
    if not hostlist:
        return None
    rank = int(os.environ.get("THRILL_TPU_RANK", "0"))
    secret = wire.secret_from_env()
    if secret is None:
        import sys
        print("thrill_tpu.net.tcp: THRILL_TPU_SECRET unset — "
              "connections are unauthenticated and restricted to the "
              "non-executing wire codec", file=sys.stderr)
    return construct_tcp_group(rank, parse_hostlist(hostlist),
                               secret=secret)
