"""MPI transport backend: control plane + bulk byte-frame data plane.

Equivalent of the reference's net/mpi backend
(/root/reference/thrill/net/mpi/group.cpp:26,654-660 and
net/mpi/dispatcher.cpp:67): MPI as a Connection/Group transport. Three
defining disciplines:

* **Serialized threading**: the reference initializes
  ``MPI_THREAD_SERIALIZED`` and guards every MPI call with one global
  mutex (``g_mutex``). Here ``_MPI_LOCK`` wraps each mpi4py call the
  same way, so any number of framework threads can share the library.

* **NO blocking in send** (the round-3 advisor's deadlock): messages
  above MPI's eager threshold complete their Isend only when the
  matching receive posts (rendezvous), and both the shared collectives
  (e.g. Bruck all_gather, net/group.py) and the multiplexer's
  host_exchange have EVERY rank send before it receives. A send that
  waits for isend completion therefore deadlocks the whole world.
  Instead ``send`` queues the request on a per-world engine and returns;
  pending isends are completed LAZILY — tested inside ``recv``'s Iprobe
  poll loop, opportunistically at the next send, and exhaustively in
  ``flush``. This mirrors the reference's async MPI dispatcher, which
  parks Isend requests and polls ``MPI_Testsome``
  (net/mpi/dispatcher.cpp:67).

* **Byte-frame transport**: payloads travel as raw byte buffers over
  ``Isend``/``Irecv`` with ``MPI.BYTE`` (the bulk data plane the
  round-3 verdict called for), framed by the non-executing wire codec
  (net/wire.py) — the same frames the TCP data plane ships. Pickle
  inside the codec is enabled: MPI ranks are co-launched instances of
  one program under mpirun, the identical trust model the reference
  assumes for its MPI world. The engine keeps an in-flight byte
  account: over the cap, send() reaps aggressively while completions
  keep arriving, but it NEVER blocks — blocking over the cap would
  re-create the rendezvous deadlock. The cap is therefore a drain
  accelerator, not a hard memory bound; the actual bound is
  structural: each group queues at most one exchange's outgoing
  frames (the collectives and host_exchange are phase-synchronous,
  so a rank's pending set peaks at its own per-phase send volume —
  data the caller holds materialized anyway). The reference's async
  MPI dispatcher queues posted Isends the same unbounded way
  (net/mpi/dispatcher.cpp:67).

Groups share ``COMM_WORLD`` as tag namespaces (group_tag = the MPI
message tag), exactly how the reference multiplexes its kGroupCount
groups over one MPI world (flow group 0, data group 1).

SDK-gated like vfs/s3_file.py: mpi4py is not in this image, so
``construct()`` raises with the actionable fix unless an MPI module is
injected. Tests inject a STRICT-rendezvous socket-backed fake world
(tests/net/fake_mpi.py) and spawn real OS processes over it, so the
backend's queueing/reaping state machine is exercised multi-process;
a real deployment installs mpi4py and runs under mpirun.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, List, Optional

from . import wire
from .group import Connection, Group

#: serialized-MPI discipline: one lock around every MPI call (and the
#: engine's queue, which is only touched around MPI calls anyway)
_MPI_LOCK = threading.Lock()

#: injection point — tests (or embedders) may set this to an object
#: exposing the mpi4py.MPI surface used here (COMM_WORLD, Isend, Irecv,
#: Iprobe, Status, BYTE, ...)
MPI: Optional[Any] = None


class MpiUnavailable(RuntimeError):
    pass


def _load_mpi():
    global MPI
    if MPI is not None:
        return MPI
    try:
        from mpi4py import MPI as _mpi  # type: ignore
    except ImportError as e:
        raise MpiUnavailable(
            "MPI backend requires mpi4py, which is not installed in "
            "this image. Install mpi4py and launch with "
            "`mpirun -np <P> python your_program.py`, or set "
            "THRILL_TPU_NET=tcp to use the built-in TCP backend "
            "(reference parity: thrill/net/mpi/group.cpp)") from e
    # the reference demands at least MPI_THREAD_SERIALIZED
    if hasattr(_mpi, "Query_thread") and \
            _mpi.Query_thread() < _mpi.THREAD_SERIALIZED:
        raise MpiUnavailable(
            "MPI library initialized below MPI_THREAD_SERIALIZED; the "
            "framework's serialized-call discipline needs it "
            "(reference: MPI_Init_thread, net/mpi/group.cpp:26)")
    MPI = _mpi
    return MPI


def _req_done(req) -> bool:
    """Poll a request once, normalizing the two mpi4py shapes:
    uppercase Test() -> bool and lowercase test() -> (flag, msg)."""
    res = req.Test() if hasattr(req, "Test") else req.test()
    return res[0] if isinstance(res, tuple) else bool(res)


class _SendEngine:
    """Per-world ledger of in-flight Isend requests.

    Keeps (request, payload) pairs alive until MPI reports completion —
    the payload buffer must outlive the Isend (MPI reads it lazily
    during rendezvous). ``reap_locked`` is called from every send and
    every recv poll (caller holds ``_MPI_LOCK``); ``flush`` completes
    everything and is the only place allowed to wait, because at flush
    points (group close / explicit barrier) every queued message's
    matching receive is already posted or will be without our help.
    """

    #: drain-accelerator threshold (bytes), NOT a hard memory bound:
    #: over this, send() keeps reaping while completions arrive, but
    #: never blocks without progress (see module docstring — the hard
    #: bound is the caller's per-phase send volume)
    CAP_BYTES = int(os.environ.get("THRILL_TPU_MPI_INFLIGHT_CAP",
                                   str(32 << 20)))

    #: async-progress poll period. Lazy reaping alone starves rendezvous
    #: completion when the OWNING thread blocks outside the transport
    #: with an isend still pending — e.g. inside an XLA cross-process
    #: collective, where no recv poll ever runs while the peer waits for
    #: this rank's DATA. Real MPI deployments run an async progress
    #: thread for exactly this; ours honors the serialized-call lock.
    PROGRESS_POLL_S = 2e-3

    def __init__(self) -> None:
        self.pending: collections.deque = collections.deque()
        self.pending_bytes = 0
        self._progress_wake = threading.Event()
        self._progress_thread: Optional[threading.Thread] = None
        self._progress_on = os.environ.get(
            "THRILL_TPU_MPI_PROGRESS", "1") != "0"

    def note_send_locked(self, req, payload) -> None:
        self.pending.append((req, payload))
        self.pending_bytes += len(payload)
        if self._progress_on:
            if self._progress_thread is None:
                self._progress_thread = threading.Thread(
                    target=self._progress_loop,
                    name="mpi-progress", daemon=True)
                self._progress_thread.start()
            self._progress_wake.set()

    def _progress_loop(self) -> None:
        """Daemon: complete pending isends while the app threads are
        parked elsewhere. Parks itself (Event) whenever the pending set
        drains, so an idle world costs nothing. MUST outlive transport
        errors: a raising request was already dropped by reap_locked,
        so note it and keep pumping — a dead daemon would silently
        reinstate the rendezvous-starvation wedge, and the app threads
        surface the peer failure through their own sends/recvs."""
        while True:
            self._progress_wake.wait()
            try:
                with _MPI_LOCK:
                    self.reap_locked()
                    if not self.pending:
                        self._progress_wake.clear()
            except Exception as e:
                import sys
                print(f"thrill_tpu.net.mpi: async progress dropped a "
                      f"failing isend ({e!r}); the peer error will "
                      f"surface on the owning thread's next transport "
                      f"call", file=sys.stderr)
            time.sleep(self.PROGRESS_POLL_S)

    def reap_locked(self) -> int:
        """One non-blocking pass over pending isends; returns how many
        completed (and were dropped). A request whose Test RAISES is
        dropped with its byte account settled before the error
        propagates — a dead peer's send must not inflate
        ``pending_bytes`` forever."""
        done = 0
        for _ in range(len(self.pending)):
            req, payload = self.pending.popleft()
            try:
                ok = _req_done(req)
            except Exception:
                self.pending_bytes -= len(payload)
                raise
            if ok:
                self.pending_bytes -= len(payload)
                done += 1
            else:
                self.pending.append((req, payload))
        return done

    def enforce_cap(self) -> None:
        """Reap while over the cap AND completions keep arriving. Stops
        at the first no-progress pass — never a liveness hazard."""
        while True:
            with _MPI_LOCK:
                if self.pending_bytes <= self.CAP_BYTES:
                    return
                if self.reap_locked() == 0:
                    return

    def flush(self, timeout_s: float | None = None) -> None:
        """Complete every pending isend (group close / barrier point).

        The deadline is a DIAGNOSTIC for a vanished peer, not flow
        control — real MPI_Waitall blocks forever here — so it scales
        with machine load (common/timeouts.py): a peer that is merely
        slow under contention must not read as dead."""
        from ..common.timeouts import budget_fn
        # RE-evaluated each poll when defaulted (cadence-limited
        # loadavg read): a load spike arriving near the distress point
        # must stretch an already-started wait, not just future ones
        budget = budget_fn(timeout_s, 120.0)
        start = time.monotonic()
        while True:
            with _MPI_LOCK:
                self.reap_locked()
                if not self.pending:
                    return
            b = budget()
            if time.monotonic() - start > b:
                raise TimeoutError(
                    f"MPI flush: {len(self.pending)} isends still "
                    f"pending after {b:.0f}s (peer gone or "
                    f"matching recv never posted)")
            time.sleep(MpiConnection.POLL_S)


class MpiConnection(Connection):
    """One peer within one group (tag namespace)."""

    # poll interval for the Iprobe spin; the reference's dispatcher
    # polls Testsome in a loop the same way (net/mpi/dispatcher.cpp:67)
    POLL_S = 50e-6

    def __init__(self, mpi, comm, peer: int, tag: int,
                 engine: _SendEngine) -> None:
        self.mpi = mpi
        self.comm = comm
        self.peer = peer
        self.tag = tag
        self.engine = engine

    def send(self, obj: Any) -> int:
        """Queue the framed payload as an Isend and RETURN the
        serialized byte count (the wire truth, measured where the
        frame is encoded — the multiplexer's accounting reads it
        instead of paying a second serialization) — completion is lazy
        (engine reaps in recv polls / flush). See module docstring for
        why waiting here deadlocks rendezvous MPI."""
        payload = wire.dumps(obj, allow_pickle=True)
        with _MPI_LOCK:
            req = self.comm.Isend([payload, self.mpi.BYTE],
                                  dest=self.peer, tag=self.tag)
            self.engine.note_send_locked(req, payload)
            self.engine.reap_locked()
        self.engine.enforce_cap()
        return len(payload)

    def recv(self) -> Any:
        """Iprobe poll -> sized Irecv -> Test poll; every poll iteration
        also reaps pending isends (their lazy completion point)."""
        st = self.mpi.Status()
        while True:
            with _MPI_LOCK:
                self.engine.reap_locked()
                if self.comm.Iprobe(source=self.peer, tag=self.tag,
                                    status=st):
                    n = st.Get_count(self.mpi.BYTE)
                    buf = bytearray(n)
                    rreq = self.comm.Irecv([buf, self.mpi.BYTE],
                                           source=self.peer,
                                           tag=self.tag)
                    break
            time.sleep(self.POLL_S)
        while True:
            with _MPI_LOCK:
                self.engine.reap_locked()
                done = _req_done(rreq)
            if done:
                return wire.loads(bytes(buf), allow_pickle=True)
            time.sleep(self.POLL_S)


class MpiGroup(Group):
    """A tag namespace over an MPI communicator."""

    def __init__(self, mpi, comm, group_tag: int = 0,
                 engine: Optional[_SendEngine] = None) -> None:
        with _MPI_LOCK:
            rank = comm.Get_rank()
            size = comm.Get_size()
        super().__init__(rank, size)
        self.mpi = mpi
        self.comm = comm
        self.group_tag = group_tag
        self.engine = engine if engine is not None else _SendEngine()
        self._conns = {}

    def connection(self, peer: int) -> MpiConnection:
        if peer == self.my_rank or not 0 <= peer < self.num_hosts:
            raise ValueError(f"bad peer {peer} (rank {self.my_rank} "
                             f"of {self.num_hosts})")
        conn = self._conns.get(peer)
        if conn is None:
            conn = self._conns[peer] = MpiConnection(
                self.mpi, self.comm, peer, self.group_tag, self.engine)
        return conn

    def flush(self) -> None:
        """Complete all pending isends issued through this group's
        world engine (safe wherever every sent message's receive is
        guaranteed — barriers, teardown)."""
        self.engine.flush()

    def close(self) -> None:
        self.flush()


def construct(group_count: int = 2) -> List[MpiGroup]:
    """kGroupCount tag-namespace groups over COMM_WORLD (reference:
    flow group 0 + data group 1, net/manager.hpp:61-92). All groups of
    one world share one send engine — pending isends are a per-world
    resource, like the reference dispatcher's request table."""
    mpi = _load_mpi()
    engine = _SendEngine()
    return [MpiGroup(mpi, mpi.COMM_WORLD, group_tag=g, engine=engine)
            for g in range(group_count)]


def available() -> bool:
    try:
        _load_mpi()
        return True
    except MpiUnavailable:
        return False
