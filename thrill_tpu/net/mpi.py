"""MPI transport backend for the host control plane.

Equivalent of the reference's net/mpi backend
(/root/reference/thrill/net/mpi/group.cpp:26,654-660 and
net/mpi/dispatcher.cpp:67): MPI as a Connection/Group transport, with
the reference's two defining disciplines mirrored exactly:

* **Serialized threading**: the reference initializes
  ``MPI_THREAD_SERIALIZED`` and guards every MPI call with one global
  mutex (``g_mutex``). Here ``_MPI_LOCK`` wraps each mpi4py call the
  same way, so any number of framework threads can share the library.
* **Polling receives**: a blocking ``MPI_Recv`` under the global lock
  would deadlock other threads' sends, so receives spin on ``Iprobe``
  + short sleeps, taking the lock only per poll — the reference's
  sync-ops-spin-on-async-dispatcher pattern (net/mpi/group.cpp:56-80).

Groups share ``COMM_WORLD`` as tag namespaces (group_tag = the MPI
message tag), exactly how the reference multiplexes its kGroupCount
groups over one MPI world (flow group 0, data group 1).

SDK-gated like vfs/s3_file.py: mpi4py is not in this image, so
``construct()`` raises with the actionable fix unless an MPI module is
injected (tests inject an in-process fake; a real deployment just
installs mpi4py and runs under mpirun).
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Optional

from .group import Connection, Group

#: serialized-MPI discipline: one lock around every MPI call
_MPI_LOCK = threading.Lock()

#: injection point — tests (or embedders) may set this to an object
#: exposing the mpi4py.MPI surface used here (COMM_WORLD, Iprobe...)
MPI: Optional[Any] = None


class MpiUnavailable(RuntimeError):
    pass


def _load_mpi():
    global MPI
    if MPI is not None:
        return MPI
    try:
        from mpi4py import MPI as _mpi  # type: ignore
    except ImportError as e:
        raise MpiUnavailable(
            "MPI backend requires mpi4py, which is not installed in "
            "this image. Install mpi4py and launch with "
            "`mpirun -np <P> python your_program.py`, or set "
            "THRILL_TPU_NET=tcp to use the built-in TCP backend "
            "(reference parity: thrill/net/mpi/group.cpp)") from e
    # the reference demands at least MPI_THREAD_SERIALIZED
    if hasattr(_mpi, "Query_thread") and \
            _mpi.Query_thread() < _mpi.THREAD_SERIALIZED:
        raise MpiUnavailable(
            "MPI library initialized below MPI_THREAD_SERIALIZED; the "
            "framework's serialized-call discipline needs it "
            "(reference: MPI_Init_thread, net/mpi/group.cpp:26)")
    MPI = _mpi
    return MPI


class MpiConnection(Connection):
    """One peer within one group (tag namespace)."""

    # poll interval for the Iprobe spin; the reference's dispatcher
    # polls Testsome in a loop the same way (net/mpi/dispatcher.cpp:67)
    POLL_S = 50e-6

    def __init__(self, comm, peer: int, tag: int) -> None:
        self.comm = comm
        self.peer = peer
        self.tag = tag

    def send(self, obj: Any) -> None:
        # non-blocking send + completion poll, same discipline as recv:
        # a blocking MPI_Send above the eager threshold would park in
        # rendezvous while HOLDING the global lock (deadlocking the
        # Iprobe poll that drains the matching inbound message) — the
        # reference issues MPI_Isend through its dispatcher for exactly
        # this reason (net/mpi/dispatcher.cpp:67)
        with _MPI_LOCK:
            req = self.comm.isend(obj, dest=self.peer, tag=self.tag)
        while True:
            with _MPI_LOCK:
                res = req.test()
            done = res[0] if isinstance(res, tuple) else bool(res)
            if done:
                return
            time.sleep(self.POLL_S)

    def recv(self) -> Any:
        while True:
            with _MPI_LOCK:
                if self.comm.Iprobe(source=self.peer, tag=self.tag):
                    return self.comm.recv(source=self.peer,
                                          tag=self.tag)
            time.sleep(self.POLL_S)


class MpiGroup(Group):
    """A tag namespace over an MPI communicator."""

    def __init__(self, comm, group_tag: int = 0) -> None:
        with _MPI_LOCK:
            rank = comm.Get_rank()
            size = comm.Get_size()
        super().__init__(rank, size)
        self.comm = comm
        self.group_tag = group_tag
        self._conns = {}

    def connection(self, peer: int) -> MpiConnection:
        if peer == self.my_rank or not 0 <= peer < self.num_hosts:
            raise ValueError(f"bad peer {peer} (rank {self.my_rank} "
                             f"of {self.num_hosts})")
        conn = self._conns.get(peer)
        if conn is None:
            conn = self._conns[peer] = MpiConnection(
                self.comm, peer, self.group_tag)
        return conn


def construct(group_count: int = 2) -> List[MpiGroup]:
    """kGroupCount tag-namespace groups over COMM_WORLD (reference:
    flow group 0 + data group 1, net/manager.hpp:61-92)."""
    mpi = _load_mpi()
    return [MpiGroup(mpi.COMM_WORLD, group_tag=g)
            for g in range(group_count)]


def available() -> bool:
    try:
        _load_mpi()
        return True
    except MpiUnavailable:
        return False
