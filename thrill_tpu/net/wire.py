"""Non-executing wire codec + HMAC connection authentication.

The control-plane sockets (net/tcp.py) originally framed raw pickle —
any process able to reach the port could execute code via a crafted
payload. This module provides:

- ``dumps``/``loads``: a small self-describing binary codec for the
  values collectives actually ship (None, bool, int, float, str, bytes,
  tuple, list, dict, numpy scalars/arrays). Decoding never executes
  code. Arbitrary objects are only ever pickled when the connection is
  *authenticated* (``allow_pickle=True``), and an unauthenticated
  receiver refuses pickle frames outright.
- ``mutual_auth``: role-bound HMAC-SHA256 challenge-response in both
  directions over a shared secret, modeled on
  multiprocessing.connection's deliver/answer challenge (role binding
  defeats reflection).

Reference analog: the reference trusts its cluster network (raw
sockets, thrill/net/tcp/construct.cpp); we keep the trusted-cluster
fast path but gate code-executing deserialization behind the secret.
"""

from __future__ import annotations

import hmac
import io
import os
import pickle
import struct
from typing import Any, Callable, Optional

import numpy as np

_MAX_DEPTH = 100

# type tags
_T_NONE = b"N"
_T_TRUE = b"T"
_T_FALSE = b"F"
_T_INT = b"I"       # signed big int: 4-byte len + bytes
_T_FLOAT = b"f"     # 8-byte double
_T_STR = b"s"
_T_BYTES = b"b"
_T_TUPLE = b"t"
_T_LIST = b"l"
_T_DICT = b"d"
_T_NDARRAY = b"a"   # dtype-str, shape, raw bytes
_T_NPSCALAR = b"n"  # dtype-str, raw bytes
_T_PICKLE = b"P"    # authenticated connections only


def _w_len(buf: io.BytesIO, n: int) -> None:
    buf.write(struct.pack("<I", n))


def _w_bytes(buf: io.BytesIO, b: bytes) -> None:
    _w_len(buf, len(b))
    buf.write(b)


def _encode(buf: io.BytesIO, obj: Any, allow_pickle: bool,
            depth: int) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("wire: nesting too deep")
    if obj is None:
        buf.write(_T_NONE)
    elif obj is True:
        buf.write(_T_TRUE)
    elif obj is False:
        buf.write(_T_FALSE)
    elif type(obj) is int:
        raw = obj.to_bytes((obj.bit_length() + 8) // 8 + 1, "little",
                           signed=True)
        buf.write(_T_INT)
        _w_bytes(buf, raw)
    elif type(obj) is float:
        buf.write(_T_FLOAT)
        buf.write(struct.pack("<d", obj))
    elif type(obj) is str:
        buf.write(_T_STR)
        _w_bytes(buf, obj.encode("utf-8"))
    elif type(obj) is bytes:
        buf.write(_T_BYTES)
        _w_bytes(buf, obj)
    elif type(obj) is tuple or type(obj) is list:
        buf.write(_T_TUPLE if type(obj) is tuple else _T_LIST)
        _w_len(buf, len(obj))
        for x in obj:
            _encode(buf, x, allow_pickle, depth + 1)
    elif type(obj) is dict:
        buf.write(_T_DICT)
        _w_len(buf, len(obj))
        for k, v in obj.items():
            _encode(buf, k, allow_pickle, depth + 1)
            _encode(buf, v, allow_pickle, depth + 1)
    elif isinstance(obj, np.ndarray) and obj.dtype.hasobject is False:
        a = np.ascontiguousarray(obj)
        buf.write(_T_NDARRAY)
        _w_bytes(buf, a.dtype.str.encode())
        _w_len(buf, a.ndim)
        for d in a.shape:
            _w_len(buf, d)
        _w_bytes(buf, a.tobytes())
    elif isinstance(obj, np.generic) and not isinstance(obj, np.object_):
        buf.write(_T_NPSCALAR)
        _w_bytes(buf, obj.dtype.str.encode())
        _w_bytes(buf, obj.tobytes())
    elif allow_pickle:
        buf.write(_T_PICKLE)
        _w_bytes(buf, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
    else:
        raise TypeError(
            f"wire: refusing to send {type(obj).__name__} over an "
            f"unauthenticated connection (set THRILL_TPU_SECRET on all "
            f"hosts to enable pickled payloads)")


def dumps(obj: Any, allow_pickle: bool = False) -> bytes:
    buf = io.BytesIO()
    _encode(buf, obj, allow_pickle, 0)
    return buf.getvalue()


# payloads at least this large take the zero-copy parts path
_BIG_PAYLOAD = 1 << 16


def dumps_parts(obj: Any, allow_pickle: bool = False) -> list:
    """Encode to a LIST of buffers whose concatenation equals
    ``dumps(obj)``. Large ``bytes`` and numpy-array payloads are
    returned as borrowed views instead of being copied into one
    contiguous buffer — senders with scatter-gather I/O (sendmsg, the
    async engine's per-buffer writes) skip the O(size) framing copies
    entirely."""
    if type(obj) is bytes and len(obj) >= _BIG_PAYLOAD:
        head = io.BytesIO()
        head.write(_T_BYTES)
        _w_len(head, len(obj))
        return [head.getvalue(), obj]
    if (isinstance(obj, np.ndarray) and obj.dtype.hasobject is False
            and obj.nbytes >= _BIG_PAYLOAD):
        a = np.ascontiguousarray(obj)
        head = io.BytesIO()
        head.write(_T_NDARRAY)
        _w_bytes(head, a.dtype.str.encode())
        _w_len(head, a.ndim)
        for d in a.shape:
            _w_len(head, d)
        _w_len(head, a.nbytes)
        return [head.getvalue(), a.data.cast("B")]
    return [dumps(obj, allow_pickle)]


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("wire: truncated frame")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def take_len(self) -> int:
        (n,) = struct.unpack("<I", self.take(4))
        return n

    def take_bytes(self) -> bytes:
        return self.take(self.take_len())


def _decode(r: _Reader, allow_pickle: bool, depth: int) -> Any:
    if depth > _MAX_DEPTH:
        raise ValueError("wire: nesting too deep")
    tag = r.take(1)
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return int.from_bytes(r.take_bytes(), "little", signed=True)
    if tag == _T_FLOAT:
        return struct.unpack("<d", r.take(8))[0]
    if tag == _T_STR:
        return r.take_bytes().decode("utf-8")
    if tag == _T_BYTES:
        return r.take_bytes()
    if tag in (_T_TUPLE, _T_LIST):
        n = r.take_len()
        items = [_decode(r, allow_pickle, depth + 1) for _ in range(n)]
        return tuple(items) if tag == _T_TUPLE else items
    if tag == _T_DICT:
        n = r.take_len()
        return {_decode(r, allow_pickle, depth + 1):
                _decode(r, allow_pickle, depth + 1) for _ in range(n)}
    if tag == _T_NDARRAY:
        dtype = np.dtype(r.take_bytes().decode())
        if dtype.hasobject:
            raise ValueError("wire: object dtype refused")
        ndim = r.take_len()
        shape = tuple(r.take_len() for _ in range(ndim))
        # .copy(): frombuffer views are read-only; receivers expect
        # writable arrays (parity with the former pickle format)
        return np.frombuffer(r.take_bytes(),
                             dtype=dtype).reshape(shape).copy()
    if tag == _T_NPSCALAR:
        dtype = np.dtype(r.take_bytes().decode())
        if dtype.hasobject:
            raise ValueError("wire: object dtype refused")
        return np.frombuffer(r.take_bytes(), dtype=dtype).copy()[0]
    if tag == _T_PICKLE:
        if not allow_pickle:
            raise ValueError(
                "wire: pickle frame refused on unauthenticated "
                "connection")
        return pickle.loads(r.take_bytes())
    raise ValueError(f"wire: unknown tag {tag!r}")


def loads(data: bytes, allow_pickle: bool = False) -> Any:
    r = _Reader(data)
    obj = _decode(r, allow_pickle, 0)
    if r.pos != len(r.data):
        raise ValueError("wire: trailing bytes in frame")
    return obj


# -- HMAC challenge-response (both directions) --------------------------

_CHALLENGE_LEN = 32


class AuthError(ConnectionError):
    """HMAC authentication failure (definitive, not transient)."""


def secret_from_env() -> Optional[bytes]:
    s = os.environ.get("THRILL_TPU_SECRET")
    return s.encode("utf-8") if s else None


def _answer(secret: bytes, role: bytes, challenge: bytes) -> bytes:
    return hmac.new(secret, role + b":" + challenge, "sha256").digest()


def mutual_auth(secret: bytes, role: str,
                send_raw: Callable[[bytes], None],
                recv_raw: Callable[[int], bytes]) -> bytes:
    """Run a mutual challenge-response over raw framed I/O.

    Both sides issue a random challenge and verify the peer's HMAC
    answer; either side raises on mismatch. The answering side's *role*
    ("client" = dialer, "server" = acceptor) is bound into the MAC, so
    reflecting a side's own challenge back at it yields an answer keyed
    to the wrong role and fails verification (no reflection attack).
    ``send_raw`` writes a fixed-size blob, ``recv_raw(n)`` reads
    exactly n bytes.

    Returns the derived per-connection *session key* — the handshake
    only proves who is at each end; every subsequent frame must carry a
    MAC under this key (``frame_mac``) or an on-path attacker could
    inject a pickle frame into the authenticated stream.
    """
    if role not in ("client", "server"):
        raise ValueError(f"wire: bad auth role {role!r}")
    my_role = role.encode()
    peer_role = b"server" if role == "client" else b"client"
    my_challenge = os.urandom(_CHALLENGE_LEN)
    send_raw(my_challenge)
    peer_challenge = recv_raw(_CHALLENGE_LEN)
    if hmac.compare_digest(peer_challenge, my_challenge):
        raise AuthError("wire: reflected challenge")
    send_raw(_answer(secret, my_role, peer_challenge))
    peer_answer = recv_raw(32)
    if not hmac.compare_digest(
            peer_answer, _answer(secret, peer_role, my_challenge)):
        raise AuthError("wire: HMAC authentication failed")
    client_chal = my_challenge if role == "client" else peer_challenge
    server_chal = peer_challenge if role == "client" else my_challenge
    return hmac.new(secret, b"session:" + client_chal + server_chal,
                    "sha256").digest()


_MAC_LEN = 16


def frame_mac(session_key: bytes, direction: bytes, seq: int,
              payload: bytes) -> bytes:
    """Per-frame MAC: binds session key, direction and sequence number
    (anti-injection + anti-replay + anti-reorder)."""
    return frame_mac_parts(session_key, direction, seq, [payload])


def frame_mac_parts(session_key: bytes, direction: bytes, seq: int,
                    parts) -> bytes:
    """``frame_mac`` over a payload given as buffer parts (the
    scatter-gather send path) — hmac streams, no concatenation."""
    h = hmac.new(session_key, direction + seq.to_bytes(8, "little"),
                 "sha256")
    for p in parts:
        h.update(p)
    return h.digest()[:_MAC_LEN]
