"""Non-executing wire codec + HMAC connection authentication.

The control-plane sockets (net/tcp.py) originally framed raw pickle —
any process able to reach the port could execute code via a crafted
payload. This module provides:

- ``dumps``/``loads``: a small self-describing binary codec for the
  values collectives actually ship (None, bool, int, float, str, bytes,
  tuple, list, dict, numpy scalars/arrays). Decoding never executes
  code. Arbitrary objects are only ever pickled when the connection is
  *authenticated* (``allow_pickle=True``), and an unauthenticated
  receiver refuses pickle frames outright.
- ``mutual_auth``: role-bound HMAC-SHA256 challenge-response in both
  directions over a shared secret, modeled on
  multiprocessing.connection's deliver/answer challenge (role binding
  defeats reflection).

Reference analog: the reference trusts its cluster network (raw
sockets, thrill/net/tcp/construct.cpp); we keep the trusted-cluster
fast path but gate code-executing deserialization behind the secret.
"""

from __future__ import annotations

import hmac
import io
import os
import pickle
import struct
import threading
from typing import Any, Callable, Optional, Tuple

import numpy as np

_MAX_DEPTH = 100

# type tags
_T_NONE = b"N"
_T_TRUE = b"T"
_T_FALSE = b"F"
_T_INT = b"I"       # signed big int: 4-byte len + bytes
_T_FLOAT = b"f"     # 8-byte double
_T_STR = b"s"
_T_BYTES = b"b"
_T_TUPLE = b"t"
_T_LIST = b"l"
_T_DICT = b"d"
_T_NDARRAY = b"a"   # dtype-str, shape, raw bytes
_T_NPSCALAR = b"n"  # dtype-str, raw bytes
_T_PICKLE = b"P"    # authenticated connections only
_T_COMPRESSED = b"C"  # compressed integer column (see _compress_column)

# -- frame compression (the shrink-the-wire host plane) -----------------
#
# Integer columns — ndarrays and homogeneous int lists/tuples — can ride
# one of three self-describing codecs instead of their raw bytes:
#
#   NARROW  min/max fit a narrower dtype: ship the cast (u8..i32), the
#           receiver casts back — exact for in-range ints by definition.
#   DELTA   monotone non-decreasing 1-D column: ship the first value +
#           the narrowed gaps (sorted splitters, offsets, cumsums).
#   RICE    strictly increasing non-negative 1-D column: delta + Rice
#           coded bit stream (core/golomb.py) — the reference's Golomb
#           CatStream for LocationDetection/DuplicateDetection hash
#           fingerprints (thrill/core/golomb_bit_stream.hpp:29).
#
# The encoder picks the smallest candidate per column and falls back to
# the raw tags whenever nothing shrinks; the decoder accepts every
# scheme unconditionally (decoding never executes code, same stance as
# the rest of this module). Floats are NEVER compressed — NaN payloads
# and signed zeros must round-trip bit-identically, and no narrowing is
# exact for them. THRILL_TPU_WIRE_COMPRESS=0 restores the pre-codec
# frames byte-identically (no _T_COMPRESSED tag is ever emitted).

_SCHEME_NARROW = 1
_SCHEME_DELTA = 2
_SCHEME_RICE = 3
_CONT_NDARRAY = 0
_CONT_LIST = 1
_CONT_TUPLE = 2

_COMPRESS_MIN_BYTES = 256        # tiny columns: headers beat savings
_COMPRESS_MIN_ITEMS = 32

_STATS_LOCK = threading.Lock()
_STATS = {"columns": 0, "bytes_raw": 0, "bytes_out": 0}

try:
    from ..common import faults as _faults
    _F_COMPRESS = _faults.declare("net.wire.compress")
except Exception:                # standalone import in codec tests
    _faults = None
    _F_COMPRESS = None


def compress_enabled() -> bool:
    """THRILL_TPU_WIRE_COMPRESS=0 disables the per-frame column codec:
    dumps() output is then byte-identical to the pre-codec wire format
    (master switch for the host plane; the device plane's row
    narrowing has its own sub-knob, data/exchange.py). One parser for
    the flag — config.wire_compress_enabled — so the master switch can
    never split across the two planes; the inline fallback only serves
    standalone codec imports."""
    try:
        from ..common.config import wire_compress_enabled
        return wire_compress_enabled()
    except Exception:
        v = os.environ.get("THRILL_TPU_WIRE_COMPRESS")
        return v not in ("0", "off", "false")


def compress_stats() -> Tuple[int, int, int]:
    """(columns compressed, raw bytes they held, bytes shipped) —
    process-wide; the multiplexer snapshots deltas around an exchange
    to attribute savings to its mesh (wire_compress_ratio)."""
    with _STATS_LOCK:
        return (_STATS["columns"], _STATS["bytes_raw"],
                _STATS["bytes_out"])


def _note_compressed(raw: int, out: int) -> None:
    with _STATS_LOCK:
        _STATS["columns"] += 1
        _STATS["bytes_raw"] += raw
        _STATS["bytes_out"] += out


_NARROW_LADDER = (np.dtype(np.uint8), np.dtype(np.int8),
                  np.dtype(np.uint16), np.dtype(np.int16),
                  np.dtype(np.uint32), np.dtype(np.int32))


def narrow_dtype(lo: int, hi: int, itemsize: int) -> Optional[np.dtype]:
    """Smallest ladder dtype holding [lo, hi], if strictly narrower."""
    for d in _NARROW_LADDER:
        if d.itemsize >= itemsize:
            return None
        info = np.iinfo(d)
        if info.min <= lo and hi <= info.max:
            return d
    return None


def _compress_column(a: np.ndarray) -> Optional[bytes]:
    """Best compressed payload for an integer column, or None when raw
    wins. Returned bytes are everything AFTER the _T_COMPRESSED tag and
    the container/original-dtype/shape header."""
    n = a.size
    flat = a.reshape(-1)
    lo, hi = int(flat.min()), int(flat.max())
    isz = a.dtype.itemsize
    best: Optional[bytes] = None

    nd = narrow_dtype(lo, hi, isz)
    if nd is not None:
        body = io.BytesIO()
        body.write(bytes([_SCHEME_NARROW]))
        _w_bytes(body, nd.str.encode())
        body.write(flat.astype(nd, copy=False).tobytes())
        best = body.getvalue()

    # Rice/delta code through int64 math: u64 values past int64.max
    # (and their gaps) would wrap — those columns only get NARROW
    if a.ndim == 1 and n >= 2 and hi <= np.iinfo(np.int64).max \
            and lo >= np.iinfo(np.int64).min:
        gaps = np.diff(flat.astype(np.int64))
        gmin, gmax = int(gaps.min()), int(gaps.max())
        if gmin >= 0:                        # monotone non-decreasing
            if gmin > 0 and lo >= 0:
                # strictly increasing: the Rice stream (mean-gap k)
                from ..core.golomb import encode_sorted_np, rice_parameter
                k = rice_parameter(max((hi - lo) / max(n - 1, 1), 1.0))
                # unary blowup guard: a few giant gaps in an otherwise
                # dense column would code to huge runs — bound total
                # unary bits to ~4/value before paying the encode
                if int(np.sum(gaps >> k)) + (int(flat[0]) >> k) \
                        <= 4 * n + 64:
                    payload, nbits, count = encode_sorted_np(flat, k)
                    body = io.BytesIO()
                    body.write(bytes([_SCHEME_RICE, k]))
                    struct_pack = struct.pack
                    body.write(struct_pack("<QI", nbits, count))
                    _w_bytes(body, payload)
                    cand = body.getvalue()
                    if best is None or len(cand) < len(best):
                        best = cand
            gd = narrow_dtype(0, gmax, isz)
            if gd is not None:
                body = io.BytesIO()
                body.write(bytes([_SCHEME_DELTA]))
                body.write(struct.pack("<q", int(flat[0])))
                _w_bytes(body, gd.str.encode())
                body.write(gaps.astype(gd, copy=False).tobytes())
                cand = body.getvalue()
                if best is None or len(cand) < len(best):
                    best = cand

    if best is not None and len(best) < a.nbytes:
        return best
    return None


def _try_compress_ndarray(a: np.ndarray) -> Optional[bytes]:
    """Full _T_COMPRESSED frame body for an ndarray (container +
    original dtype + shape + scheme payload), or None."""
    if (a.dtype.kind not in "iu" or a.dtype.itemsize < 2
            or a.nbytes < _COMPRESS_MIN_BYTES or a.size == 0):
        return None
    if _faults is not None and _faults.REGISTRY.active():
        try:
            _faults.check(_F_COMPRESS)
        except _faults.InjectedFault:
            # degrade, never fail the frame: the raw tags are always
            # a correct encoding of the same column
            _faults.note("recovery", what="wire.compress_degrade")
            return None
    payload = _compress_column(np.ascontiguousarray(a))
    if payload is None:
        return None
    head = io.BytesIO()
    head.write(bytes([_CONT_NDARRAY]))
    _w_bytes(head, a.dtype.str.encode())
    _w_len(head, a.ndim)
    for d in a.shape:
        _w_len(head, d)
    head.write(payload)
    out = head.getvalue()
    if len(out) >= a.nbytes:
        return None
    _note_compressed(a.nbytes, len(out))
    return out


def _try_compress_intseq(obj) -> Optional[bytes]:
    """_T_COMPRESSED body for a homogeneous list/tuple of python ints
    (the fingerprint/hash-list frames), or None."""
    if len(obj) < _COMPRESS_MIN_ITEMS:
        return None
    for x in obj:
        if type(x) is not int:
            return None
    try:
        a = np.asarray(obj, dtype=np.int64)
    except OverflowError:
        return None
    if _faults is not None and _faults.REGISTRY.active():
        try:
            _faults.check(_F_COMPRESS)
        except _faults.InjectedFault:
            _faults.note("recovery", what="wire.compress_degrade")
            return None
    payload = _compress_column(a)
    if payload is None:
        return None
    head = io.BytesIO()
    head.write(bytes([_CONT_LIST if type(obj) is list else _CONT_TUPLE]))
    _w_bytes(head, a.dtype.str.encode())
    _w_len(head, 1)
    _w_len(head, len(obj))
    head.write(payload)
    out = head.getvalue()
    # raw equivalent: each int costs 1 tag + 4 len + ~9 value bytes
    _note_compressed(14 * len(obj), len(out))
    return out


def _decode_compressed(r: "_Reader") -> Any:
    cont = r.take(1)[0]
    dtype = np.dtype(r.take_bytes().decode())
    if dtype.hasobject:
        raise ValueError("wire: object dtype refused")
    ndim = r.take_len()
    shape = tuple(r.take_len() for _ in range(ndim))
    n = int(np.prod(shape, dtype=np.int64)) if shape else 1
    scheme = r.take(1)[0]
    if scheme == _SCHEME_NARROW:
        nd = np.dtype(r.take_bytes().decode())
        flat = np.frombuffer(r.take(n * nd.itemsize), dtype=nd)
        flat = flat.astype(dtype)
    elif scheme == _SCHEME_DELTA:
        if n < 1:
            # the encoder only emits DELTA for n >= 2; a forged n of 0
            # would turn the gaps read into a negative (rewinding) take
            raise ValueError("wire: delta column size mismatch")
        (first,) = struct.unpack("<q", r.take(8))
        gd = np.dtype(r.take_bytes().decode())
        gaps = np.frombuffer(r.take((n - 1) * gd.itemsize), dtype=gd)
        flat = np.empty(n, dtype=np.int64)
        flat[0] = first
        flat[1:] = first + np.cumsum(gaps.astype(np.int64))
        flat = flat.astype(dtype)
    elif scheme == _SCHEME_RICE:
        k = r.take(1)[0]
        nbits, count = struct.unpack("<QI", r.take(12))
        payload = r.take_bytes()          # bounded by the real buffer
        # validate the CLAIMED sizes before allocating by them: every
        # value consumes at least one bit, and the bit count must fit
        # the payload actually present — a forged count/nbits must not
        # drive allocation (decoding stays payload-bounded, like the
        # raw ndarray path)
        if count != n or nbits > 8 * len(payload) or count > nbits:
            raise ValueError("wire: Rice column size mismatch")
        from ..core.golomb import decode_sorted_np
        flat = decode_sorted_np(payload, int(nbits), int(count),
                                int(k)).astype(dtype)
    else:
        raise ValueError(f"wire: unknown compression scheme {scheme}")
    if flat.shape[0] != n:
        raise ValueError("wire: compressed column size mismatch")
    if cont == _CONT_NDARRAY:
        return flat.reshape(shape).copy()
    vals = [int(x) for x in flat]
    return vals if cont == _CONT_LIST else tuple(vals)


def _w_len(buf: io.BytesIO, n: int) -> None:
    buf.write(struct.pack("<I", n))


def _w_bytes(buf: io.BytesIO, b: bytes) -> None:
    _w_len(buf, len(b))
    buf.write(b)


def _encode(buf: io.BytesIO, obj: Any, allow_pickle: bool,
            depth: int, compress: bool = False) -> None:
    if depth > _MAX_DEPTH:
        raise ValueError("wire: nesting too deep")
    if obj is None:
        buf.write(_T_NONE)
    elif obj is True:
        buf.write(_T_TRUE)
    elif obj is False:
        buf.write(_T_FALSE)
    elif type(obj) is int:
        raw = obj.to_bytes((obj.bit_length() + 8) // 8 + 1, "little",
                           signed=True)
        buf.write(_T_INT)
        _w_bytes(buf, raw)
    elif type(obj) is float:
        buf.write(_T_FLOAT)
        buf.write(struct.pack("<d", obj))
    elif type(obj) is str:
        buf.write(_T_STR)
        _w_bytes(buf, obj.encode("utf-8"))
    elif type(obj) is bytes:
        buf.write(_T_BYTES)
        _w_bytes(buf, obj)
    elif type(obj) is tuple or type(obj) is list:
        if compress:
            body = _try_compress_intseq(obj)
            if body is not None:
                buf.write(_T_COMPRESSED)
                buf.write(body)
                return
        buf.write(_T_TUPLE if type(obj) is tuple else _T_LIST)
        _w_len(buf, len(obj))
        for x in obj:
            _encode(buf, x, allow_pickle, depth + 1, compress)
    elif type(obj) is dict:
        buf.write(_T_DICT)
        _w_len(buf, len(obj))
        for k, v in obj.items():
            _encode(buf, k, allow_pickle, depth + 1, compress)
            _encode(buf, v, allow_pickle, depth + 1, compress)
    elif isinstance(obj, np.ndarray) and obj.dtype.hasobject is False:
        a = np.ascontiguousarray(obj)
        if compress:
            body = _try_compress_ndarray(a)
            if body is not None:
                buf.write(_T_COMPRESSED)
                buf.write(body)
                return
        buf.write(_T_NDARRAY)
        _w_bytes(buf, a.dtype.str.encode())
        _w_len(buf, a.ndim)
        for d in a.shape:
            _w_len(buf, d)
        _w_bytes(buf, a.tobytes())
    elif isinstance(obj, np.generic) and not isinstance(obj, np.object_):
        buf.write(_T_NPSCALAR)
        _w_bytes(buf, obj.dtype.str.encode())
        _w_bytes(buf, obj.tobytes())
    elif allow_pickle:
        buf.write(_T_PICKLE)
        _w_bytes(buf, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
    else:
        raise TypeError(
            f"wire: refusing to send {type(obj).__name__} over an "
            f"unauthenticated connection (set THRILL_TPU_SECRET on all "
            f"hosts to enable pickled payloads)")


def dumps(obj: Any, allow_pickle: bool = False,
          compress: Optional[bool] = None) -> bytes:
    if compress is None:
        compress = compress_enabled()
    buf = io.BytesIO()
    _encode(buf, obj, allow_pickle, 0, compress)
    return buf.getvalue()


# payloads at least this large take the zero-copy parts path
_BIG_PAYLOAD = 1 << 16


def dumps_parts(obj: Any, allow_pickle: bool = False,
                compress: Optional[bool] = None) -> list:
    """Encode to a LIST of buffers whose concatenation equals
    ``dumps(obj)``. Large ``bytes`` and numpy-array payloads are
    returned as borrowed views instead of being copied into one
    contiguous buffer — senders with scatter-gather I/O (sendmsg, the
    async engine's per-buffer writes) skip the O(size) framing copies
    entirely. A big integer ndarray that the column codec shrinks
    takes the compressed (copying) form instead — fewer wire bytes
    beat a saved framing copy."""
    if compress is None:
        compress = compress_enabled()
    if type(obj) is bytes and len(obj) >= _BIG_PAYLOAD:
        head = io.BytesIO()
        head.write(_T_BYTES)
        _w_len(head, len(obj))
        return [head.getvalue(), obj]
    if (isinstance(obj, np.ndarray) and obj.dtype.hasobject is False
            and obj.nbytes >= _BIG_PAYLOAD):
        a = np.ascontiguousarray(obj)
        if compress:
            body = _try_compress_ndarray(a)
            if body is not None:
                return [_T_COMPRESSED + body]
        head = io.BytesIO()
        head.write(_T_NDARRAY)
        _w_bytes(head, a.dtype.str.encode())
        _w_len(head, a.ndim)
        for d in a.shape:
            _w_len(head, d)
        _w_len(head, a.nbytes)
        return [head.getvalue(), a.data.cast("B")]
    return [dumps(obj, allow_pickle, compress=compress)]


class _Reader:
    def __init__(self, data: bytes) -> None:
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.data):
            raise ValueError("wire: truncated frame")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def take_len(self) -> int:
        (n,) = struct.unpack("<I", self.take(4))
        return n

    def take_bytes(self) -> bytes:
        return self.take(self.take_len())


def _decode(r: _Reader, allow_pickle: bool, depth: int) -> Any:
    if depth > _MAX_DEPTH:
        raise ValueError("wire: nesting too deep")
    tag = r.take(1)
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return int.from_bytes(r.take_bytes(), "little", signed=True)
    if tag == _T_FLOAT:
        return struct.unpack("<d", r.take(8))[0]
    if tag == _T_STR:
        return r.take_bytes().decode("utf-8")
    if tag == _T_BYTES:
        return r.take_bytes()
    if tag in (_T_TUPLE, _T_LIST):
        n = r.take_len()
        items = [_decode(r, allow_pickle, depth + 1) for _ in range(n)]
        return tuple(items) if tag == _T_TUPLE else items
    if tag == _T_DICT:
        n = r.take_len()
        return {_decode(r, allow_pickle, depth + 1):
                _decode(r, allow_pickle, depth + 1) for _ in range(n)}
    if tag == _T_NDARRAY:
        dtype = np.dtype(r.take_bytes().decode())
        if dtype.hasobject:
            raise ValueError("wire: object dtype refused")
        ndim = r.take_len()
        shape = tuple(r.take_len() for _ in range(ndim))
        # .copy(): frombuffer views are read-only; receivers expect
        # writable arrays (parity with the former pickle format)
        return np.frombuffer(r.take_bytes(),
                             dtype=dtype).reshape(shape).copy()
    if tag == _T_NPSCALAR:
        dtype = np.dtype(r.take_bytes().decode())
        if dtype.hasobject:
            raise ValueError("wire: object dtype refused")
        return np.frombuffer(r.take_bytes(), dtype=dtype).copy()[0]
    if tag == _T_COMPRESSED:
        # decoding a compressed column never executes code (pure
        # numpy casts + the Rice bit stream), so it is accepted on
        # unauthenticated connections exactly like _T_NDARRAY
        return _decode_compressed(r)
    if tag == _T_PICKLE:
        if not allow_pickle:
            raise ValueError(
                "wire: pickle frame refused on unauthenticated "
                "connection")
        return pickle.loads(r.take_bytes())
    raise ValueError(f"wire: unknown tag {tag!r}")


def loads(data: bytes, allow_pickle: bool = False) -> Any:
    r = _Reader(data)
    obj = _decode(r, allow_pickle, 0)
    if r.pos != len(r.data):
        raise ValueError("wire: trailing bytes in frame")
    return obj


# -- HMAC challenge-response (both directions) --------------------------

_CHALLENGE_LEN = 32


class AuthError(ConnectionError):
    """HMAC authentication failure (definitive, not transient)."""


def secret_from_env() -> Optional[bytes]:
    s = os.environ.get("THRILL_TPU_SECRET")
    return s.encode("utf-8") if s else None


def _answer(secret: bytes, role: bytes, challenge: bytes) -> bytes:
    return hmac.new(secret, role + b":" + challenge, "sha256").digest()


def mutual_auth(secret: bytes, role: str,
                send_raw: Callable[[bytes], None],
                recv_raw: Callable[[int], bytes]) -> bytes:
    """Run a mutual challenge-response over raw framed I/O.

    Both sides issue a random challenge and verify the peer's HMAC
    answer; either side raises on mismatch. The answering side's *role*
    ("client" = dialer, "server" = acceptor) is bound into the MAC, so
    reflecting a side's own challenge back at it yields an answer keyed
    to the wrong role and fails verification (no reflection attack).
    ``send_raw`` writes a fixed-size blob, ``recv_raw(n)`` reads
    exactly n bytes.

    Returns the derived per-connection *session key* — the handshake
    only proves who is at each end; every subsequent frame must carry a
    MAC under this key (``frame_mac``) or an on-path attacker could
    inject a pickle frame into the authenticated stream.
    """
    if role not in ("client", "server"):
        raise ValueError(f"wire: bad auth role {role!r}")
    my_role = role.encode()
    peer_role = b"server" if role == "client" else b"client"
    my_challenge = os.urandom(_CHALLENGE_LEN)
    send_raw(my_challenge)
    peer_challenge = recv_raw(_CHALLENGE_LEN)
    if hmac.compare_digest(peer_challenge, my_challenge):
        raise AuthError("wire: reflected challenge")
    send_raw(_answer(secret, my_role, peer_challenge))
    peer_answer = recv_raw(32)
    if not hmac.compare_digest(
            peer_answer, _answer(secret, peer_role, my_challenge)):
        raise AuthError("wire: HMAC authentication failed")
    client_chal = my_challenge if role == "client" else peer_challenge
    server_chal = peer_challenge if role == "client" else my_challenge
    return hmac.new(secret, b"session:" + client_chal + server_chal,
                    "sha256").digest()


_MAC_LEN = 16


def frame_mac(session_key: bytes, direction: bytes, seq: int,
              payload: bytes) -> bytes:
    """Per-frame MAC: binds session key, direction and sequence number
    (anti-injection + anti-replay + anti-reorder)."""
    return frame_mac_parts(session_key, direction, seq, [payload])


def frame_mac_parts(session_key: bytes, direction: bytes, seq: int,
                    parts) -> bytes:
    """``frame_mac`` over a payload given as buffer parts (the
    scatter-gather send path) — hmac streams, no concatenation."""
    h = hmac.new(session_key, direction + seq.to_bytes(8, "little"),
                 "sha256")
    for p in parts:
        h.update(p)
    return h.digest()[:_MAC_LEN]
