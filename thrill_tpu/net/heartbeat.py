"""Liveness heartbeats multiplexed over the group's connections.

The collective watchdog (net/group.py recv deadlines) only fires while
a rank is *blocked in a recv* — a worker that died between collectives
would go unnoticed until the next one wedges. This monitor closes that
gap: a background thread sends a tiny heartbeat frame to every peer on
a fixed cadence over the SAME authenticated connections the
collectives use (transports filter the frames out before they can
reach a payload stream — tcp: ``TcpConnection._recv_msg``; any other
transport: ``Group.recv_from``).

A heartbeat send that still fails after the shared retry policy means
the kernel reported the peer's socket dead (RST/EPIPE — the OS-level
verdict on a kill -9'd process): the monitor latches a
:class:`~thrill_tpu.net.group.ClusterAbort` on the group naming the
dead rank and poisons the surviving peers, converting silent worker
loss into a fast, attributable abort that a supervising re-launch
(run-scripts/supervise.sh + checkpoint resume) can recover from.

Opt-in via ``THRILL_TPU_HEARTBEAT_S=<seconds>`` (off by default: the
control plane's frame streams stay byte-identical to the
pre-heartbeat protocol unless the operator asks for liveness).
Injection site ``net.heartbeat`` (common/faults.py) rides every probe:
a transient fire is absorbed by the retry policy, a persistent one
exercises the real dead-peer verdict path.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..common import faults
from ..common.retry import default_policy
from .group import F_HEARTBEAT, HEARTBEAT_KEY, Group, heal_timeout_s


def heartbeat_interval_s() -> Optional[float]:
    v = os.environ.get("THRILL_TPU_HEARTBEAT_S", "")
    try:
        t = float(v)
    except ValueError:
        return None
    return t if t > 0 else None


class HeartbeatMonitor:
    """Background prober for one Group; one instance per process."""

    def __init__(self, group: Group, interval_s: float) -> None:
        self.group = group
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        # one bounded-backoff policy for all probes: a single EAGAIN
        # blip must not declare a peer dead
        self._policy = default_policy()
        # first time each peer's link was seen down-but-repairable:
        # the monitor defers to the generation heal for a bounded
        # window only — a link that stays broken far past any heal
        # deadline with no repair is a dead peer after all
        self._broken_since: dict = {}

    def start(self) -> "HeartbeatMonitor":
        if self.group.num_hosts <= 1 or self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="thrill-tpu-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0 + self.interval_s)

    # -- the probe loop -------------------------------------------------
    def _run(self) -> None:
        g = self.group
        while not self._stop.wait(self.interval_s):
            self._seq += 1
            frame = {HEARTBEAT_KEY: {"seq": self._seq,
                                     "rank": g.my_rank}}
            for peer in range(g.num_hosts):
                if peer == g.my_rank or self._stop.is_set():
                    continue
                if g.link_repairable(peer):
                    if self._defer_to_heal(peer):
                        # the link is down but a generation heal can
                        # reconnect it: that is a PIPELINE-scoped event
                        # the heal owns — probing now would fast-fail
                        # on the broken mark and misrule a dropped LINK
                        # as a dead PROCESS (if nobody answers the
                        # reconnect, the heal's dial budget produces
                        # that verdict instead)
                        continue
                    # deferral window spent with no heal: probe (and
                    # fast-fail into the dead verdict) after all
                else:
                    # link healthy or repaired: a LATER drop is a new
                    # incident with its own full deferral window
                    self._broken_since.pop(peer, None)
                try:
                    self._probe(peer, frame)
                except TimeoutError:
                    # peer not draining but socket alive: slow, not
                    # dead — the collective watchdog owns that verdict
                    continue
                except Exception as e:
                    if (g.link_repairable(peer)
                            and self._defer_to_heal(peer)):
                        # the probe itself was first to discover the
                        # drop (send failed, link now marked broken):
                        # re-check repairability AFTER the failure too,
                        # or the first-to-hit probe would misrule a
                        # reconnectable drop as a dead process
                        continue
                    cause = (f"heartbeat: rank {peer} is unreachable "
                             f"({type(e).__name__}: {e}"
                             f"{self._staleness(peer)}) — worker "
                             f"presumed dead")
                    faults.note("recovery", what="heartbeat.peer_dead",
                                peer=peer, error=repr(e))
                    g.mark_dead(peer, cause)
                    self._stop.set()
                    return

    def _defer_to_heal(self, peer: int) -> bool:
        """Should a down-but-repairable link still be left to the
        generation heal? Only within a bounded window (2x the heal
        deadline) of the CURRENT incident: an application that never
        heals (no ctx.pipeline() in use) must still get the dead-peer
        verdict eventually, or silent worker loss goes unreported.
        The window is keyed to the group's repair counter, not to the
        monitor observing a healthy instant — under sustained drops
        (one per pipeline, each healed) every probe pass may sample
        the link mid-incident, and an accumulated window would issue a
        false dead-process verdict for a peer whose every heal
        succeeded."""
        now = time.monotonic()
        repairs = getattr(self.group, "stats_reconnects", 0)
        first, seen = self._broken_since.get(peer, (now, repairs))
        if repairs != seen:
            # a repair landed since this incident began: whatever is
            # broken NOW is a new incident with a fresh window
            first, seen = now, repairs
        self._broken_since[peer] = (first, seen)
        return now - first < 2.0 * heal_timeout_s()

    def _staleness(self, peer: int) -> str:
        """Last inbound heartbeat seen from ``peer``, for the verdict
        cause: the transports stamp arrival times (TcpConnection.
        last_heartbeat, Group._hb_last) and this is where they are
        read."""
        last = self.group._hb_last.get(peer, 0.0)
        conn_last = getattr(self.group.connection(peer),
                            "last_heartbeat", 0.0)
        last = max(last, conn_last)
        if not last:
            return "; no heartbeat ever received from it"
        return (f"; its last heartbeat was "
                f"{time.monotonic() - last:.1f}s ago")

    def _probe(self, peer: int, frame: dict) -> None:
        bound = max(self.interval_s, 0.25)

        def once():
            faults.check(F_HEARTBEAT, peer=peer)
            # re-fetch per attempt: a concurrent generation heal may
            # swap in a freshly reconnected connection mid-retry — the
            # probe must judge the CURRENT link, not the dropped one
            self.group.connection(peer).send_bounded(frame, bound)

        self._policy.run(once, what="net.heartbeat", seed=peer)


def maybe_start(group: Group) -> Optional[HeartbeatMonitor]:
    """Start a monitor when THRILL_TPU_HEARTBEAT_S is set (>0)."""
    interval = heartbeat_interval_s()
    if interval is None or group.num_hosts <= 1:
        return None
    return HeartbeatMonitor(group, interval).start()
