"""Per-tenant HBM budgets and tenant activation.

Enforcement lives in the existing :class:`~thrill_tpu.mem.hbm.
HbmGovernor` ledger (mem/hbm.py): every cached DIA result is stamped
with the tenant that was active when its node was created
(``Context.current_tenant``, set by the scheduler around each job),
and the governor keeps per-tenant byte counts next to its global
ledger. When a tenant crosses ITS budget the governor spills that
tenant's LRU-coldest shards — and only that tenant's — to the host
block store; the spilled tenant's next pull pays the restore (and,
under real HBM limits, its dispatches ride the PR-5 pressure ladder:
admission spill, OOM-retry, split, host fallback). Another tenant's
cached shards are never evicted for this tenant's pressure; genuine
GLOBAL pressure still goes through the tenant-blind paths
(``maybe_spill`` / the PressureMonitor), because a full device is a
full device no matter whose bytes fill it.

This module is the thin policy layer: budget parsing
(``THRILL_TPU_SERVE_HBM_BUDGETS="a=512Mi,b=1Gi"``), explicit
``set_budget``, and the ``activate`` context manager for callers
running pipelines under a tenant without the scheduler.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Optional, Union

from ..common.config import parse_kv_spec, parse_si_iec_units

ENV_BUDGETS = "THRILL_TPU_SERVE_HBM_BUDGETS"


def _budget(v: str) -> int:
    nb = parse_si_iec_units(v)
    if nb <= 0:
        raise ValueError(v)
    return nb


def parse_budgets(spec: str) -> Dict[str, int]:
    """Parse "tenant=SIZE,..." (SI/IEC units per parse_si_iec_units);
    malformed entries are skipped loudly."""
    return parse_kv_spec(spec, _budget, ENV_BUDGETS)


def configure(ctx, budgets: Optional[Dict[str, int]] = None) -> None:
    """Install tenant budgets on the Context's governor. Env budgets
    fill only tenants without an explicit budget (idempotent — the
    scheduler calls this on construction)."""
    explicit = budgets or {}
    ctx.hbm.tenant_budgets.update(explicit)
    for tenant, nb in parse_budgets(
            os.environ.get(ENV_BUDGETS, "")).items():
        ctx.hbm.tenant_budgets.setdefault(tenant, nb)


def set_budget(ctx, tenant: str, limit: Union[int, str]) -> None:
    """Set one tenant's HBM budget (bytes, or an SI/IEC size string)."""
    nb = parse_si_iec_units(limit) if isinstance(limit, str) else int(limit)
    if nb <= 0:
        raise ValueError(f"tenant budget must be positive, got {limit!r}")
    ctx.hbm.tenant_budgets[tenant] = nb


@contextlib.contextmanager
def activate(ctx, tenant: str):
    """Run a block with ``tenant`` as the active tenant: nodes created
    inside are stamped and accounted against its budget. The scheduler
    does this around every job; this is the direct-use form (tests,
    single-tenant batch jobs that still want a budget)."""
    prev = ctx.current_tenant
    ctx.current_tenant = tenant
    try:
        yield
    finally:
        ctx.current_tenant = prev
