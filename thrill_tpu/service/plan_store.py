"""Persistent compile/plan store: learned plan state across restarts.

A long-lived service amortizes its planning cost in-process — sticky
exchange capacities, narrow specs, plan kinds (data/exchange.py) and
pre-shuffle verdicts (core/preshuffle.py) are learned once per
``MeshExec.cached`` / ``FusionPlan`` composite identity and reused for
every later query. A process RESTART used to throw all of it away:
every exchange site paid the synced host plan step again (~one link
RTT each — the 140 ms/dispatch class of cost the whole dispatch budget
fights), every auto-prune site re-ran its cost model. This store
persists that state through the vfs (file://, s3://, hdfs://) so a
warm restart re-runs a known pipeline with ``plan_builds == 0``.

Key/versioning rules:

* Keys are SHA-1 digests of the ``repr`` of the in-memory identity
  tuples (call-site ident + shapes + dtypes + treedefs) — stable for a
  fixed program across processes, and garbage for a changed one, which
  is exactly right: a changed pipeline simply misses and re-learns.
* Every on-disk key carries a ``w{W}:`` prefix (the mesh width the
  entry was learned at): capacities, narrow ranges and loop tapes are
  W-SHAPED vectors, and an elastic service that resizes W=2→3 must
  not install 2-wide caps into a 3-wide mesh. Loads filter to the
  CURRENT width and strip the prefix; entries of other widths stay on
  disk untouched, so a resize back to a previously-served W warm
  starts again. (This is the on-disk twin of MeshExec.resize's in-
  memory per-W archive, parallel/mesh.py.)
* Values are CORRECTNESS-NEUTRAL by construction: a lying capacity or
  narrow range is caught by the exchange's in-trace overflow/range
  flag and healed by the synced re-run; a wrong plan kind or prune
  verdict costs performance, never results. That is why a plan store
  may be trusted at all — and why corruption handling can afford to be
  simple: any parse/CRC/version failure degrades LOUDLY to an empty
  store (cold recompile), never to a partial read.
* The file carries ``version`` (STORE_VERSION — bump on any format
  change; skewed versions are refused wholesale) and a CRC-32 over the
  canonical entries JSON. Writes go through
  ``vfs.write_file_atomic`` — readers see the old store or the whole
  new one, never a torn prefix.

Compiled XLA executables are deliberately NOT stored here: jax's own
persistent compilation cache (THRILL_TPU_COMPILE_CACHE, wired since
round 1) already buries repeat compile costs; this store covers the
DATA-DRIVEN half of planning that jax cannot know about.
"""

from __future__ import annotations

import contextlib
import json
import zlib
from typing import Optional

from ..common import faults

# v2: keys gained the w{W}: width prefix (elastic mesh) — v1 stores
# carry width-ambiguous keys and are refused wholesale by the version
# check (loud cold recompile), exactly the documented skew behavior
STORE_VERSION = 2
_FILE = "plans.json"
#: the decision ledger's audited-accuracy summary persists NEXT TO the
#: plan state it judges (common/decisions.py; Context.close writes it)
_LEDGER_FILE = "decisions.json"

# fired at load time: an armed fire makes THIS load read as corrupt —
# the store degrades to empty (cold recompile), results stay exact
_F_CORRUPT = faults.declare("service.plan_store.corrupt")

#: entry kinds and their owners (data/exchange.py, core/preshuffle.py,
#: parallel/mesh.py, api/loop.py)
_KINDS = ("caps", "plan", "ranges", "prune_decisions", "prune_history",
          "out_bytes", "loop_tape")


def _crc(entries: dict) -> int:
    return zlib.crc32(json.dumps(entries, sort_keys=True).encode())


def _for_width(entries: dict, w: int) -> dict:
    """The store slice learned at mesh width ``w``: keep only
    ``w{w}:``-prefixed keys, stripped. Entries of other widths (or
    unprefixed strays) are simply not installed — they are not wrong,
    they are for a differently-shaped mesh."""
    pre = f"w{w}:"
    return {kind: {k[len(pre):]: v for k, v in m.items()
                   if isinstance(k, str) and k.startswith(pre)}
            for kind, m in entries.items() if isinstance(m, dict)}


def install_entries(mex, entries: dict, *,
                    symmetric: bool = False) -> int:
    """Install loaded store entries into a MeshExec's lazy seed
    tables; returns how many arrived. Shared by :meth:`PlanStore.attach`
    (this process read the file) and the Context's multi-process path
    (rank 0 read it and BROADCAST the entries over the host control
    plane, so every rank installs the identical seeds —
    api/context.py; that caller passes ``symmetric=True``, the
    attestation that keeps the optimistic exchange gate open on
    multi-controller meshes — data/exchange.py install_plan_seeds).
    Filters to the mesh's CURRENT width (keys are ``w{W}:``-prefixed
    on disk — see the module docstring)."""
    from ..api import loop
    from ..core import preshuffle
    from ..data import exchange
    entries = _for_width(entries, mex.num_workers)
    n = exchange.import_plan_state(mex, entries, symmetric=symmetric)
    n += preshuffle.import_plan_state(mex, entries,
                                      symmetric=symmetric)
    n += loop.import_plan_state(mex, entries, symmetric=symmetric)
    ob = entries.get("out_bytes")
    if isinstance(ob, dict) and hasattr(mex, "import_learned_sizes"):
        n_ob = mex.import_learned_sizes(ob)
        if n_ob and not symmetric:
            # learned sizes ride the same provenance rule as the seed
            # table: a non-attested install closes the optimism gate
            mex._plan_seed_symmetric = False
        n += n_ob
    return n


class PlanStore:
    """One on-disk plan-state file under a vfs directory."""

    def __init__(self, path: str, logger=None) -> None:
        self.path = path
        self.file = path.rstrip("/") + "/" + _FILE
        self.logger = logger
        self._last_corrupt: Optional[str] = None

    # -- reading --------------------------------------------------------
    def load(self) -> dict:
        """Entries by kind; {} when cold. NEVER raises: any failure —
        missing file aside — is a loud degrade to empty (the service
        recompiles; a plan store must not be able to take it down)."""
        from ..vfs import file_io
        self._last_corrupt = None
        try:
            faults.check(_F_CORRUPT, path=self.file)
            with file_io.OpenReadStream(self.file) as f:
                raw = f.read()
        except FileNotFoundError:
            return {}
        except Exception as e:
            return self._corrupt(f"unreadable: {e!r}")
        try:
            payload = json.loads(raw.decode())
            if not isinstance(payload, dict):
                return self._corrupt("not a JSON object")
            if payload.get("version") != STORE_VERSION:
                return self._corrupt(
                    f"version skew: {payload.get('version')!r} != "
                    f"{STORE_VERSION}")
            entries = payload.get("entries")
            if not isinstance(entries, dict):
                return self._corrupt("entries missing")
            if _crc(entries) != payload.get("crc"):
                return self._corrupt("CRC mismatch")
        except Exception as e:
            return self._corrupt(f"parse failure: {e!r}")
        return {k: dict(v) for k, v in entries.items()
                if k in _KINDS and isinstance(v, dict)}

    def _corrupt(self, why: str) -> dict:
        self._last_corrupt = why
        faults.note("recovery", what="plan_store.corrupt",
                    path=self.file, why=why[:200])
        import sys
        print(f"thrill_tpu.service: plan store {self.file} ignored "
              f"({why}); recompiling cold", file=sys.stderr)
        return {}

    def attach(self, mex) -> int:
        """Seed a MeshExec's plan state from the store; returns the
        number of entries imported. The seeds are consumed lazily at
        each site's first lookup (data/exchange.py plan_seed), so an
        entry for a pipeline this process never runs costs nothing."""
        return install_entries(mex, self.load())

    # -- writing --------------------------------------------------------
    def save(self, mex) -> None:
        """Persist the MeshExec's current plan state, merged with what
        is already on disk (capacities elementwise-max; unknown
        digests are kept — another pipeline's state is not ours to
        drop). On posix paths the load-merge-write runs under an
        flock, so concurrent services sharing one store only ever
        ratchet; object-store schemes (s3://, hdfs://) have no lock
        primitive and keep last-writer-wins there. A corrupt on-disk
        store is replaced wholesale."""
        with self._save_lock():
            self._save_locked(mex)

    @contextlib.contextmanager
    def _save_lock(self):
        if "://" in self.path and not self.path.startswith("file://"):
            yield                        # no lock primitive: best effort
            return
        import os
        d = self.path[len("file://"):] if self.path.startswith(
            "file://") else self.path
        os.makedirs(d, exist_ok=True)
        import fcntl
        with open(d.rstrip("/") + "/.plans.lock", "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)

    def _save_locked(self, mex) -> None:
        from ..api import loop
        from ..core import preshuffle
        from ..data import exchange
        from ..vfs import file_io
        entries = exchange.export_plan_state(mex)
        entries.update(preshuffle.export_plan_state(mex))
        entries.update(loop.export_plan_state(mex))
        if hasattr(mex, "export_learned_sizes"):
            entries["out_bytes"] = mex.export_learned_sizes()
        # stamp every exported key with the width it was learned at
        # (the in-memory tables are all CURRENT-W state: MeshExec.resize
        # parks other widths in its archive, never in these exports)
        pre = f"w{mex.num_workers}:"
        entries = {kind: {pre + dg: v for dg, v in m.items()}
                   for kind, m in entries.items()}
        prev = self.load()
        if self._last_corrupt is None:
            for kind, old in prev.items():
                new = entries.setdefault(kind, {})
                for dg, v in old.items():
                    if dg not in new:
                        new[dg] = v
                    elif kind == "caps":
                        try:
                            new[dg] = [max(int(a), int(b)) for a, b
                                       in zip(new[dg], v)] \
                                if len(new[dg]) == len(v) else new[dg]
                        except (TypeError, ValueError):
                            pass
        payload = {"version": STORE_VERSION, "crc": _crc(entries),
                   "entries": entries}
        file_io.write_file_atomic(
            self.file, json.dumps(payload, sort_keys=True).encode())
        if self.logger is not None and self.logger.enabled:
            self.logger.line(event="plan_store_save", path=self.file,
                             entries=sum(len(v)
                                         for v in entries.values()))

    def save_ledger(self, summary: dict) -> None:
        """Persist the decision ledger's accuracy summary beside
        plans.json: per-kind predicted-vs-actual MAE plus the
        worst-audited sites. Plain overwrite (no merge): the ledger is
        a per-run audit report, not ratcheting plan state — the newest
        run's verdict on the cost model is the one that matters."""
        from ..vfs import file_io
        path = self.path.rstrip("/") + "/" + _LEDGER_FILE
        file_io.write_file_atomic(
            path, json.dumps(summary, sort_keys=True).encode())
        if self.logger is not None and self.logger.enabled:
            self.logger.line(event="decision_ledger_save", path=path,
                             decisions=summary.get("decisions", 0))
