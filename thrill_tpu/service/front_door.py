"""Network front door: socket admission, shed-load, streamed results.

The service plane (service/scheduler.py) is production-shaped on the
inside — WFQ across tenants, bounded queues, per-job failure domains —
but until ISSUE 18 its "clients" were threads inside the controller
process. This module is the network edge: a TCP admission protocol
riding the existing :class:`~thrill_tpu.net.tcp.TcpConnection` framing
and :mod:`~thrill_tpu.net.wire` codec, with the control/data plane
split the reference keeps (PAPER.md): admission frames are SMALL and
ride their own sockets, never the bulk exchange plane.

Protocol (one wire-codec frame per message, client dials, MACed when
``THRILL_TPU_SECRET`` is set — the same mutual HMAC handshake every
PR-8 mesh link runs):

* ``("hello", {"tenant", "proto"})`` -> ``("welcome", {"proto"})``
* ``("submit", {"id", "pipeline", "args", "deadline_s", "weight"})``
  -> ``("accept", id, {"mode": "blob"|"items"})`` or
  ``("reject", id, kind, retry_after_s, msg)``
* results stream back as ``("chunk", id, seq, payload)`` frames AS THE
  JOB'S EGRESS DRAINS, closed by ``("done", id, nchunks, meta)`` — a
  job failure is ``("error", id, kind, msg)``. Never one giant blob at
  job end: chunking bounds both sides' memory and lets a slow client
  be detected per-chunk instead of wedging a whole result write.
* ``("bye", reason)`` ends a connection in either direction.

Pipelines are NAMED: clients submit a registry key + args
(:meth:`FrontDoor.register`), never code — nothing executable ever
rides the wire, so an unauthenticated deployment still has a
no-pickle, no-exec admission surface (the wire codec refuses pickled
payloads on unauthenticated links by construction).

Robustness is the headline — overload is a designed regime:

* every rejection is TYPED (:class:`~.scheduler.ShedLoad` taxonomy:
  ``rate_limited`` / ``tenant_queue_full`` / ``queue_full`` /
  ``draining`` / ``unknown_pipeline`` / ``deadline``) and carries a
  retry-after hint; nothing is ever silently dropped or left hanging;
* every client socket has READ deadlines (a slow-loris client torn
  mid-frame, or a half-open one idling past
  ``THRILL_TPU_SERVE_READ_TIMEOUT_S`` with nothing in flight, is
  dropped) and WRITE deadlines (a client not draining its result
  stream within ``THRILL_TPU_SERVE_WRITE_TIMEOUT_S`` is dropped —
  its jobs still complete, other tenants never stall);
* per-connection egress is byte-bounded
  (``THRILL_TPU_SERVE_EGRESS_BYTES``): the dispatcher offers chunks
  with a bounded wait and shed-drops the CONNECTION, never blocks the
  mesh on a dead socket;
* graceful drain (:meth:`FrontDoor.drain`, SIGTERM via
  :meth:`FrontDoor.install_sigterm`): stop accepting, reject new
  submits with ``draining`` + retry-after, finish every in-flight job
  and flush its stream, then say ``bye`` — bounded by
  ``THRILL_TPU_SERVE_DRAIN_TIMEOUT_S``.

Single-controller only: an external socket submits on ONE rank, which
would violate the multi-controller lockstep admission contract the
scheduler's ordering frames exist for — a spanning front door needs a
cross-rank submit broadcast that does not exist yet (loud refusal,
like ``Scheduler.fence``).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from ..common import faults
from ..net import wire
from ..net.group import CollectiveHangTimeout
from ..net.tcp import F_CLIENT_DISCONNECT, TcpConnection, \
    _exchange_auth_flag
from .scheduler import ShedLoad

# protocol range this server speaks. v1: the original frame set, the
# hello carries a single int and equality decides. v2: the hello may
# carry ``[min, max]``, the server negotiates the highest common
# version into the welcome (``{"proto": negotiated, "range": [..]}``)
# and stamps the mesh generation onto accept frames (elastic resize
# awareness). Out-of-range clients get a TYPED ``version_mismatch``
# reject naming the supported range — never a silent EOF.
PROTO_MIN = 1
PROTO_MAX = 2
# legacy shorthand: the single version a pre-range peer offers/expects
PROTO_VERSION = 1

# fired per accepted socket, before the handshake: an armed fire drops
# the connection (the client sees EOF and its retry policy redials)
_F_ACCEPT = faults.declare("service.front_door.accept")
# fired per result chunk as the dispatcher offers it to the egress: an
# armed fire aborts exactly that stream with a typed ("error", ...,
# "stream") frame — the job still completes, the connection survives
_F_STREAM = faults.declare("service.front_door.stream")
# armed with delay= it makes the writer a deterministic straggler (the
# slow-client detection's test hook); a raising fire drops the client
_F_SLOW = faults.declare("service.front_door.slow_client")


def _env_f(name: str, default: float) -> float:
    try:
        v = os.environ.get(name)
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        v = os.environ.get(name)
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


class _Conn:
    """One client connection: reader thread + writer thread + a
    byte-bounded egress queue between the dispatcher and the socket.

    The DISPATCHER never touches the socket: job wrappers ``offer()``
    frames into ``out`` (bounded wait, shed on overflow) and the
    writer thread drains them through ``send_bounded`` — so a dead or
    slow client costs the mesh at most one bounded offer, never a
    blocked collective."""

    __slots__ = ("conn", "peer", "tenant", "proto", "out", "out_bytes",
                 "cv", "dead", "inflight", "reader", "writer",
                 "t_last_frame", "fd")

    def __init__(self, fd: "FrontDoor", conn: TcpConnection,
                 peer: str) -> None:
        self.fd = fd
        self.conn = conn
        self.peer = peer
        self.tenant = "default"
        self.proto = PROTO_MIN     # negotiated up in the handshake
        self.out: deque = deque()
        self.out_bytes = 0
        self.cv = threading.Condition()
        self.dead = False
        self.inflight: Dict[int, Any] = {}      # id -> JobFuture
        self.t_last_frame = time.monotonic()
        self.reader: Optional[threading.Thread] = None
        self.writer: Optional[threading.Thread] = None

    # -- egress ---------------------------------------------------------
    def enqueue(self, frame, nbytes: int = 0) -> bool:
        """Queue a CONTROL frame (accept/reject/done/error/bye):
        always admitted — the taxonomy's never-silent rule — unless
        the connection is already dead."""
        with self.cv:
            if self.dead:
                return False
            self.out.append((frame, nbytes))
            self.out_bytes += nbytes
            self.cv.notify_all()
        return True

    def offer(self, frame, nbytes: int, timeout_s: float) -> bool:
        """Queue a STREAM chunk under the egress byte budget, waiting
        (bounded) for the writer to drain. False = the budget stayed
        full past the timeout (slow client) or the connection died."""
        deadline = time.monotonic() + timeout_s
        with self.cv:
            while not self.dead and self.out_bytes + nbytes \
                    > self.fd.egress_budget and self.out:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self.cv.wait(min(left, 0.2))
            if self.dead:
                return False
            self.out.append((frame, nbytes))
            self.out_bytes += nbytes
            self.cv.notify_all()
        return True

    def kill(self, why: str) -> None:
        """Drop this client for real: mark dead (enqueues become
        no-ops, blocked offers return), close the socket (both
        threads unblock), discard queued egress. In-flight jobs keep
        running — their futures belong to the scheduler, and a
        SIGKILLed client must never stall other tenants' work."""
        with self.cv:
            if self.dead:
                return
            self.dead = True
            self.out.clear()
            self.out_bytes = 0
            self.cv.notify_all()
        try:
            self.conn.close()
        except Exception:
            pass
        self.fd._conn_closed(self, why)

    def idle(self) -> bool:
        with self.cv:
            return not self.inflight and not self.out


class FrontDoor:
    """The TCP admission edge of one serving Context.

    ``FrontDoor(ctx, port=0)`` binds and starts accepting; ``.port``
    is the bound port (ephemeral when 0). Register pipelines with
    :meth:`register` before clients submit them. ``close()`` (or
    ``Context.close``) stops accepting, drains and tears down."""

    def __init__(self, ctx, port: Optional[int] = None,
                 host: str = "127.0.0.1") -> None:
        if ctx.net.num_workers > 1 or ctx.mesh_exec.num_processes > 1:
            raise RuntimeError(
                "FrontDoor is single-controller only: an external "
                "socket submits on one rank, violating the lockstep "
                "admission contract (see service/front_door.py)")
        self.ctx = ctx
        self.secret = wire.secret_from_env()
        self.read_timeout_s = _env_f(
            "THRILL_TPU_SERVE_READ_TIMEOUT_S", 60.0)
        self.write_timeout_s = _env_f(
            "THRILL_TPU_SERVE_WRITE_TIMEOUT_S", 10.0)
        self.drain_timeout_s = _env_f(
            "THRILL_TPU_SERVE_DRAIN_TIMEOUT_S", 30.0)
        self.chunk_bytes = max(
            4096, _env_i("THRILL_TPU_SERVE_CHUNK", 256 << 10))
        self.egress_budget = max(
            self.chunk_bytes,
            _env_i("THRILL_TPU_SERVE_EGRESS_BYTES", 8 << 20))
        self._pipelines: Dict[str, Callable] = {}
        self._conns: list = []
        self._lock = threading.Lock()
        self._draining = False
        self._closed = False
        self.drained = threading.Event()
        # resize verdict gate: while a Context.resize has REQUESTED
        # its dispatcher fence but the swap has not completed, no
        # admission verdict frame may be emitted — an accept sent in
        # that window would name a generation (and mesh W) the resize
        # is about to invalidate. Reader threads block on this gate at
        # the top of _handle_submit; Context.resize brackets its
        # fenced swap with begin/end (see that method).
        self._fence_cv = threading.Condition()
        self._fencing = 0
        # the fd_* counter row (Context.overall_stats merges stats(),
        # so the Prometheus endpoint exports these for free)
        self.conns_accepted = 0
        self.conns_dropped = 0
        self.jobs_submitted = 0
        self.jobs_rejected = 0
        self.chunks_sent = 0
        self.slow_clients = 0
        self.deadline_expired = 0
        if port is None:
            port = _env_i("THRILL_TPU_SERVE_PORT", 0)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, int(port)))
        self._srv.listen(16)
        self._srv.settimeout(0.25)
        self.host = host
        self.port = self._srv.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="thrill-fd-accept",
            daemon=True)
        self._accept_thread.start()
        ctx.front_door = self
        log = ctx.logger
        if log.enabled:
            log.line(event="front_door_listen", host=host,
                     port=self.port,
                     authenticated=self.secret is not None)

    # -- registry -------------------------------------------------------
    def register(self, name: str, fn: Callable) -> None:
        """Register ``fn(ctx, args) -> result`` under ``name``. A
        GENERATOR function streams: each yielded item becomes its own
        chunk frame the moment the egress drains it — the client can
        consume results while the job is still running."""
        self._pipelines[str(name)] = fn

    def stats(self) -> dict:
        return {"fd_conns_accepted": self.conns_accepted,
                "fd_conns_dropped": self.conns_dropped,
                "fd_jobs_submitted": self.jobs_submitted,
                "fd_jobs_rejected": self.jobs_rejected,
                "fd_chunks_sent": self.chunks_sent,
                "fd_slow_clients": self.slow_clients,
                "fd_deadline_expired": self.deadline_expired}

    # -- accept side ----------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed and not self._draining:
            try:
                sock, addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break                       # listener closed under us
            peer = f"{addr[0]}:{addr[1]}"
            try:
                faults.check(_F_ACCEPT, peer=peer)
            except faults.InjectedFault:
                # injected accept failure: the client sees EOF and its
                # bounded-retry policy redials — detection, not a hang
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            conn = TcpConnection(sock)
            c = _Conn(self, conn, peer)
            with self._lock:
                if self._draining or self._closed:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                self.conns_accepted += 1
                self._conns.append(c)
            c.reader = threading.Thread(
                target=self._reader, args=(c,),
                name=f"thrill-fd-read-{peer}", daemon=True)
            c.writer = threading.Thread(
                target=self._writer, args=(c,),
                name=f"thrill-fd-write-{peer}", daemon=True)
            c.reader.start()
            c.writer.start()

    def _conn_closed(self, c: _Conn, why: str) -> None:
        with self._lock:
            if c in self._conns:
                self._conns.remove(c)
                self.conns_dropped += 1
        faults.note("recovery", what="front_door.conn_closed",
                    peer=c.peer, why=why)
        log = self.ctx.logger
        if log.enabled:
            log.line(event="front_door_conn_closed", peer=c.peer,
                     why=why)

    # -- reader ---------------------------------------------------------
    def _handshake(self, c: _Conn) -> bool:
        from ..common.timeouts import scaled
        conn = c.conn
        try:
            _exchange_auth_flag(conn, self.secret is not None)
            if self.secret is not None:
                conn.authenticate(self.secret, "server")
            frame = conn.recv_deadline(scaled(10.0))
            if not (isinstance(frame, (tuple, list)) and len(frame) == 2
                    and frame[0] == "hello"
                    and isinstance(frame[1], dict)):
                raise ConnectionError(f"bad hello {frame!r}")
            # version negotiation: a v2+ client offers [min, max], a
            # v1 client offers a single int (min == max). The server
            # picks the highest common version; no overlap is a TYPED
            # version_mismatch reject naming the supported range —
            # the client surfaces it as a permanent error, not a
            # redial-forever ConnectionError.
            offered = frame[1].get("proto", -1)
            try:
                if isinstance(offered, (list, tuple)) \
                        and len(offered) == 2:
                    cmin, cmax = int(offered[0]), int(offered[1])
                else:
                    cmin = cmax = int(offered)
            except (TypeError, ValueError):
                cmin = cmax = -1          # garbage: out of any range
            if cmin > cmax or cmax < PROTO_MIN or cmin > PROTO_MAX:
                c.enqueue(("reject", 0, "version_mismatch", 0.0,
                           f"server supports protocol "
                           f"[{PROTO_MIN},{PROTO_MAX}], client "
                           f"offered [{cmin},{cmax}]"))
                c.enqueue(("bye", "version mismatch"))
                return False
            c.proto = min(cmax, PROTO_MAX)
            c.tenant = str(frame[1].get("tenant") or "default")
            c.enqueue(("welcome", {"proto": c.proto,
                                   "range": [PROTO_MIN, PROTO_MAX]}))
            return True
        except (ConnectionError, OSError, CollectiveHangTimeout,
                wire.AuthError) as e:
            c.kill(f"handshake failed: {e!r}")
            return False

    def _reader(self, c: _Conn) -> None:
        if not self._handshake(c):
            return
        conn = c.conn
        while not c.dead and not self._closed:
            try:
                faults.check(F_CLIENT_DISCONNECT, peer=c.peer)
            except faults.InjectedFault:
                # the injected mid-stream client vanish: exactly what
                # a SIGKILLed client looks like from here
                c.kill("injected client disconnect")
                return
            try:
                frame = conn.recv_deadline(1.0)
            except CollectiveHangTimeout:
                if conn.broken:
                    # deadline fired MID-FRAME: a slow-loris client
                    # trickling bytes can never finish this frame —
                    # the link is condemned, drop it
                    self.slow_clients += 1
                    c.kill("slow-loris read (frame torn mid-read)")
                    return
                # between frames: just idle. A half-open client with
                # nothing in flight past the read timeout is dropped;
                # one with jobs running is kept (its results are
                # coming, the writer owns slow-drain detection).
                idle_s = time.monotonic() - c.t_last_frame
                if not c.inflight and idle_s > self.read_timeout_s:
                    c.enqueue(("bye", "idle timeout"))
                    # bounded courtesy: give the writer a moment to
                    # flush the bye, then drop
                    time.sleep(0.05)
                    c.kill("idle past read timeout (half-open)")
                    return
                continue
            except (ConnectionError, OSError, ValueError) as e:
                # ValueError: kill() closed the socket under this
                # blocked read (fileno() == -1 inside the poller)
                c.kill(f"client gone: {e!r}")
                return
            c.t_last_frame = time.monotonic()
            try:
                self._handle_frame(c, frame)
            except _Bye:
                c.kill("client bye")
                return

    def _handle_frame(self, c: _Conn, frame) -> None:
        if not isinstance(frame, (tuple, list)) or not frame:
            c.enqueue(("bye", f"bad frame {type(frame).__name__}"))
            raise _Bye()
        op = frame[0]
        if op == "bye":
            raise _Bye()
        if op == "submit" and len(frame) == 2 \
                and isinstance(frame[1], dict):
            self._handle_submit(c, frame[1])
            return
        c.enqueue(("bye", f"unknown frame {op!r}"))
        raise _Bye()

    def _handle_submit(self, c: _Conn, req: dict) -> None:
        jid = int(req.get("id", 0))
        name = str(req.get("pipeline") or "")
        tr = getattr(self.ctx, "tracer", None)
        # perf_counter, not monotonic: these stamps feed emit_span,
        # which places spans by perf_counter deltas (common/trace.py)
        t_accept = time.perf_counter()
        # elastic fence gate (regression: a queued-but-unaccepted job
        # during a resize): wait out any pending resize BEFORE any
        # verdict frame, so the accept below is stamped with the
        # post-resize generation and the job provably runs on the mesh
        # its accept named. No deadlock: this reader thread holds no
        # scheduler state, and the resize completes on the dispatcher
        # thread independently of it.
        with self._fence_cv:
            while self._fencing and not self._closed and not c.dead:
                self._fence_cv.wait(0.1)
        if self._draining:
            self._reject(c, jid, "draining",
                         round(self.drain_timeout_s, 3),
                         "front door is draining (SIGTERM): retry "
                         "against the relaunched service")
            return
        fn = self._pipelines.get(name)
        if fn is None:
            self._reject(c, jid, "unknown_pipeline", 0.0,
                         f"no pipeline registered under {name!r} "
                         f"(known: {sorted(self._pipelines)})")
            return
        deadline_s = req.get("deadline_s")
        deadline_at = (time.perf_counter() + float(deadline_s)
                       if deadline_s else None)
        args = req.get("args")
        import inspect
        streaming = inspect.isgeneratorfunction(fn)
        wrapper = self._make_job(c, jid, name, fn, args, deadline_at,
                                 t_accept, streaming)
        fut = self.ctx.submit(
            wrapper, tenant=c.tenant,
            name=f"fd-{c.tenant}-{jid}",
            weight=req.get("weight"))
        if fut.done():
            err = fut.exception(0)
            if isinstance(err, ShedLoad):
                self._reject(c, jid, err.kind, err.retry_after_s,
                             str(err))
                return
            if err is not None:
                self.jobs_rejected += 1
                c.enqueue(("error", jid, "submit", repr(err)[:300]))
                return
        self.jobs_submitted += 1
        with c.cv:
            c.inflight[jid] = fut
        # mode rides the accept so a client can decode items-mode
        # chunks AS THEY ARRIVE instead of waiting for the done frame;
        # v2 clients also get the generation the job will run under
        # (read AFTER the fence gate, so a concurrent resize can never
        # invalidate it)
        meta: Dict[str, Any] = {"mode": "items" if streaming
                                else "blob"}
        if c.proto >= 2:
            meta["gen"] = int(getattr(self.ctx, "generation", 0))
        c.enqueue(("accept", jid, meta))
        if tr is not None and tr.enabled:
            tr.emit_span("front_door", "admit", t_accept,
                         time.perf_counter(), tenant=c.tenant,
                         job=jid, pipeline=name)

    def _reject(self, c: _Conn, jid: int, kind: str,
                retry_after_s: float, msg: str) -> None:
        """One TYPED shed-load response — the never-silent contract:
        every rejection names its kind and when to retry."""
        self.jobs_rejected += 1
        c.enqueue(("reject", jid, kind, float(retry_after_s),
                   msg[:300]))
        log = self.ctx.logger
        if log.enabled:
            log.line(event="front_door_reject", peer=c.peer,
                     tenant=c.tenant, job=jid, kind=kind,
                     retry_after_s=retry_after_s)

    # -- the job wrapper (runs on the DISPATCHER) -----------------------
    def _make_job(self, c: _Conn, jid: int, name: str, fn: Callable,
                  args, deadline_at: Optional[float],
                  t_accept: float, streaming: bool) -> Callable:
        def job(ctx):
            t0 = time.perf_counter()
            if deadline_at is not None and t0 >= deadline_at:
                # queued past its deadline: a typed error frame, NOT a
                # pipeline abort — nothing ran, nothing needs healing
                self.deadline_expired += 1
                self._settle(c, jid, ("error", jid, "deadline",
                                      f"job spent {t0 - t_accept:.3f}s"
                                      f" queued, past its deadline"))
                return None
            try:
                if streaming:
                    out = self._stream_items(c, jid, fn, ctx, args)
                else:
                    out = self._stream_blob(c, jid, fn(ctx, args))
            except _StreamAborted:
                # the stream died (slow client / injected stream
                # fault) but the JOB is fine — typed error frame went
                # out already (or the conn is dead); nothing to heal
                return None
            except BaseException as e:
                # job failure: typed error frame BEFORE re-raising so
                # the scheduler's accounting (jobs_failed, heal) stays
                # truthful while the client still gets its verdict
                self._settle(c, jid, ("error", jid, "pipeline",
                                      repr(e)[:300]))
                raise
            self._settle(c, jid, None)
            tr = getattr(self.ctx, "tracer", None)
            if tr is not None and tr.enabled:
                tr.emit_span("front_door", f"stream:{name}", t0,
                             time.perf_counter(), tenant=c.tenant,
                             job=jid, chunks=out)
            return None

        return job

    def _settle(self, c: _Conn, jid: int, frame) -> None:
        if frame is not None:
            c.enqueue(frame)
        with c.cv:
            c.inflight.pop(jid, None)
            c.cv.notify_all()

    def _offer_chunk(self, c: _Conn, jid: int, seq: int,
                     payload: bytes) -> None:
        try:
            faults.check(_F_STREAM, job=jid, seq=seq)
        except faults.InjectedFault as e:
            # a torn result stream is a STREAM failure, not a job
            # failure: typed error frame, connection survives, the
            # scheduler never sees it (nothing to heal)
            self._settle(c, jid, ("error", jid, "stream",
                                  f"result stream aborted: {e}"))
            raise _StreamAborted()
        if not c.offer(("chunk", jid, seq, payload), len(payload),
                       self.write_timeout_s):
            if not c.dead:
                # egress stayed full past the write budget: the
                # client is alive but not draining — shed the
                # CONNECTION (typed verdict), keep the mesh moving
                self.slow_clients += 1
                faults.note("recovery",
                            what="front_door.slow_client_shed",
                            peer=c.peer, job=jid, seq=seq)
                c.kill("slow client: egress past write budget")
            raise _StreamAborted()
        self.chunks_sent += 1

    def _stream_blob(self, c: _Conn, jid: int, result) -> int:
        """Serialize once, stream in bounded chunks as the egress
        drains. Returns the chunk count."""
        try:
            payload = wire.dumps(result,
                                 allow_pickle=c.conn.authenticated)
        except Exception as e:
            self._settle(c, jid, ("error", jid, "encode",
                                  f"result not wire-encodable: "
                                  f"{e!r}"[:300]))
            raise _StreamAborted()
        n = self.chunk_bytes
        chunks = [payload[i:i + n] for i in range(0, len(payload), n)] \
            or [b""]
        for seq, chunk in enumerate(chunks):
            self._offer_chunk(c, jid, seq, chunk)
        c.enqueue(("done", jid, len(chunks), {"mode": "blob"}))
        return len(chunks)

    def _stream_items(self, c: _Conn, jid: int, fn, ctx, args) -> int:
        """Generator pipelines: each yielded item is encoded and
        offered the moment it exists — the client consumes results
        while the job is still running."""
        seq = 0
        for item in fn(ctx, args):
            try:
                payload = wire.dumps(item,
                                     allow_pickle=c.conn.authenticated)
            except Exception as e:
                self._settle(c, jid, ("error", jid, "encode",
                                      f"item {seq} not "
                                      f"wire-encodable: {e!r}"[:300]))
                raise _StreamAborted()
            self._offer_chunk(c, jid, seq, payload)
            seq += 1
        c.enqueue(("done", jid, seq, {"mode": "items"}))
        return seq

    # -- writer ---------------------------------------------------------
    def _writer(self, c: _Conn) -> None:
        conn = c.conn
        while True:
            with c.cv:
                while not c.out and not c.dead and not self._closed:
                    c.cv.wait(0.25)
                if c.dead or (self._closed and not c.out):
                    return
                frame, nbytes = c.out.popleft()
                c.out_bytes -= nbytes
                c.cv.notify_all()
            try:
                faults.check(_F_SLOW, peer=c.peer)
            except faults.InjectedFault:
                self.slow_clients += 1
                c.kill("injected slow client")
                return
            # WRITE deadline on every frame: a client that stopped
            # reading blocks at most write_timeout_s of this writer
            # thread (never the dispatcher), then gets dropped
            try:
                conn.send_bounded(frame, self.write_timeout_s)
            except TimeoutError:
                self.slow_clients += 1
                c.kill("slow client: frame write past deadline")
                return
            except (ConnectionError, OSError, ValueError) as e:
                c.kill(f"client write failed: {e!r}")
                return

    # -- resize verdict gate --------------------------------------------
    def begin_resize_fence(self) -> None:
        """Called by ``Context.resize`` BEFORE it requests the
        dispatcher fence: from here until :meth:`end_resize_fence`,
        no admission verdict frame leaves the front door (readers
        park at the gate in ``_handle_submit``). Re-entrant — nested
        resizes each count."""
        with self._fence_cv:
            self._fencing += 1

    def end_resize_fence(self) -> None:
        """Open the gate after the fenced swap completed (or failed —
        callers pair this in a ``finally``). Parked readers re-read
        ``ctx.generation`` after waking, so their accept frames carry
        the post-resize generation."""
        with self._fence_cv:
            self._fencing = max(0, self._fencing - 1)
            self._fence_cv.notify_all()

    # -- drain / close --------------------------------------------------
    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful drain: stop accepting, typed ``draining`` rejects
        for new submits, finish every in-flight job and flush its
        stream, then ``bye``. True = fully drained inside the budget;
        False = the budget expired and remaining clients were dropped
        (each with a loud note, never silently)."""
        timeout_s = (self.drain_timeout_s if timeout_s is None
                     else float(timeout_s))
        with self._lock:
            if self._draining:
                return self.drained.wait(timeout_s)
            self._draining = True
        log = self.ctx.logger
        if log.enabled:
            log.line(event="front_door_drain", timeout_s=timeout_s)
        try:
            self._srv.close()
        except OSError:
            pass
        deadline = time.monotonic() + timeout_s
        clean = True
        while True:
            with self._lock:
                live = list(self._conns)
            busy = [c for c in live if not c.idle()]
            if not busy:
                break
            if time.monotonic() >= deadline:
                clean = False
                for c in busy:
                    faults.note("recovery",
                                what="front_door.drain_expired",
                                peer=c.peer,
                                inflight=len(c.inflight))
                    c.kill("drain budget expired")
                break
            time.sleep(0.05)
        with self._lock:
            live = list(self._conns)
        for c in live:
            c.enqueue(("bye", "drained"))
        # bounded courtesy flush of the byes, then close
        t_end = time.monotonic() + 1.0
        while time.monotonic() < t_end and any(c.out for c in live):
            time.sleep(0.02)
        for c in live:
            c.kill("drained")
        self.drained.set()
        return clean

    def install_sigterm(self) -> None:
        """SIGTERM -> graceful drain on a background thread (signal
        handlers must not block); chains any previous handler."""
        import signal
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, sig_frame):
            threading.Thread(target=self.drain,
                             name="thrill-fd-drain",
                             daemon=True).start()
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, sig_frame)

        signal.signal(signal.SIGTERM, handler)

    def close(self, drain: bool = True) -> None:
        if self._closed:
            return
        if drain:
            self.drain()
        self._closed = True
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            live = list(self._conns)
        for c in live:
            c.kill("front door closed")
        if self.ctx.front_door is self:
            self.ctx.front_door = None


class _Bye(Exception):
    """Internal: client ended the session."""


class _StreamAborted(Exception):
    """Internal: this job's result stream died (slow client, injected
    stream fault, dead connection) — the job itself is fine."""


def maybe_start(ctx) -> Optional[FrontDoor]:
    """Start the front door when THRILL_TPU_SERVE_PORT names a port
    (mirrors common/metrics.py maybe_start). A bind failure is loud
    and degrades to no front door — the job itself must still run."""
    raw = os.environ.get("THRILL_TPU_SERVE_PORT", "")
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        import sys
        print(f"thrill_tpu: bad THRILL_TPU_SERVE_PORT={raw!r}; "
              f"front door disabled", file=sys.stderr)
        return None
    if port <= 0:
        return None
    try:
        return FrontDoor(ctx, port)
    except (OSError, RuntimeError) as e:
        import sys
        print(f"thrill_tpu: front door failed to start on port "
              f"{port}: {e}", file=sys.stderr)
        return None
