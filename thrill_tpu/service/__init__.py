"""Service plane: always-on multi-tenant pipeline serving.

The reference framework (and this reproduction until ISSUE 8) is
batch-shaped: one Context per program, torn down at exit. The ROADMAP
north star — "heavy traffic from millions of users" — needs the
opposite: ONE long-lived Context serving many pipelines submitted by
many clients. PR 8 delivered the failure-domain precondition (a
Context survives pipeline aborts, link drops and wedged peers); this
package turns that healed Context into a query service:

* :mod:`.scheduler` — ``ctx.submit(pipeline_fn, tenant=...) ->
  JobFuture``: concurrent submission from client threads, serialized
  onto the SPMD mesh in weighted-fair order across tenants, each job
  in its own generation-scoped failure domain (a failed job raises
  :class:`~thrill_tpu.api.PipelineError` into its OWN future and heals
  only its generation — the queue never stalls).
* :mod:`.tenancy` — per-tenant HBM budgets enforced through the
  existing :class:`~thrill_tpu.mem.hbm.HbmGovernor` ledger: one
  tenant's memory pressure spills ITS cold shards (and rides its own
  PR-5 escalation ladder), never another tenant's cached results.
* :mod:`.plan_store` — a vfs-backed on-disk store for the learned
  plan state keyed by the ``MeshExec.cached`` / ``FusionPlan``
  composite identities (sticky exchange capacities, narrow specs,
  plan kinds, pre-shuffle verdicts), so a warm restart re-runs a
  known pipeline with ``plan_builds == 0`` — no data-driven host plan
  syncs at all.
* :mod:`.front_door` / :mod:`.client` — the NETWORK edge (ISSUE 18):
  a TCP admission protocol over the PR-8 authenticated transport with
  per-tenant token-bucket rate limits and bounded queues ahead of the
  scheduler, typed shed-load rejections carrying retry-after hints,
  chunked result streaming as job egress drains, read/write deadlines
  on every client socket (slow-loris and half-open clients are
  dropped, never waited on), and graceful SIGTERM drain. The client
  library retries sheds with ``max(server hint, full jitter)``.
"""

from .scheduler import (JobFuture, QueueFull, RateLimited,  # noqa: F401
                        Scheduler, ShedLoad, TenantQueueFull)
from .tenancy import activate, configure, set_budget  # noqa: F401
from .plan_store import PlanStore  # noqa: F401
from .front_door import FrontDoor  # noqa: F401
from .client import (FrontDoorClient, Rejected,  # noqa: F401
                     RemoteJob, RemoteJobError)
