"""Concurrent pipeline scheduling on one long-lived Context.

``ctx.submit(pipeline_fn, tenant=...)`` accepts pipelines from any
number of client threads and returns a :class:`JobFuture`. A single
dispatcher thread drains the queue and runs each job on the SPMD mesh
— the Context (like the reference's) is not re-entrant, so jobs
SERIALIZE on the device; concurrency buys queueing, fairness and
isolation, not co-scheduling. Each job runs inside its own
``ctx.pipeline()`` failure domain (api/context.py): a failing job
surfaces its :class:`~thrill_tpu.api.PipelineError` into its OWN
future while the Context heals that generation — later jobs run
normally, the queue never stalls. An UNRECOVERABLE verdict (heartbeat-
confirmed dead peer, failed heal) fails the whole queue loudly: that
Context cannot serve anymore and the supervised-relaunch path owns it.

Fairness is start-time weighted-fair queueing (SFQ) across tenants:
job ``start_tag = max(global_vtime, tenant.finish)``, ``tenant.finish
= start_tag + 1/weight``; the dispatcher always runs the queued job
with the smallest start tag (ties break by tenant name, then FIFO), so
a tenant with weight 2 gets ~2x the job slots of a weight-1 tenant
under sustained load while an idle tenant's first job is admitted
immediately. Weights come from ``THRILL_TPU_SERVE_WEIGHTS``
("a=3,b=1") or per-submit ``weight=``.

Cross-rank admission order (multi-controller meshes): there is no
central master — every controller must submit the same jobs at the
same program points (the lockstep contract every collective already
has), but client-thread timing may enqueue them in different LOCAL
orders. Rank 0's dispatcher therefore picks the next job and
broadcasts an ordering frame ``(tenant, tenant_seq)`` over the host
control plane (``ctx.net``); the other ranks run exactly that job.
The frames ride the same generation-tagged wire as every PR-8
control frame, so a heal's stale-frame drain discards ordering frames
of an aborted generation along with everything else.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..common import faults

# fired at job admission, INSIDE the job's pipeline() failure domain:
# an armed fire aborts exactly that job's generation — its future gets
# the PipelineError, the Context heals, the next job runs normally
_F_SUBMIT = faults.declare("service.submit")


class ShedLoad(RuntimeError):
    """Base of every typed admission rejection — a shed job's future
    (and the front door's reject frame) always carries one of these,
    never a silent drop. ``kind`` is the rejection taxonomy label
    (ARCHITECTURE.md "Front door & overload control"); ``retry_after_s``
    is the server's backoff hint — the earliest moment a retry could
    plausibly be admitted (queue drain estimate for depth sheds, token
    refill time for rate sheds). Clients honoring it
    (service/client.py submit_retry) turn an overload spike into a
    delayed success instead of a retry storm."""

    kind = "shed"

    def __init__(self, msg: str, tenant: str,
                 retry_after_s: float = 0.0) -> None:
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = max(float(retry_after_s), 0.0)


class QueueFull(ShedLoad):
    """submit() shed this job: the admission queue sits at its
    THRILL_TPU_SERVE_QUEUE depth cap. The rejection is IMMEDIATE and
    per-job — the returned future is born resolved with this error,
    nothing was queued, and the scheduler keeps serving everything
    already admitted. Carries the tenant and the depth/cap pair so a
    client's backpressure loop can tell "my tenant is flooding" from
    "the service is drowning"."""

    kind = "queue_full"

    def __init__(self, tenant: str, depth: int, cap: int,
                 retry_after_s: float = 0.0) -> None:
        super().__init__(
            f"admission queue full: depth {depth} >= cap {cap} "
            f"(THRILL_TPU_SERVE_QUEUE); job for tenant {tenant!r} shed",
            tenant, retry_after_s)
        self.depth = depth
        self.cap = cap


class TenantQueueFull(ShedLoad):
    """submit() shed this job: THIS tenant's queue sits at its
    THRILL_TPU_SERVE_TENANT_QUEUE depth cap. Per-tenant bounding is
    the isolation half of backpressure: one flooding tenant fills its
    own queue and sheds, while every other tenant keeps its full
    admission depth."""

    kind = "tenant_queue_full"

    def __init__(self, tenant: str, depth: int, cap: int,
                 retry_after_s: float = 0.0) -> None:
        super().__init__(
            f"tenant queue full: tenant {tenant!r} at depth {depth} "
            f">= cap {cap} (THRILL_TPU_SERVE_TENANT_QUEUE); job shed",
            tenant, retry_after_s)
        self.depth = depth
        self.cap = cap


class RateLimited(ShedLoad):
    """submit() shed this job: the tenant's token bucket
    (THRILL_TPU_SERVE_RATE) is empty. ``retry_after_s`` is the exact
    refill time of the next token — the one rejection whose hint is a
    guarantee, not an estimate."""

    kind = "rate_limited"

    def __init__(self, tenant: str, rate: float,
                 retry_after_s: float) -> None:
        super().__init__(
            f"rate limited: tenant {tenant!r} over {rate:g} jobs/s "
            f"(THRILL_TPU_SERVE_RATE); retry after "
            f"{retry_after_s:.3f}s", tenant, retry_after_s)
        self.rate = rate


def _queue_cap(var: str = "THRILL_TPU_SERVE_QUEUE") -> int:
    """Admission depth cap from ``var``; 0 = unbounded (the default).
    Malformed values are skipped loudly — a typo must not silently
    shed traffic."""
    v = os.environ.get(var, "")
    if not v:
        return 0
    try:
        cap = int(v)
    except ValueError:
        import sys
        print(f"thrill_tpu.service: ignoring malformed "
              f"{var}={v!r} (want an integer); "
              f"queue is unbounded", file=sys.stderr)
        return 0
    return max(cap, 0)


class _TokenBucket:
    """One tenant's admission token bucket: ``rate`` tokens/s refill,
    ``burst`` capacity (a freshly-seen tenant starts full, so a burst
    up to ``burst`` jobs is admitted before pacing kicks in)."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t_last = time.monotonic()

    def try_take(self) -> float:
        """0.0 when a token was taken (admitted); else the seconds
        until the next token exists — the retry-after hint."""
        now = time.monotonic()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t_last) * self.rate)
        self.t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate


def _rate_entry(v: str):
    """One THRILL_TPU_SERVE_RATE value: ``rps`` or ``rps:burst``."""
    rps, _, burst = v.partition(":")
    r = float(rps)
    if r <= 0:
        raise ValueError(v)
    b = float(burst) if burst else max(1.0, r)
    if b < 1.0:
        raise ValueError(v)
    return (r, b)


def _parse_rates(spec: str) -> Dict[str, tuple]:
    """Parse THRILL_TPU_SERVE_RATE ("a=5,b=2:10,default=50") —
    jobs/s[:burst] per tenant; the ``default`` key covers tenants not
    named. Malformed entries are skipped loudly."""
    from ..common.config import parse_kv_spec
    return parse_kv_spec(spec, _rate_entry, "SERVE_RATE")


def _weight(v: str) -> float:
    w = float(v)
    if w <= 0:
        raise ValueError(v)
    return w


#: accept-to-result latency histogram buckets: fixed log2 boundaries,
#: bucket i = [2^(i-1), 2^i) milliseconds (bucket 0 = sub-millisecond).
#: 28 buckets reach ~37 hours. The BUCKETING is deterministic — two
#: runs whose jobs land in the same buckets report identical
#: serve_p50/p99 — which is what lets the quantiles ride stats
#: contracts where raw wall clocks cannot.
_LAT_BUCKETS = 28


def _lat_bucket(ms: float) -> int:
    return min(max(int(ms), 0).bit_length(), _LAT_BUCKETS - 1)


def _lat_quantile(counts: List[int], q: float) -> float:
    """Upper bucket boundary (ms) at quantile ``q`` — 2^i for bucket
    i, deterministic given the counts."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    need = max(1, -(-int(total * q * 1000) // 1000))  # ceil(q*total)
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= need:
            return float(1 << i)
    return float(1 << (_LAT_BUCKETS - 1))


def _parse_weights(spec: str) -> Dict[str, float]:
    """Parse THRILL_TPU_SERVE_WEIGHTS ("a=3,b=1.5"); malformed entries
    are skipped loudly (a typo must not silently starve a tenant)."""
    from ..common.config import parse_kv_spec
    return parse_kv_spec(spec, _weight, "SERVE_WEIGHTS")


class JobFuture:
    """Handle to one submitted pipeline.

    ``result()`` blocks until the job ran and returns its value — or
    raises the job's error (:class:`~thrill_tpu.api.PipelineError` for
    a scoped pipeline failure, the original abort for an unrecoverable
    one). ``queue_wait_s`` / ``run_s`` / ``generation`` are populated
    when the job completes."""

    def __init__(self, job_id: int, tenant: str, name: str) -> None:
        self.job_id = job_id
        self.tenant = tenant
        self.name = name
        self.queue_wait_s = 0.0
        self.run_s = 0.0
        self.generation: Optional[int] = None
        # plan choices the decision ledger recorded while THIS job
        # ran (the serve lane's plan-choices-per-job metric)
        self.plan_decisions = 0
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None

    def _finish(self, result: Any = None,
                error: Optional[BaseException] = None) -> None:
        self._result = result
        self._error = error
        self._event.set()

    @classmethod
    def failed(cls, job_id: int, tenant: str, name: str,
               error: BaseException) -> "JobFuture":
        """A future born resolved-with-error: the one shape every
        rejected submission (dead scheduler, closing scheduler, closed
        Context) hands back."""
        fut = cls(job_id, tenant, name)
        fut._finish(error=error)
        return fut

    def done(self) -> bool:
        return self._event.is_set()

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise TimeoutError(f"job {self.job_id} ({self.name}) still "
                               f"queued/running after {timeout}s")
        return self._error

    def result(self, timeout: Optional[float] = None) -> Any:
        err = self.exception(timeout)
        if err is not None:
            raise err
        return self._result


class _Job:
    __slots__ = ("fn", "tenant", "name", "future", "t_submit",
                 "tenant_seq", "start_tag")

    def __init__(self, fn, tenant: str, name: str, future: JobFuture,
                 tenant_seq: int, start_tag: float) -> None:
        self.fn = fn
        self.tenant = tenant
        self.name = name
        self.future = future
        self.t_submit = time.monotonic()
        self.tenant_seq = tenant_seq
        self.start_tag = start_tag


class _TenantQ:
    __slots__ = ("weight", "finish", "jobs", "seq")

    def __init__(self, weight: float) -> None:
        self.weight = weight
        self.finish = 0.0         # virtual finish tag of the last job
        self.jobs: List[_Job] = []
        self.seq = 0              # per-tenant submission counter


class WfqQueue:
    """Start-time fair queue over per-tenant FIFOs (caller locks)."""

    def __init__(self, weights: Optional[Dict[str, float]] = None) -> None:
        self._tenants: Dict[str, _TenantQ] = {}
        self._weights = dict(weights or {})
        self._vtime = 0.0          # start tag of the job last serviced
        self.depth = 0
        self.depth_peak = 0

    def set_weight(self, tenant: str, weight: float) -> None:
        self._weights[tenant] = float(weight)
        tq = self._tenants.get(tenant)
        if tq is not None:
            tq.weight = float(weight)

    def tenant_depth(self, tenant: str) -> int:
        tq = self._tenants.get(tenant)
        return len(tq.jobs) if tq is not None else 0

    def push(self, fn, tenant: str, name: str, future: JobFuture) -> _Job:
        tq = self._tenants.get(tenant)
        if tq is None:
            tq = self._tenants[tenant] = _TenantQ(
                self._weights.get(tenant, 1.0))
        start = max(self._vtime, tq.finish)
        tq.finish = start + 1.0 / tq.weight
        tq.seq += 1
        if not name:
            name = f"{tenant}-{tq.seq}"
            future.name = name
        job = _Job(fn, tenant, name, future, tq.seq, start)
        tq.jobs.append(job)
        self.depth += 1
        if self.depth > self.depth_peak:
            self.depth_peak = self.depth
        return job

    def pop(self) -> Optional[_Job]:
        """The queued job with the smallest start tag (ties: tenant
        name, then FIFO — per-tenant FIFOs keep submission order)."""
        best_t = None
        for t, tq in sorted(self._tenants.items()):
            if not tq.jobs:
                continue
            if best_t is None or (tq.jobs[0].start_tag
                                  < self._tenants[best_t].jobs[0].start_tag):
                best_t = t
        if best_t is None:
            return None
        job = self._tenants[best_t].jobs.pop(0)
        self.depth -= 1
        self._vtime = max(self._vtime, job.start_tag)
        return job

    def take(self, tenant: str, tenant_seq: int) -> Optional[_Job]:
        """Remove a SPECIFIC job (non-root ranks following rank 0's
        ordering frame). None until the lockstep submission arrives."""
        tq = self._tenants.get(tenant)
        if tq is None:
            return None
        for i, job in enumerate(tq.jobs):
            if job.tenant_seq == tenant_seq:
                tq.jobs.pop(i)
                self.depth -= 1
                self._vtime = max(self._vtime, job.start_tag)
                return job
        return None

    def drain(self) -> List[_Job]:
        out = [j for tq in self._tenants.values() for j in tq.jobs]
        for tq in self._tenants.values():
            tq.jobs.clear()
        self.depth = 0
        return out


class Scheduler:
    """Owns the admission queue and the dispatcher thread of one
    Context. Constructed lazily by ``Context.submit``."""

    def __init__(self, ctx) -> None:
        self.ctx = ctx
        from . import tenancy
        tenancy.configure(ctx)          # env budgets, idempotent
        self._cv = threading.Condition()
        self.queue = WfqQueue(_parse_weights(
            os.environ.get("THRILL_TPU_SERVE_WEIGHTS", "")))
        self.jobs_submitted = 0
        self.jobs_failed = 0
        # bounded admission (THRILL_TPU_SERVE_QUEUE): jobs shed at the
        # cap, total and per tenant. Enforced ONLY on single-controller
        # meshes — admission is per-rank client-thread timing, so two
        # controllers could legally disagree on which submit hits the
        # cap, and a job rank 0 runs that a follower rejected wedges
        # the mesh collectives. Multi-controller: loud one-time skip.
        self.queue_cap = _queue_cap()
        # per-tenant backpressure (ISSUE 18): a flooding tenant fills
        # its OWN bounded queue / drains its OWN token bucket and
        # sheds, while other tenants keep their full admission depth.
        # Same single-controller-only rule as the global cap.
        self.tenant_queue_cap = _queue_cap("THRILL_TPU_SERVE_TENANT_QUEUE")
        self._rates = _parse_rates(
            os.environ.get("THRILL_TPU_SERVE_RATE", ""))
        self._buckets: Dict[str, _TokenBucket] = {}
        self.jobs_rejected = 0
        self.jobs_rate_limited = 0
        self.rejected_by_tenant: Dict[str, int] = {}
        # EWMA of completed-job run seconds: the drain-time estimate
        # behind queue-full retry-after hints (depth * ewma)
        self._run_ewma_s = 0.0
        self._cap_skip_noted = False
        # resize fencing (Context.resize): callables the dispatcher
        # runs EXCLUSIVELY, between jobs — never concurrent with a
        # pipeline that would trace W-shaped programs mid-swap
        self._fences: List[Any] = []
        # jobs that LEFT the system (resolved any way: result, scoped
        # failure, drain) — the live metrics endpoint's jobs_in_flight
        # gauge is submitted - done (common/metrics.py)
        self.jobs_done = 0
        # per-tenant accept-to-result latency histograms (fixed log2
        # buckets — see _LAT_BUCKETS): serve_p50/p99 in
        # overall_stats() and the Prometheus histogram export both
        # read these. Only jobs that RAN are recorded (a drained
        # future's latency is the shutdown's, not the service's).
        self._lat: Dict[str, List[int]] = {}
        self._lat_count: Dict[str, int] = {}
        self._lat_sum_ms: Dict[str, float] = {}
        self._job_ids = 0
        self._closing = False
        self._dead: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._loop, name="thrill-serve-dispatch", daemon=True)
        self._thread.start()

    # -- client side ----------------------------------------------------
    def submit(self, fn: Callable, tenant: str = "default",
               name: str = "", weight: Optional[float] = None
               ) -> JobFuture:
        """Queue ``fn(ctx) -> result`` for execution; thread-safe."""
        with self._cv:
            self._job_ids += 1
            # the default name must be RANK-DETERMINISTIC under the
            # per-tenant lockstep contract: the global job counter
            # depends on how tenants' client threads interleave, which
            # may legally differ across ranks — the follower's
            # divergence check compares names, so a counter-based
            # default would poison a legal submission order. The
            # per-tenant seq is what the contract agrees on.
            if self._dead is not None:
                return JobFuture.failed(
                    self._job_ids, tenant,
                    name or f"job-{self._job_ids}",
                    RuntimeError(
                        f"scheduler is dead after an unrecoverable "
                        f"abort: {self._dead!r}"))
            if self._closing:
                return JobFuture.failed(
                    self._job_ids, tenant,
                    name or f"job-{self._job_ids}",
                    RuntimeError("scheduler is closed"))
            err = self._admission_verdict(tenant)
            if err is not None:
                if self.ctx.net.num_workers > 1 \
                        or self.ctx.mesh_exec.num_processes > 1:
                    # cross-rank divergent rejection would be fatal
                    # (see __init__) — never shed on multi-controller
                    if not self._cap_skip_noted:
                        self._cap_skip_noted = True
                        import sys
                        print("thrill_tpu.service: THRILL_TPU_SERVE_"
                              "QUEUE / _TENANT_QUEUE / _RATE ignored "
                              "on a multi-controller mesh — per-rank "
                              "shed decisions could diverge and "
                              "desync the lockstep admission "
                              "contract; admission is unbounded",
                              file=sys.stderr)
                else:
                    return self._reject(tenant, name, err)
            future = JobFuture(self._job_ids, tenant, name)
            if weight is not None:
                self.queue.set_weight(tenant, weight)
            job = self.queue.push(fn, tenant, future.name, future)
            self.jobs_submitted += 1
            depth = self.queue.depth
            self._cv.notify_all()
        log = self.ctx.logger
        if log.enabled:
            log.line(event="job_submit", job=future.job_id,
                     name=future.name, tenant=tenant,
                     queue_depth=depth)
        return future

    def _admission_verdict(self, tenant: str) -> Optional[ShedLoad]:
        """The typed shed verdict for one would-be submission, or None
        when admitted (caller holds _cv). Check order: rate limit
        first (cheapest hint, and a paced tenant should not consume
        queue headroom), then the tenant depth cap, then the global
        cap. Retry-after hints: token refill time is exact; depth
        sheds estimate drain as depth * run-seconds EWMA."""
        rate = self._rates.get(tenant) or self._rates.get("default")
        if rate is not None:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = _TokenBucket(*rate)
            wait = bucket.try_take()
            if wait > 0.0:
                return RateLimited(tenant, rate[0], wait)
        ewma = self._run_ewma_s or 0.05
        if self.tenant_queue_cap:
            depth = self.queue.tenant_depth(tenant)
            if depth >= self.tenant_queue_cap:
                return TenantQueueFull(
                    tenant, depth, self.tenant_queue_cap,
                    retry_after_s=round(depth * ewma, 3))
        if self.queue_cap and self.queue.depth >= self.queue_cap:
            depth = self.queue.depth
            return QueueFull(tenant, depth, self.queue_cap,
                             retry_after_s=round(depth * ewma, 3))
        return None

    def _reject(self, tenant: str, name: str,
                err: ShedLoad) -> JobFuture:
        """Shed one job with its typed verdict (caller holds _cv)."""
        self.jobs_rejected += 1
        if isinstance(err, RateLimited):
            self.jobs_rate_limited += 1
        n = self.rejected_by_tenant.get(tenant, 0) + 1
        self.rejected_by_tenant[tenant] = n
        fut = JobFuture.failed(self._job_ids, tenant,
                               name or f"job-{self._job_ids}", err)
        log = self.ctx.logger
        if log.enabled:
            log.line(event="job_reject", tenant=tenant, kind=err.kind,
                     retry_after_s=err.retry_after_s,
                     depth=self.queue.depth, tenant_rejected=n,
                     jobs_rejected=self.jobs_rejected)
        if n == 1:
            # first shed PER TENANT goes to stderr: a flooding client
            # must be visible even without the JSON log
            import sys
            print(f"thrill_tpu.service: shedding load for tenant "
                  f"{tenant!r} — {err}", file=sys.stderr)
        return fut

    def fence(self, fn: Callable[[], Any],
              timeout: Optional[float] = None) -> Any:
        """Run ``fn()`` EXCLUSIVELY on the dispatcher thread, at the
        next job boundary, and return its result (or re-raise its
        error). Fences take PRIORITY over queued jobs — under
        sustained traffic the queue may never drain, and a resize must
        not wait for it. This is how ``Context.resize`` swaps the mesh
        under live traffic: the in-flight job finishes on the old W,
        queued jobs run on the new — no pipeline ever observes a
        half-swapped mesh.

        Deliberately NOT wrapped in ``ctx.pipeline()``: pipeline()
        restores the parent generation on exit, which would undo the
        generation bump a resize performs. Single-controller only (the
        callers that need multi-controller coordination — there are
        none today — would have to broadcast the fence like a job)."""
        if self.ctx.net.num_workers > 1 \
                or self.ctx.mesh_exec.num_processes > 1:
            raise RuntimeError(
                "Scheduler.fence is single-controller only: a fence is "
                "not part of the cross-rank admission agreement")
        done = threading.Event()
        cell: Dict[str, Any] = {}
        with self._cv:
            if self._dead is not None:
                raise RuntimeError(
                    f"scheduler is dead after an unrecoverable abort: "
                    f"{self._dead!r}")
            self._fences.append((fn, done, cell))
            self._cv.notify_all()
        if not done.wait(timeout):
            raise TimeoutError(
                f"fence did not run within {timeout}s (dispatcher "
                f"busy or stopped)")
        if "error" in cell:
            raise cell["error"]
        return cell.get("result")

    def _run_fence(self, fence) -> None:
        fn, done, cell = fence
        try:
            cell["result"] = fn()
        except BaseException as e:
            cell["error"] = e
        finally:
            done.set()

    def _fail_fences(self, fences, cause: str) -> None:
        for _fn, done, cell in fences:
            cell["error"] = RuntimeError(cause)
            done.set()

    @property
    def alive(self) -> bool:
        """The dispatcher thread still owns the mesh/control plane."""
        return self._thread.is_alive()

    def stats(self) -> dict:
        with self._cv:
            return {"jobs_submitted": self.jobs_submitted,
                    "jobs_failed": self.jobs_failed,
                    "jobs_rejected": self.jobs_rejected,
                    "jobs_rate_limited": self.jobs_rate_limited,
                    "queue_depth_peak": self.queue.depth_peak}

    def _note_latency(self, tenant: str, seconds: float) -> None:
        ms = seconds * 1e3
        with self._cv:
            counts = self._lat.get(tenant)
            if counts is None:
                counts = self._lat[tenant] = [0] * _LAT_BUCKETS
                self._lat_count[tenant] = 0
                self._lat_sum_ms[tenant] = 0.0
            counts[_lat_bucket(ms)] += 1
            self._lat_count[tenant] += 1
            self._lat_sum_ms[tenant] += ms

    def latency_quantiles(self) -> dict:
        """Per-tenant accept-to-result p50/p99 (log2-bucket upper
        bounds, ms) — the overall_stats() serve-latency summary the
        front-door work will be judged by."""
        with self._cv:
            return {
                "serve_p50_ms": {t: _lat_quantile(c, 0.50)
                                 for t, c in sorted(self._lat.items())},
                "serve_p99_ms": {t: _lat_quantile(c, 0.99)
                                 for t, c in sorted(self._lat.items())},
            }

    def latency_histogram(self) -> dict:
        """Raw per-tenant histogram state for the Prometheus export:
        {tenant: (bucket_counts, count, sum_ms)}."""
        with self._cv:
            return {t: (list(c), self._lat_count[t],
                        self._lat_sum_ms[t])
                    for t, c in sorted(self._lat.items())}

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain queued jobs, then stop the dispatcher. Called by
        ``Context.close`` — submitted futures always resolve."""
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        t = self._thread
        if t.is_alive() and t is not threading.current_thread():
            from ..common.timeouts import scaled
            t.join(timeout=timeout if timeout is not None
                   else scaled(300.0))
            if t.is_alive():
                import sys
                print("thrill_tpu.service: dispatcher thread did not "
                      "drain before close timeout", file=sys.stderr)

    # -- dispatcher side ------------------------------------------------
    def _loop(self) -> None:
        while True:
            job = self._next_job()
            if job is None:
                break
            self._run(job)
        # whatever ended the loop, no submitted future may be left
        # pending — close()'s contract is that every future resolves
        # (_poison already drained on the dead paths; this covers a
        # rank whose local queue still held jobs at the sentinel).
        # Pending fences resolve too: a resize blocked on fence()
        # must not hang forever on a stopping dispatcher.
        with self._cv:
            stranded = self.queue.drain()
            fences, self._fences = self._fences, []
            self.jobs_failed += len(stranded)
            self.jobs_done += len(stranded)
        for job in stranded:
            job.future._finish(error=RuntimeError(
                "scheduler stopped before this job ran"))
        self._fail_fences(fences,
                          "scheduler stopped before this fence ran")

    def _next_job(self) -> Optional[_Job]:
        net = self.ctx.net
        multi = net.num_workers > 1
        if not multi or net.group.my_rank == 0:
            while True:
                fence = None
                with self._cv:
                    while True:
                        if self._dead is not None:
                            job = None
                            break
                        if self._fences:
                            # between-jobs exclusivity: the fence runs
                            # HERE, on the dispatcher thread, before
                            # the next job is even picked (fences are
                            # single-controller only — see fence())
                            fence = self._fences.pop(0)
                            job = None
                            break
                        job = self.queue.pop()
                        if job is not None or self._closing:
                            break
                        self._cv.wait()
                if fence is None:
                    break
                self._run_fence(fence)
            if multi:
                # the admission agreement: rank 0's pick becomes the
                # cluster's next job (or the drain sentinel). The
                # frame rides the generation-tagged control plane.
                # (tenant, tenant_seq) identifies the job ONLY when
                # each tenant's submission order agrees across ranks —
                # the per-tenant half of the lockstep contract (one
                # submitting thread per tenant, or an order the app
                # makes rank-deterministic). The job NAME rides along
                # so a violated contract dies loudly on the follower
                # instead of silently running different pipelines in
                # the same collective slot.
                frame = (None if job is None
                         else (job.tenant, job.tenant_seq, job.name))
                try:
                    net.broadcast(frame, origin=0)
                except Exception as e:
                    if job is not None:
                        # already popped: _poison's drain won't see it,
                        # count its failure here
                        with self._cv:
                            self.jobs_failed += 1
                            self.jobs_done += 1
                        job.future._finish(error=e)
                        self._poison(e)
                    return None
            return job
        # non-root: follow rank 0's ordering frame, then wait for the
        # lockstep submission to arrive locally
        try:
            frame = net.broadcast(None, origin=0)
        except Exception as e:
            self._poison(e)
            return None
        if frame is None:
            return None
        tenant, seq, name = frame
        with self._cv:
            while True:
                job = self.queue.take(tenant, seq)
                if job is not None:
                    if job.name != name:
                        # per-tenant submission order diverged across
                        # ranks: running this job in rank 0's slot
                        # would mismatch the mesh collectives — fail
                        # LOUDLY instead
                        err = RuntimeError(
                            f"cross-rank admission divergence: rank 0 "
                            f"announced ({tenant}, {seq}) = {name!r}, "
                            f"this rank holds {job.name!r} — tenant "
                            f"submission order must be "
                            f"rank-deterministic")
                        # already taken off the queue: _poison's drain
                        # won't see it — settle its counters here
                        # (the Condition's RLock tolerates the nested
                        # _poison acquisition)
                        self.jobs_failed += 1
                        self.jobs_done += 1
                        job.future._finish(error=err)
                        self._poison(err)
                        return None
                    return job
                if self._dead is not None:
                    return None
                # NOT an exit on _closing: rank 0 announced this job,
                # so by the lockstep contract the local submit is on
                # its way — leaving now would strand the future AND
                # desert rank 0 mid-collective. The drain sentinel
                # (frame is None) is the orderly exit; a violated
                # contract is bounded by close()'s join timeout (the
                # dispatcher is a daemon thread).
                self._cv.wait()

    def _run(self, job: _Job) -> None:
        ctx = self.ctx
        fut = job.future
        t0 = time.monotonic()
        fut.queue_wait_s = t0 - job.t_submit
        from ..api.context import PipelineError
        err: Optional[BaseException] = None
        # plan choices recorded during this job (decision ledger delta
        # across the run — the dispatcher serializes jobs, so the
        # delta is unambiguously this job's)
        led = getattr(ctx, "decisions", None)
        dec0 = (sum(led.kind_counts.values())
                if led is not None and led.enabled else None)

        def settle_decisions() -> None:
            # must run BEFORE fut._finish: result() unblocks the
            # client the instant the future's event is set, and a
            # client reading fut.plan_decisions right after result()
            # must not race the dispatcher's bookkeeping
            if dec0 is not None:
                fut.plan_decisions = (sum(led.kind_counts.values())
                                      - dec0)

        tr = getattr(ctx, "tracer", None)
        sp = None
        if tr is not None and tr.enabled:
            # the queue-wait bar (submit -> start, measured on the
            # monotonic clock the scheduler already uses) and the run
            # span; every dispatch/exchange/loop span the job's
            # pipeline emits nests under the run span and inherits the
            # job name through the tracer's current_job tag
            now = time.perf_counter()
            tr.emit_span("service", "queue_wait",
                         now - fut.queue_wait_s, now,
                         job=fut.name, tenant=job.tenant)
            sp = tr.begin("service", f"job:{fut.name}",
                          tenant=job.tenant, job=fut.name,
                          job_id=fut.job_id)
            tr.current_job = fut.name
        try:
            with ctx.pipeline(name=job.name) as gen:
                fut.generation = gen
                ctx.current_tenant = job.tenant
                faults.check(_F_SUBMIT, job=fut.job_id,
                             tenant=job.tenant)
                out = job.fn(ctx)
            fut.run_s = time.monotonic() - t0
            settle_decisions()
            fut._finish(result=out)
        except PipelineError as e:
            # scoped failure: the Context healed; only THIS job failed
            err = e
            fut.generation = e.generation
            fut.run_s = time.monotonic() - t0
            with self._cv:
                self.jobs_failed += 1
            settle_decisions()
            fut._finish(error=e)
        except BaseException as e:
            # unrecoverable abort (dead peer, failed heal): the
            # Context cannot serve anymore — fail everything queued,
            # loudly; supervised relaunch owns recovery from here
            err = e
            fut.run_s = time.monotonic() - t0
            with self._cv:
                self.jobs_failed += 1
            settle_decisions()
            fut._finish(error=e)
            self._poison(e)
        finally:
            ctx.current_tenant = None
            # accept-to-result: submit() call to future resolution,
            # queue wait included — the latency a CLIENT of this
            # tenant actually observed for the job
            self._note_latency(job.tenant,
                               time.monotonic() - job.t_submit)
            with self._cv:
                self.jobs_done += 1
                # drain-time estimate behind retry-after hints
                self._run_ewma_s = (fut.run_s if not self._run_ewma_s
                                    else 0.8 * self._run_ewma_s
                                    + 0.2 * fut.run_s)
            if sp is not None:
                tr.current_job = None
                tr.end(sp, generation=fut.generation,
                       ok=err is None,
                       error=(repr(err)[:200] if err is not None
                              else None))
        log = ctx.logger
        if log.enabled:
            log.line(event="job_done", job=fut.job_id, name=fut.name,
                     tenant=job.tenant, ok=err is None,
                     generation=fut.generation,
                     queue_wait_s=round(fut.queue_wait_s, 4),
                     run_s=round(fut.run_s, 4),
                     plan_decisions=(fut.plan_decisions
                                     if dec0 is not None else None),
                     error=(repr(err)[:200] if err is not None
                            else None))

    def _poison(self, cause: BaseException) -> None:
        with self._cv:
            self._dead = cause
            stranded = self.queue.drain()
            fences, self._fences = self._fences, []
            self.jobs_failed += len(stranded)
            self.jobs_done += len(stranded)
            self._cv.notify_all()
        for job in stranded:
            job.future._finish(error=RuntimeError(
                f"job never ran: scheduler died after an unrecoverable "
                f"abort: {cause!r}"))
        self._fail_fences(
            fences, f"fence never ran: scheduler died after an "
                    f"unrecoverable abort: {cause!r}")
        faults.note("recovery", what="service.scheduler_dead",
                    stranded=len(stranded), error=repr(cause)[:200])
