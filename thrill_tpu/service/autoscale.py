"""Autoscaling policy: decide WHEN the mesh should resize.

PR 16 made W a per-generation property and `Context.resize_processes`
(api/context.py) makes a multi-process W change one orchestrated move
— but nothing decided *when*. This module is that policy layer: a
deterministic, tick-counted state machine fed by the metrics the
service plane already exports (queue depth, ``jobs_rejected``, the
per-tenant serve-latency p99 behind ``overall_stats()`` and the
Prometheus endpoint).

Design rules, in priority order:

* **Deterministic core.** :meth:`Autoscaler.observe` consumes one
  metric sample and returns a target W or ``None`` — no wall clocks,
  no randomness, no I/O. Hysteresis is counted in TICKS (consecutive
  confirmation + cooldown), so tests pin the exact decision tick by
  injecting a metric sequence (tests/service/test_autoscale.py), and
  a multi-process deployment can run one Autoscaler per rank over the
  SAME injected sequence and reach the SAME decision — SPMD style,
  no coordinator needed.
* **Hysteresis both ways.** Scale-up needs ``confirm_ticks``
  consecutive hot samples past a high-watermark (queue depth, reject
  delta, or p99); scale-down needs ``idle_ticks`` consecutive idle
  samples (empty queue, nothing in flight, no rejects). Every
  decision starts a ``cooldown_ticks`` window in which no further
  decision fires — a resize costs a drain + relaunch, and a policy
  that flaps pays it twice for nothing.
* **Audited.** Every decision lands in the PR-11 ledger
  (``kind=autoscale``: inputs, predicted target, chosen move,
  rejected hold) and therefore in ``ctx.explain()``.
* **Crash-safe.** The ``svc.autoscale.decide`` fault site fires at
  tick entry, BEFORE the sample mutates any hysteresis state — an
  injected failure leaves streaks and cooldown exactly as they were,
  and the next tick retries from the same state
  (tests/common/test_faults.py proves nothing-mutated-then-retry).

The live side (``maybe_start``, ``THRILL_TPU_AUTOSCALE_S`` ticks on a
daemon thread) is single-process only: a thread on one rank calling a
collective move would desync a multi-process mesh. Multi-process
deployments drive the same policy deterministically from the job loop
(see tests/net/resize_proc_child.py and ARCHITECTURE.md "Elastic
mesh, phase 2").
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from ..common import faults

#: fired at decision-tick entry, before the sample advances any
#: hysteresis state — an injected failure is a skipped tick, nothing
#: mutated, clean retry on the next tick
F_DECIDE = faults.declare("svc.autoscale.decide")


def _env_i(name: str, default: int) -> int:
    try:
        v = os.environ.get(name)
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


def _env_f(name: str, default: float) -> float:
    try:
        v = os.environ.get(name)
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


class AutoscalePolicy:
    """The knobs (all overridable by env; see README "Environment").

    High-watermarks trigger scale-UP when any one is crossed:
    ``up_queue`` (queue depth), ``up_rejects`` (jobs_rejected delta
    per tick), ``up_p99_ms`` (serve p99; 0 disables the latency
    trigger). Scale-DOWN on sustained idle tenancy only. W is clamped
    to ``[min_w, max_w]`` and moves ONE step per decision — the
    cheapest move that changes the signal, and the one the
    orchestrated resize amortizes best."""

    def __init__(self,
                 min_w: Optional[int] = None,
                 max_w: Optional[int] = None,
                 up_queue: Optional[int] = None,
                 up_rejects: Optional[int] = None,
                 up_p99_ms: Optional[float] = None,
                 confirm_ticks: Optional[int] = None,
                 idle_ticks: Optional[int] = None,
                 cooldown_ticks: Optional[int] = None) -> None:
        self.min_w = max(1, min_w if min_w is not None
                         else _env_i("THRILL_TPU_AUTOSCALE_MIN_W", 1))
        self.max_w = max(self.min_w,
                         max_w if max_w is not None
                         else _env_i("THRILL_TPU_AUTOSCALE_MAX_W", 4))
        self.up_queue = up_queue if up_queue is not None \
            else _env_i("THRILL_TPU_AUTOSCALE_UP_QUEUE", 8)
        self.up_rejects = up_rejects if up_rejects is not None \
            else _env_i("THRILL_TPU_AUTOSCALE_UP_REJECTS", 1)
        self.up_p99_ms = up_p99_ms if up_p99_ms is not None \
            else _env_f("THRILL_TPU_AUTOSCALE_UP_P99_MS", 0.0)
        self.confirm_ticks = max(1, confirm_ticks
                                 if confirm_ticks is not None
                                 else _env_i(
                                     "THRILL_TPU_AUTOSCALE_CONFIRM", 2))
        self.idle_ticks = max(1, idle_ticks if idle_ticks is not None
                              else _env_i(
                                  "THRILL_TPU_AUTOSCALE_IDLE_TICKS", 5))
        self.cooldown_ticks = max(0, cooldown_ticks
                                  if cooldown_ticks is not None
                                  else _env_i(
                                      "THRILL_TPU_AUTOSCALE_COOLDOWN",
                                      3))


class Autoscaler:
    """One Context's scaling policy.

    Pure use (tests, multi-process SPMD driving)::

        a = Autoscaler(policy=AutoscalePolicy(confirm_ticks=2))
        target = a.observe({"queue_depth": 12, ...}, current_w=2)

    Live use (``maybe_start``): a daemon thread samples the
    scheduler/front-door counters every ``THRILL_TPU_AUTOSCALE_S``
    seconds and applies decisions through ``apply_fn`` (default:
    ``ctx.resize`` on a single-process mesh — a multi-process mesh
    must drive the policy from its own job loop, see module doc)."""

    def __init__(self, ctx=None,
                 policy: Optional[AutoscalePolicy] = None,
                 apply_fn: Optional[Callable[[int], None]] = None,
                 tick_s: Optional[float] = None) -> None:
        self.ctx = ctx
        self.policy = policy or AutoscalePolicy()
        self.apply_fn = apply_fn
        self.tick_s = tick_s if tick_s is not None \
            else _env_f("THRILL_TPU_AUTOSCALE_S", 0.0)
        # hysteresis state — mutated ONLY by observe(), after the
        # fault site in tick() had its chance to abort the tick
        self._tick = 0
        self._hot = 0
        self._idle = 0
        self._cooldown = 0
        self._last_rejected: Optional[int] = None
        # observability (overall_stats: autoscale_decisions)
        self.decisions_made = 0
        self.last_decision: Optional[dict] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- deterministic core ---------------------------------------------
    def observe(self, m: Dict[str, float], current_w: int
                ) -> Optional[int]:
        """Consume one metric sample; return the target W of a
        scaling decision, or None. ``m`` keys: ``queue_depth``,
        ``jobs_rejected`` (cumulative), ``jobs_in_flight``,
        ``serve_p99_ms``. Pure: ticks are the only clock."""
        p = self.policy
        self._tick += 1
        rejected = int(m.get("jobs_rejected", 0))
        if self._last_rejected is None:
            reject_delta = 0
        else:
            reject_delta = max(0, rejected - self._last_rejected)
        self._last_rejected = rejected
        depth = int(m.get("queue_depth", 0))
        inflight = int(m.get("jobs_in_flight", 0))
        p99 = float(m.get("serve_p99_ms", 0.0))
        hot = (depth > p.up_queue
               or reject_delta >= max(1, p.up_rejects)
               or (p.up_p99_ms > 0 and p99 > p.up_p99_ms))
        idle = depth == 0 and inflight == 0 and reject_delta == 0
        if hot:
            self._hot += 1
            self._idle = 0
        elif idle:
            self._idle += 1
            self._hot = 0
        else:
            self._hot = 0
            self._idle = 0
        if self._cooldown > 0:
            # streaks keep counting through the cooldown so a
            # sustained condition fires on the first eligible tick,
            # but no decision lands inside the window
            self._cooldown -= 1
            return None
        target: Optional[int] = None
        why = ""
        if self._hot >= p.confirm_ticks and current_w < p.max_w:
            target = current_w + 1
            why = (f"hot x{self._hot}: depth={depth} "
                   f"rejects+{reject_delta} p99={p99:.0f}ms")
        elif self._idle >= p.idle_ticks and current_w > p.min_w:
            target = current_w - 1
            why = f"idle x{self._idle}"
        if target is None:
            return None
        self._hot = 0
        self._idle = 0
        self._cooldown = p.cooldown_ticks
        self.decisions_made += 1
        self.last_decision = {
            "tick": self._tick, "from_w": current_w, "to_w": target,
            "queue_depth": depth, "rejects_delta": reject_delta,
            "p99_ms": p99, "reason": why}
        self._ledger(current_w, target, depth, reject_delta, p99, why)
        return target

    def _ledger(self, w: int, target: int, depth: int,
                reject_delta: int, p99: float, why: str) -> None:
        ctx = self.ctx
        led = getattr(ctx, "decisions", None) if ctx is not None \
            else None
        if led is None or not led.enabled:
            return
        led.record(
            "autoscale", "svc.autoscale.decide",
            f"resize:{w}->{target}", predicted=float(target),
            rejected=[(f"hold:{w}", None)], reason=why,
            tick=self._tick, queue_depth=depth,
            rejects_delta=reject_delta, p99_ms=round(p99, 1))
        log = getattr(ctx, "logger", None)
        if log is not None and log.enabled:
            log.line(event="autoscale_decision", from_w=w,
                     to_w=target, tick=self._tick, queue_depth=depth,
                     rejects_delta=reject_delta,
                     p99_ms=round(p99, 1))

    # -- live side ------------------------------------------------------
    def sample(self) -> Dict[str, float]:
        """One live metric sample off the Context's service plane —
        the same counters ``overall_stats()``/Prometheus export, read
        directly so a tick never pays a full stats merge."""
        ctx = self.ctx
        m: Dict[str, float] = {"queue_depth": 0, "jobs_rejected": 0,
                               "jobs_in_flight": 0, "serve_p99_ms": 0.0}
        if ctx is None:
            return m
        svc = ctx.service
        if svc is not None:
            with svc._cv:
                m["queue_depth"] = svc.queue.depth
                m["jobs_rejected"] = svc.jobs_rejected
                m["jobs_in_flight"] = max(
                    0, svc.jobs_submitted - svc.jobs_done)
            q = svc.latency_quantiles().get("serve_p99_ms", {})
            if q:
                m["serve_p99_ms"] = max(q.values())
        fd = getattr(ctx, "front_door", None)
        if fd is not None:
            m["jobs_rejected"] += fd.jobs_rejected
        return m

    def tick(self) -> Optional[int]:
        """One live decision tick: fault gate, sample, observe. The
        fault site fires BEFORE the sample is consumed, so an injected
        failure mutates nothing — streaks, cooldown and the reject
        baseline all retry identical on the next tick."""
        faults.check(F_DECIDE, tick=self._tick + 1)
        ctx = self.ctx
        w = ctx.num_workers if ctx is not None else 0
        return self.observe(self.sample(), w)

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            ctx = self.ctx
            if ctx is None or ctx._closed:
                return
            try:
                target = self.tick()
            except faults.InjectedFault:
                continue              # skipped tick; state untouched
            if target is None:
                continue
            try:
                if self.apply_fn is not None:
                    self.apply_fn(target)
                else:
                    ctx.resize(target)
            except Exception as e:
                faults.note("recovery", what="svc.autoscale.apply",
                            target=target, error=repr(e)[:200])

    def start(self) -> "Autoscaler":
        if self._thread is None and self.tick_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name="thrill-autoscale",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout)

    def stats(self) -> dict:
        return {"autoscale_decisions": self.decisions_made,
                "autoscale_ticks": self._tick}


def maybe_start(ctx) -> Optional[Autoscaler]:
    """Start the live policy thread when ``THRILL_TPU_AUTOSCALE_S``
    names a tick period (mirrors front_door/metrics maybe_start).
    Single-process only — per-rank threads deciding on their own
    timing would desync a multi-process mesh's collective resize;
    those deployments drive
    the policy from the job loop instead (module doc)."""
    period = _env_f("THRILL_TPU_AUTOSCALE_S", 0.0)
    if period <= 0:
        return None
    if ctx.mesh_exec.num_processes > 1 or ctx.net.num_workers > 1:
        import sys
        print("thrill_tpu.service: THRILL_TPU_AUTOSCALE_S ignored on "
              "a multi-process mesh — drive the Autoscaler from the "
              "job loop so every rank reaches the same decision "
              "(ARCHITECTURE.md \"Elastic mesh, phase 2\")",
              file=sys.stderr)
        return None
    return Autoscaler(ctx, tick_s=period).start()
