"""Client library for the front door (service/front_door.py).

A thin, dependency-free peer of the server's admission protocol:
dial, mutual-HMAC authenticate when a secret is set, ``hello`` /
``welcome``, then submit named pipelines and consume streamed result
chunks. The client is built for an OVERLOADED or RESTARTING server —
the regimes the front door is designed around:

* connect runs under the shared bounded full-jitter
  :class:`~thrill_tpu.common.retry.RetryPolicy` (a restarting server
  is a transient, not an error);
* a typed ``reject`` raises :class:`Rejected` carrying the server's
  ``kind`` and ``retry_after_s`` hint — :meth:`FrontDoorClient
  .submit_retry` honors the hint: it sleeps the MAX of the server's
  hint and its own full-jitter delay, so a fleet of shed clients
  neither hammers the server early nor thundering-herds on the same
  beat (the jitter half) nor returns before the queue could have
  drained (the hint half);
* chunks are consumable AS THEY ARRIVE (:meth:`RemoteJob.chunks`) —
  an items-mode pipeline streams results while the job is still
  running server-side.

Threading: one reader thread per client demultiplexes frames to
:class:`RemoteJob` objects by id; ``submit`` only writes. All public
methods are thread-safe.
"""

from __future__ import annotations

import itertools
import random
import socket
import threading
import time
from collections import deque
from typing import Any, Iterator, Optional

from ..common.retry import default_policy
from ..net import wire
from ..net.tcp import TcpConnection, _exchange_auth_flag
from .front_door import PROTO_MAX, PROTO_MIN, PROTO_VERSION


class Rejected(RuntimeError):
    """The server shed this submit — typed, with a retry-after hint."""

    def __init__(self, kind: str, retry_after_s: float,
                 msg: str) -> None:
        super().__init__(f"rejected ({kind}, retry after "
                         f"{retry_after_s:.3f}s): {msg}")
        self.kind = kind
        self.retry_after_s = float(retry_after_s)


class VersionMismatch(RuntimeError):
    """The server speaks no protocol version in this client's range —
    PERMANENT by construction (a plain RuntimeError subclass, so the
    connect retry policy surfaces it immediately instead of redialing
    a server that will refuse forever). Carries the server's
    supported range parsed from the typed reject."""

    def __init__(self, msg: str) -> None:
        super().__init__(f"protocol version mismatch: {msg} "
                         f"(this client speaks "
                         f"[{PROTO_MIN},{PROTO_MAX}])")


class RemoteJobError(RuntimeError):
    """The job was accepted but failed server-side (``error`` frame:
    pipeline exception, missed deadline, torn result stream)."""

    def __init__(self, kind: str, msg: str) -> None:
        super().__init__(f"remote job failed ({kind}): {msg}")
        self.kind = kind


class RemoteJob:
    """One in-flight submit: resolves to chunks then a terminal frame.

    ``result(timeout)`` blocks for the whole result; ``chunks()``
    yields decoded chunks as they arrive (items mode: one result item
    per chunk, usable mid-job). Terminal failures raise their typed
    exception from either method."""

    def __init__(self, jid: int) -> None:
        self.id = jid
        self.mode = "blob"
        # v2 servers stamp the accept with the mesh generation the
        # job runs under (None from v1 servers) — the elastic-fence
        # regression test pins that a resize can never invalidate it
        self.generation: Optional[int] = None
        self._chunks: deque = deque()
        self._raw: list = []
        self._cv = threading.Condition()
        self._accepted = False
        self._done = False
        self._exc: Optional[BaseException] = None

    # -- reader side ----------------------------------------------------
    def _on_accept(self, meta: dict) -> None:
        with self._cv:
            self._accepted = True
            self.mode = str(meta.get("mode", "blob"))
            gen = meta.get("gen")
            self.generation = int(gen) if gen is not None else None
            self._cv.notify_all()

    def _on_chunk(self, payload: bytes) -> None:
        with self._cv:
            self._raw.append(payload)
            self._chunks.append(payload)
            self._cv.notify_all()

    def _finish(self, exc: Optional[BaseException]) -> None:
        with self._cv:
            if self._done:
                return
            self._done = True
            self._exc = exc
            self._cv.notify_all()

    # -- consumer side --------------------------------------------------
    def wait_accepted(self, timeout: Optional[float] = None) -> None:
        """Block until the admission verdict: returns on ``accept``,
        raises :class:`Rejected` on ``reject`` (TimeoutError if the
        server answered neither in time)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while not self._accepted and not self._done:
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"no admission verdict for job {self.id}")
                self._cv.wait(left if left is not None else 1.0)
            if self._done and self._exc is not None:
                raise self._exc

    def chunks(self, timeout: Optional[float] = None
               ) -> Iterator[Any]:
        """Yield decoded chunks as they arrive (items mode: each is
        one result item). ``timeout`` bounds the wait per chunk."""
        seen = 0
        while True:
            with self._cv:
                deadline = None if timeout is None else \
                    time.monotonic() + timeout
                while not self._chunks and not self._done:
                    left = None if deadline is None else \
                        deadline - time.monotonic()
                    if left is not None and left <= 0:
                        raise TimeoutError(
                            f"no chunk for job {self.id} after "
                            f"{timeout}s (server slow or stream "
                            f"wedged)")
                    self._cv.wait(left if left is not None else 1.0)
                if self._chunks:
                    payload = self._chunks.popleft()
                elif self._exc is not None:
                    raise self._exc
                else:
                    return
            # decode OUTSIDE the lock; blob-mode chunks are raw
            # slices of one encoded payload — yield bytes, result()
            # does the join+decode
            yield wire.loads(payload) if self.mode == "items" \
                else payload
            seen += 1

    def result(self, timeout: Optional[float] = None) -> Any:
        """The whole result: blob mode decodes the reassembled
        payload; items mode returns the list of items."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        with self._cv:
            while not self._done:
                left = None if deadline is None else \
                    deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"job {self.id} not done after {timeout}s")
                self._cv.wait(left if left is not None else 1.0)
            if self._exc is not None:
                raise self._exc
            raw = list(self._raw)
        if self.mode == "items":
            return [wire.loads(p) for p in raw]
        return wire.loads(b"".join(raw))


class FrontDoorClient:
    """One authenticated connection to a front door.

    ``secret`` defaults to ``THRILL_TPU_SECRET`` (the same env the
    server and every mesh link read); pass ``secret=None`` explicitly
    AND unset the env for an unauthenticated dev connection."""

    def __init__(self, host: str, port: int, tenant: str = "default",
                 secret: Optional[bytes] = None,
                 connect_timeout_s: float = 10.0) -> None:
        self.tenant = str(tenant)
        self.secret = secret if secret is not None \
            else wire.secret_from_env()
        self._ids = itertools.count(1)
        self._jobs: dict = {}
        self._lock = threading.Lock()
        self._closed = False
        self._conn_lost: Optional[BaseException] = None
        self._bye_reason: Optional[str] = None
        # filled by the handshake: the negotiated protocol version and
        # the server's advertised [min, max]
        self.proto = PROTO_VERSION
        self.server_range = (PROTO_VERSION, PROTO_VERSION)

        def dial() -> TcpConnection:
            sock = socket.create_connection(
                (host, int(port)), timeout=connect_timeout_s)
            sock.settimeout(None)
            conn = TcpConnection(sock)
            try:
                _exchange_auth_flag(conn, self.secret is not None)
                if self.secret is not None:
                    conn.authenticate(self.secret, "client")
                # v2 hello: offer the whole range. A v1 server reads
                # the field with int() and rejects the list with its
                # "proto mismatch" bye — falling back to a plain int
                # there is not needed in-tree (server and client ship
                # together); cross-version cover is the v2 server
                # accepting v1 clients' single-int hellos.
                conn.send(("hello", {"tenant": self.tenant,
                                     "proto": [PROTO_MIN, PROTO_MAX]}))
                frame = conn.recv_deadline(connect_timeout_s)
            except BaseException:
                conn.close()
                raise
            if (isinstance(frame, (tuple, list)) and len(frame) >= 5
                    and frame[0] == "reject"
                    and frame[2] == "version_mismatch"):
                conn.close()
                raise VersionMismatch(str(frame[4]))
            if not (isinstance(frame, (tuple, list)) and frame
                    and frame[0] == "welcome"):
                conn.close()
                raise ConnectionError(
                    f"front door refused handshake: {frame!r}")
            # negotiated version + server range (v1 servers send just
            # {"proto": 1}: range degrades to [proto, proto])
            meta = frame[1] if len(frame) > 1 \
                and isinstance(frame[1], dict) else {}
            self.proto = int(meta.get("proto", PROTO_VERSION))
            rng = meta.get("range") or [self.proto, self.proto]
            self.server_range = (int(rng[0]), int(rng[1]))
            return conn

        # a restarting / briefly-saturated server is a transient:
        # bounded full-jitter redial, permanent errors (AuthError)
        # surface immediately
        self.conn = default_policy().run(
            dial, what=f"front_door.connect:{host}:{port}")
        self._reader = threading.Thread(
            target=self._read_loop, name="thrill-fd-client-read",
            daemon=True)
        self._reader.start()

    # -- reader ---------------------------------------------------------
    def _read_loop(self) -> None:
        while not self._closed:
            try:
                frame = self.conn.recv()
            except (ConnectionError, OSError, EOFError,
                    ValueError) as e:
                # ValueError: close() tore the socket under this
                # blocked recv (fileno() == -1 inside the poller)
                self._fail_all(ConnectionError(
                    f"front door connection lost: {e!r}"
                    if self._bye_reason is None else
                    f"front door said bye: {self._bye_reason}"))
                return
            try:
                self._dispatch(frame)
            except _ServerBye:
                self._fail_all(ConnectionError(
                    f"front door said bye: {self._bye_reason}"))
                return

    def _dispatch(self, frame) -> None:
        if not isinstance(frame, (tuple, list)) or not frame:
            return
        op = frame[0]
        if op == "bye":
            self._bye_reason = frame[1] if len(frame) > 1 else ""
            raise _ServerBye()
        if len(frame) < 2:
            return
        job = self._jobs.get(frame[1])
        if job is None:
            return
        if op == "accept":
            job._on_accept(frame[2] if len(frame) > 2 else {})
        elif op == "reject":
            _, _, kind, retry_after_s, msg = frame
            job._finish(Rejected(kind, retry_after_s, msg))
        elif op == "chunk":
            job._on_chunk(frame[3])
        elif op == "done":
            job._finish(None)
        elif op == "error":
            job._finish(RemoteJobError(frame[2], frame[3]))

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            self._conn_lost = exc
            jobs = list(self._jobs.values())
            self._jobs.clear()
        for job in jobs:
            job._finish(exc)

    # -- submit side ----------------------------------------------------
    def submit(self, pipeline: str, args: Any = None,
               deadline_s: Optional[float] = None,
               weight: Optional[float] = None) -> RemoteJob:
        """Submit a named pipeline; returns immediately with a
        :class:`RemoteJob` (the admission verdict arrives async —
        ``wait_accepted()`` / ``result()`` surface a ``reject`` as
        :class:`Rejected`)."""
        if self._closed:
            raise ConnectionError("client is closed")
        jid = next(self._ids)
        job = RemoteJob(jid)
        with self._lock:
            # fail FAST after a lost connection: a submit racing the
            # reader's _fail_all would otherwise never resolve
            if self._conn_lost is not None:
                raise ConnectionError(
                    f"no connection: {self._conn_lost}") \
                    from self._conn_lost
            self._jobs[jid] = job
        req = {"id": jid, "pipeline": str(pipeline), "args": args}
        if deadline_s is not None:
            req["deadline_s"] = float(deadline_s)
        if weight is not None:
            req["weight"] = float(weight)
        try:
            self.conn.send(("submit", req))
        except (ConnectionError, OSError) as e:
            with self._lock:
                self._jobs.pop(jid, None)
            raise ConnectionError(f"submit failed: {e!r}") from e
        return job

    def submit_retry(self, pipeline: str, args: Any = None,
                     deadline_s: Optional[float] = None,
                     attempts: int = 6,
                     verdict_timeout_s: float = 30.0,
                     seed: Optional[int] = None) -> RemoteJob:
        """Submit, retrying TYPED sheds until accepted or the attempt
        budget runs out. Sleeps ``max(server retry-after hint,
        full-jitter backoff)`` between tries — the hint keeps retries
        out of a window the server PROMISED is full, the jitter keeps
        a fleet of shed clients from herding back on one beat. The
        last :class:`Rejected` re-raises unchanged."""
        policy = default_policy()
        rng = random.Random(seed)
        last: Optional[Rejected] = None
        for attempt in range(max(1, int(attempts))):
            job = self.submit(pipeline, args, deadline_s=deadline_s)
            try:
                job.wait_accepted(verdict_timeout_s)
                return job
            except Rejected as e:
                last = e
                time.sleep(max(e.retry_after_s,
                               policy.delay(attempt, rng)))
        assert last is not None
        raise last

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self.conn.send(("bye",))
        except (ConnectionError, OSError):
            pass
        try:
            self.conn.close()
        except Exception:
            pass
        self._fail_all(ConnectionError("client closed"))

    def __enter__(self) -> "FrontDoorClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _ServerBye(Exception):
    """Internal: the server ended the session."""
