"""S3 object storage backend for the vfs layer.

Reference: thrill/vfs/s3_file.cpp (~1,100 LoC over vendored libs3):
object listing for Glob, ranged GETs for offset reads, streamed PUTs
for writes. Here the transport is boto3, probed lazily — the backend
self-gates with an actionable error when the SDK is absent (this image
ships no boto3 and has no network), and everything above the vfs seam
(ReadLines/ReadBinary/WriteLines byte-range splitting) is
scheme-agnostic, so enabling S3 is purely additive.

Paths: s3://bucket/key or s3://bucket/prefix* (suffix glob).
"""

from __future__ import annotations

import io
from typing import IO, List, Tuple

from ..common import faults

# scheme-level injection inside the ranged GET itself; the generic
# vfs.read/vfs.open_read sites in file_io.py wrap this stream and
# recover by reopening the range at the tracked offset
_F_S3_READ = faults.declare("vfs.s3.read")


def _boto3():
    try:
        import boto3  # type: ignore
        return boto3
    except ImportError as e:
        raise NotImplementedError(
            "vfs scheme 's3' needs the boto3 SDK, which is not "
            "installed in this image (no network to fetch it); install "
            "boto3 and configure AWS credentials, or point "
            "THRILL_TPU_OBJECT_STORE_ENDPOINT at an S3-compatible "
            "endpoint to use the SDK-free REST transport"
        ) from e


def _rest():
    """The SDK-free transport (vfs/object_store) — used when boto3 is
    absent but ``THRILL_TPU_OBJECT_STORE_ENDPOINT`` names an
    S3-compatible endpoint; None when boto3 is importable (the SDK
    stays authoritative: it owns credentials, region signing, and the
    non-path-style addressing modes)."""
    try:
        import boto3  # type: ignore # noqa: F401
        return None
    except ImportError:
        from . import object_store
        return object_store if object_store.endpoint() else None


def parse_s3_path(path: str) -> Tuple[str, str]:
    assert path.startswith("s3://"), path
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"s3 path has no bucket: {path!r}")
    return bucket, key


def s3_glob(path_or_glob: str) -> List[Tuple[str, int]]:
    """List (s3://bucket/key, size) matching the path or '*'-suffix
    prefix glob, sorted by key (reference: S3 list in vfs::Glob)."""
    rest = _rest()
    if rest is not None:
        out = [(f"s3://{url[len(rest.endpoint()) + 1:]}", sz)
               for url, sz in rest.http_glob(
                   rest.s3_rest_url(path_or_glob))]
        out.sort()
        return out
    boto3 = _boto3()
    bucket, key = parse_s3_path(path_or_glob)
    client = boto3.client("s3")
    if "*" in key:
        star = key.index("*")
        if "*" in key[star + 1:]:
            raise ValueError("s3 glob supports a single trailing '*'")
        prefix, suffix = key[:star], key[star + 1:]
    else:
        prefix, suffix = key, ""
    out: List[Tuple[str, int]] = []
    paginator = client.get_paginator("list_objects_v2")
    for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
        for obj in page.get("Contents", ()):
            k = obj["Key"]
            if suffix and not k.endswith(suffix):
                continue
            out.append((f"s3://{bucket}/{k}", int(obj["Size"])))
    out.sort()
    return out


class _S3ReadStream(io.RawIOBase):
    """Ranged sequential reads over one object (reference: ranged GET,
    s3_file.cpp)."""

    def __init__(self, bucket: str, key: str, offset: int = 0) -> None:
        client = _boto3().client("s3")
        kwargs = {"Bucket": bucket, "Key": key}
        if offset:
            kwargs["Range"] = f"bytes={offset}-"
        self._body = client.get_object(**kwargs)["Body"]

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        faults.check(_F_S3_READ)
        return self._body.read(None if n is None or n < 0 else n)

    def readinto(self, b) -> int:
        faults.check(_F_S3_READ)
        data = self._body.read(len(b))
        b[:len(data)] = data
        return len(data)

    def close(self) -> None:
        try:
            self._body.close()
        finally:
            super().close()


#: streamed PUT switchover: below this, one put_object; above, the
#: multipart protocol (reference: the streamed PUT path of
#: thrill/vfs/s3_file.cpp). S3's minimum non-final part size is 5 MiB.
MULTIPART_PART_SIZE = 8 << 20


class _S3WriteStream(io.RawIOBase):
    """Streamed object writer with an abort-on-error contract.

    Small outputs (< one part) land as a single ``put_object``. Larger
    ones stream through the multipart protocol — create_multipart_
    upload, one ``upload_part`` per part_size slice (a single huge
    write() is sliced too, so parts never exceed part_size and RAM
    stays bounded), ``complete_multipart_upload`` on a CLEAN close —
    so output size is bounded by S3's 10,000-part limit, not this
    process's RAM. ``abort()`` drops a half-written upload (no
    orphaned parts, no partial object committed); after an abort,
    writes are silently discarded and close() commits NOTHING —
    :func:`s3_open_write`'s wrapper aborts on any exception inside a
    ``with`` block so a failed producer never publishes a truncated
    object as complete."""

    def __init__(self, bucket: str, key: str,
                 part_size: int = MULTIPART_PART_SIZE) -> None:
        self._bucket = bucket
        self._key = key
        self._part_size = max(int(part_size), 5 << 20)
        self._pending = bytearray()
        self._client = _boto3().client("s3")
        self._upload_id = None
        self._parts: List[dict] = []
        self._aborted = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        if self._aborted:
            # discard, don't raise: the buffered wrapper's close()
            # flushes after an abort, and raising here would mask the
            # exception that CAUSED the abort
            return len(b)
        self._pending += b
        while len(self._pending) >= self._part_size:
            chunk = bytes(self._pending[:self._part_size])
            del self._pending[:self._part_size]
            self._upload_part(chunk)
        return len(b)

    def _upload_part(self, data: bytes) -> None:
        if self._upload_id is None:
            resp = self._client.create_multipart_upload(
                Bucket=self._bucket, Key=self._key)
            self._upload_id = resp["UploadId"]
        num = len(self._parts) + 1
        resp = self._client.upload_part(
            Bucket=self._bucket, Key=self._key,
            UploadId=self._upload_id, PartNumber=num, Body=data)
        self._parts.append({"ETag": resp["ETag"], "PartNumber": num})
        # S3 caps uploads at 10,000 parts. Past the half-way mark,
        # double the part size every 500 parts (the reference likewise
        # grows part size with the object): 500 parts at each of
        # 16 MiB..5 GiB covers S3's 5 TiB object maximum before part
        # 10,000, while the in-RAM pending buffer (one part) grows
        # only as the object actually does. 5 GiB is S3's per-part max.
        if num >= 5000 and num % 500 == 0 and self._part_size < (5 << 30):
            self._part_size = min(self._part_size * 2, 5 << 30)

    def abort(self) -> None:
        """Drop the output: abort any open multipart upload (no
        orphaned parts) and ensure close() will NOT commit anything."""
        self._aborted = True
        self._pending = bytearray()
        if self._upload_id is not None:
            try:
                self._client.abort_multipart_upload(
                    Bucket=self._bucket, Key=self._key,
                    UploadId=self._upload_id)
            finally:
                self._upload_id = None

    def close(self) -> None:
        if self.closed:
            return
        try:
            if self._aborted:
                return                   # nothing is committed
            if self._upload_id is None:
                # never crossed a part boundary: single PUT
                self._client.put_object(Bucket=self._bucket,
                                        Key=self._key,
                                        Body=bytes(self._pending))
            else:
                try:
                    if self._pending:    # the (short) final part
                        self._upload_part(bytes(self._pending))
                        self._pending = bytearray()
                    self._client.complete_multipart_upload(
                        Bucket=self._bucket, Key=self._key,
                        UploadId=self._upload_id,
                        MultipartUpload={"Parts": self._parts})
                    self._upload_id = None
                except Exception:
                    self.abort()
                    raise
        finally:
            super().close()


def s3_open_read(path: str, offset: int = 0) -> IO[bytes]:
    rest = _rest()
    if rest is not None:
        return rest.http_open_read(rest.s3_rest_url(path), offset)
    bucket, key = parse_s3_path(path)
    return io.BufferedReader(_S3ReadStream(bucket, key, offset))


class _AbortingWriter(io.BufferedWriter):
    """BufferedWriter whose ``with`` block ABORTS the upload when the
    body raises: an exception must never publish a truncated object as
    a complete output (the raw stream then discards the close-flush and
    commits nothing)."""

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            try:
                self.raw.abort()
            except Exception:
                pass                      # surface the ORIGINAL error
        return super().__exit__(exc_type, exc, tb)


def s3_open_write(path: str) -> IO[bytes]:
    rest = _rest()
    if rest is not None:
        return rest.http_open_write(rest.s3_rest_url(path))
    bucket, key = parse_s3_path(path)
    return _AbortingWriter(_S3WriteStream(bucket, key))
