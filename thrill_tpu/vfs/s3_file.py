"""S3 object storage backend for the vfs layer.

Reference: thrill/vfs/s3_file.cpp (~1,100 LoC over vendored libs3):
object listing for Glob, ranged GETs for offset reads, streamed PUTs
for writes. Here the transport is boto3, probed lazily — the backend
self-gates with an actionable error when the SDK is absent (this image
ships no boto3 and has no network), and everything above the vfs seam
(ReadLines/ReadBinary/WriteLines byte-range splitting) is
scheme-agnostic, so enabling S3 is purely additive.

Paths: s3://bucket/key or s3://bucket/prefix* (suffix glob).
"""

from __future__ import annotations

import io
from typing import IO, List, Tuple


def _boto3():
    try:
        import boto3  # type: ignore
        return boto3
    except ImportError as e:
        raise NotImplementedError(
            "vfs scheme 's3' needs the boto3 SDK, which is not "
            "installed in this image (no network to fetch it); install "
            "boto3 and configure AWS credentials to enable s3:// paths"
        ) from e


def parse_s3_path(path: str) -> Tuple[str, str]:
    assert path.startswith("s3://"), path
    rest = path[len("s3://"):]
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"s3 path has no bucket: {path!r}")
    return bucket, key


def s3_glob(path_or_glob: str) -> List[Tuple[str, int]]:
    """List (s3://bucket/key, size) matching the path or '*'-suffix
    prefix glob, sorted by key (reference: S3 list in vfs::Glob)."""
    boto3 = _boto3()
    bucket, key = parse_s3_path(path_or_glob)
    client = boto3.client("s3")
    if "*" in key:
        star = key.index("*")
        if "*" in key[star + 1:]:
            raise ValueError("s3 glob supports a single trailing '*'")
        prefix, suffix = key[:star], key[star + 1:]
    else:
        prefix, suffix = key, ""
    out: List[Tuple[str, int]] = []
    paginator = client.get_paginator("list_objects_v2")
    for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
        for obj in page.get("Contents", ()):
            k = obj["Key"]
            if suffix and not k.endswith(suffix):
                continue
            out.append((f"s3://{bucket}/{k}", int(obj["Size"])))
    out.sort()
    return out


class _S3ReadStream(io.RawIOBase):
    """Ranged sequential reads over one object (reference: ranged GET,
    s3_file.cpp)."""

    def __init__(self, bucket: str, key: str, offset: int = 0) -> None:
        client = _boto3().client("s3")
        kwargs = {"Bucket": bucket, "Key": key}
        if offset:
            kwargs["Range"] = f"bytes={offset}-"
        self._body = client.get_object(**kwargs)["Body"]

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        return self._body.read(None if n is None or n < 0 else n)

    def readinto(self, b) -> int:
        data = self._body.read(len(b))
        b[:len(data)] = data
        return len(data)

    def close(self) -> None:
        try:
            self._body.close()
        finally:
            super().close()


class _S3WriteStream(io.RawIOBase):
    """Buffered whole-object PUT on close (small coordination files and
    per-worker output chunks; multipart upload is a follow-up)."""

    def __init__(self, bucket: str, key: str) -> None:
        self._bucket = bucket
        self._key = key
        self._buf = io.BytesIO()

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        return self._buf.write(b)

    def close(self) -> None:
        if not self.closed:
            client = _boto3().client("s3")
            client.put_object(Bucket=self._bucket, Key=self._key,
                              Body=self._buf.getvalue())
        super().close()


def s3_open_read(path: str, offset: int = 0) -> IO[bytes]:
    bucket, key = parse_s3_path(path)
    return io.BufferedReader(_S3ReadStream(bucket, key, offset))


def s3_open_write(path: str) -> IO[bytes]:
    bucket, key = parse_s3_path(path)
    return io.BufferedWriter(_S3WriteStream(bucket, key))
