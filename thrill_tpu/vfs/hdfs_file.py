"""HDFS backend for the vfs layer via pyarrow.fs.HadoopFileSystem.

Reference: thrill/vfs/hdfs3_file.{hpp,cpp} (libhdfs3-backed listing +
streams). pyarrow ships in this image; the actual connection needs
libhdfs + a Hadoop config at runtime, so the backend self-gates with an
actionable error when those are absent (the same lazy-probe pattern as
vfs/s3_file.py).

Paths: hdfs://host:port/path or hdfs:///path (default namenode from
HADOOP_CONF_DIR). A single trailing '*' glob is supported.
"""

from __future__ import annotations

from typing import IO, List, Tuple
from urllib.parse import urlparse

from ..common import faults

# fires at ranged-open: file_io.py's retrying reader reopens at the
# tracked offset on a transient failure here
_F_HDFS_OPEN = faults.declare("vfs.hdfs.open")


def _connect(host: str, port: int):
    try:
        from pyarrow import fs as pafs
        return pafs.HadoopFileSystem(host=host or "default",
                                     port=port or 0)
    except Exception as e:
        raise NotImplementedError(
            "vfs scheme 'hdfs' needs pyarrow's HadoopFileSystem with "
            "libhdfs + a Hadoop runtime configured (HADOOP_HOME/"
            "CLASSPATH); neither is present in this image"
        ) from e


def parse_hdfs_path(path: str) -> Tuple[str, int, str]:
    u = urlparse(path)
    assert u.scheme == "hdfs", path
    return u.hostname or "", u.port or 0, u.path


def hdfs_glob(path_or_glob: str) -> List[Tuple[str, int]]:
    """List (hdfs://.../key, size) for the path, directory or
    '*'-suffix glob (directories list their files, like file://)."""
    host, port, p = parse_hdfs_path(path_or_glob)
    client = _connect(host, port)        # gates when pyarrow is absent
    from pyarrow import fs as pafs

    authority = f"hdfs://{host}:{port}" if host else "hdfs://"

    def _list(selector_base, prefix, suffix, recursive):
        sel = pafs.FileSelector(selector_base, recursive=recursive,
                                allow_not_found=True)
        out = []
        for info in client.get_file_info(sel):
            if info.type != pafs.FileType.File:
                continue
            path_n = "/" + info.path.lstrip("/")
            if prefix and not path_n.startswith(prefix):
                continue
            if suffix and not path_n.endswith(suffix):
                continue
            out.append((f"{authority}{path_n}", int(info.size)))
        out.sort()
        return out

    if "*" in p:
        star = p.index("*")
        if "*" in p[star + 1:]:
            raise ValueError("hdfs glob supports a single trailing '*'")
        prefix, suffix = p[:star], p[star + 1:]
        base = prefix.rsplit("/", 1)[0] or "/"
        return _list(base, prefix, suffix, recursive=True)
    info = client.get_file_info([p])[0]
    if info.type == pafs.FileType.Directory:
        return _list(p, "", "", recursive=False)
    if info.type != pafs.FileType.File:
        return []
    return [(path_or_glob, int(info.size))]


def hdfs_open_read(path: str, offset: int = 0) -> IO[bytes]:
    faults.check(_F_HDFS_OPEN, path=path, offset=offset)
    host, port, p = parse_hdfs_path(path)
    client = _connect(host, port)
    if offset:
        # random-access open + seek: ReadLines' byte-range split opens
        # every chunk at its offset, and skipping sequentially through
        # an HDFS stream would re-read the whole prefix per worker
        f = None
        try:
            f = client.open_input_file(p)
            f.seek(offset)
            return f
        except (NotImplementedError, AttributeError):
            if f is not None:          # opened but seek unsupported:
                try:                   # close before the fallback or
                    f.close()          # ReadLines leaks one handle per
                except Exception:      # byte-range chunk per worker
                    pass
            f = client.open_input_stream(p)
            remaining = offset
            while remaining > 0:
                chunk = f.read(min(remaining, 1 << 20))
                if not chunk:
                    break
                remaining -= len(chunk)
            return f
    return client.open_input_stream(p)


def hdfs_open_write(path: str) -> IO[bytes]:
    host, port, p = parse_hdfs_path(path)
    client = _connect(host, port)
    return client.open_output_stream(p)
