"""Object-store transport on stdlib HTTP: ranged GETs, streamed PUTs.

Reference: thrill/vfs/s3_file.cpp — the reference rides vendored libs3,
but the wire protocol underneath is plain HTTP: ListObjectsV2 for Glob,
``Range: bytes=N-`` GETs for offset reads, PUT (single-shot or the
multipart protocol) for writes. This module speaks that protocol with
``http.client`` only, so the out-of-core tier runs against genuinely
slow remote storage with zero new dependencies:

* ``http://`` / ``https://`` paths dispatch here behind the vfs seam
  (file_io.Glob/_open_at/OpenWriteStream) — ReadLines/ReadBinary,
  checkpoint shards, flight dumps and the plan store are all
  scheme-agnostic above that seam, so they work unmodified;
* ``s3://`` paths fall back here when boto3 is absent AND
  ``THRILL_TPU_OBJECT_STORE_ENDPOINT`` names an S3-compatible endpoint
  (path-style REST: ``<endpoint>/<bucket>/<key>``).

Retry story: this layer classifies, the shared policy retries. A
response status rides on the raised exception as ``http_status`` and
``common/retry.py`` classifies 5xx/408/429 transient (404 and 403 map
to FileNotFoundError/PermissionError, which are already permanent);
connection resets and timeouts are OSErrors and retry as today. Reads
recover by REOPENING the range at the tracked offset — the
RetryingReader wrapping this stream already does exactly that — and a
server that ignores ``Range`` fails loudly (a silent restart from byte
0 would corrupt the resumed stream).

Accounting: every GET bumps ``remote_gets`` and records its
time-to-first-byte (``get_p50_ms()``); every PUT/part bumps
``remote_puts`` (common/iostats.py) — the perf sentinel pins these
exactly, so a silent fallback to whole-file reads fails a counter
diff.
"""

from __future__ import annotations

import collections
import http.client
import io
import os
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from typing import IO, List, Optional, Tuple

from ..common import faults
from ..common.iostats import IO as _IOSTATS
from ..common.retry import default_policy

# scheme-level injection sites. raise mode exercises the recovery
# ladder (retry the request / reopen the range at the tracked offset);
# ``delay=`` mode fires once per HTTP REQUEST, which is exactly the
# latency regime of a real object store (each GET costs ~RTT, however
# many stream reads it feeds)
_F_READ = faults.declare("vfs.http.read")
_F_WRITE = faults.declare("vfs.http.write")
_F_LIST = faults.declare("vfs.http.list")


def endpoint() -> Optional[str]:
    """S3-REST endpoint used for ``s3://`` paths when boto3 is absent:
    ``THRILL_TPU_OBJECT_STORE_ENDPOINT`` (or ``AWS_ENDPOINT_URL``),
    e.g. ``http://127.0.0.1:9000``."""
    ep = os.environ.get("THRILL_TPU_OBJECT_STORE_ENDPOINT") \
        or os.environ.get("AWS_ENDPOINT_URL")
    return ep.rstrip("/") if ep else None


def part_size() -> int:
    """THRILL_TPU_OBJECT_STORE_PART: streamed-PUT part threshold. At or
    above this many buffered bytes a write switches to the multipart
    protocol, so flush RAM is bounded by one part, not the object
    (multi-GB checkpoint shards must not double RAM at flush time).
    Default 8 MiB; floor 64 KiB so tests can exercise multipart
    cheaply (real S3 requires 5 MiB non-final parts — set accordingly
    against real endpoints)."""
    try:
        v = int(os.environ.get("THRILL_TPU_OBJECT_STORE_PART", "")
                or (8 << 20))
    except ValueError:
        v = 8 << 20
    return max(1 << 16, v)


def timeout_s() -> float:
    """THRILL_TPU_OBJECT_STORE_TIMEOUT: per-request socket timeout."""
    try:
        return float(os.environ.get("THRILL_TPU_OBJECT_STORE_TIMEOUT",
                                    "") or 60.0)
    except ValueError:
        return 60.0


class HTTPStatusError(OSError):
    """Non-2xx response. ``http_status`` drives retry classification
    (common/retry.py: 5xx/408/429 transient, other 4xx permanent)."""

    def __init__(self, status: int, url: str, detail: str = "") -> None:
        super().__init__(f"HTTP {status} for {url}"
                         + (f": {detail}" if detail else ""))
        self.http_status = status
        self.url = url


# -- GET latency ledger (time-to-first-byte per request) ----------------
_LAT_LOCK = threading.Lock()
_LAT_MS: collections.deque = collections.deque(maxlen=4096)


def _record_get(ms: float) -> None:
    with _LAT_LOCK:
        _LAT_MS.append(ms)


def get_p50_ms() -> float:
    """Median GET time-to-first-byte over the recent window (bench's
    ``em_remote_get_p50_ms``); 0.0 when no GETs ran."""
    with _LAT_LOCK:
        lat = sorted(_LAT_MS)
    return lat[len(lat) // 2] if lat else 0.0


def latency_reset() -> None:
    with _LAT_LOCK:
        _LAT_MS.clear()


# -- low-level request plumbing -----------------------------------------
def _parse(url: str) -> Tuple[bool, str, int, str]:
    """(https?, host, port, path-with-query) for one absolute URL."""
    u = urllib.parse.urlsplit(url)
    if u.scheme not in ("http", "https"):
        raise ValueError(f"not an http(s) url: {url!r}")
    if not u.hostname:
        raise ValueError(f"http url has no host: {url!r}")
    secure = u.scheme == "https"
    port = u.port or (443 if secure else 80)
    path = u.path or "/"
    if u.query:
        path += "?" + u.query
    return secure, u.hostname, port, path


def _connect(secure: bool, host: str, port: int) -> http.client.HTTPConnection:
    cls = http.client.HTTPSConnection if secure \
        else http.client.HTTPConnection
    return cls(host, port, timeout=timeout_s())


def _raise_for_status(status: int, url: str, body: bytes = b"") -> None:
    """Map a failure status onto the retry taxonomy: 404/403 become the
    (permanent) errno exceptions the rest of the stack already knows;
    everything else carries ``http_status`` for classify()."""
    if status == 404:
        e: OSError = FileNotFoundError(f"object not found: {url}")
    elif status == 403:
        e = PermissionError(f"access denied: {url}")
    else:
        e = HTTPStatusError(status, url, body[:200].decode(
            "utf-8", "replace"))
    e.http_status = status  # type: ignore[attr-defined]
    raise e


def _request(method: str, url: str, body: bytes = b"",
             headers: Optional[dict] = None,
             ok: Tuple[int, ...] = (200,)) -> Tuple[int, dict, bytes]:
    """One buffered request/response round trip on a fresh connection
    (fresh per request: trivially thread-safe, and against a local
    mock/MinIO the connect cost is noise next to the injected
    latency). Returns (status, lowercased headers, body)."""
    secure, host, port, path = _parse(url)
    conn = _connect(secure, host, port)
    try:
        hdrs = {"Content-Length": str(len(body))}
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body=body or None, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        status = resp.status
        rhdrs = {k.lower(): v for k, v in resp.getheaders()}
    except http.client.HTTPException as e:
        # not an OSError by inheritance, but it IS a broken transport
        # conversation — re-raise as one so the retry policy sees it
        raise ConnectionResetError(f"{method} {url}: {e!r}") from e
    finally:
        conn.close()
    if status not in ok:
        _raise_for_status(status, url, data)
    return status, rhdrs, data


# -- ranged reads -------------------------------------------------------
class _HttpReadStream(io.RawIOBase):
    """Streamed ranged GET over one object. One HTTP request per
    stream; the wrapping RetryingReader recovers from mid-stream
    failures by reopening at the tracked offset (a fresh ranged GET)."""

    def __init__(self, url: str, offset: int = 0) -> None:
        faults.check(_F_READ, url=url, offset=offset)
        self._url = url
        secure, host, port, path = _parse(url)
        self._conn = _connect(secure, host, port)
        t0 = time.perf_counter()
        try:
            headers = {}
            if offset:
                headers["Range"] = f"bytes={offset}-"
            self._conn.request("GET", path, headers=headers)
            resp = self._conn.getresponse()
        except http.client.HTTPException as e:
            self._conn.close()
            raise ConnectionResetError(f"GET {url}: {e!r}") from e
        except BaseException:
            self._conn.close()
            raise
        _IOSTATS.add(remote_gets=1)
        _record_get((time.perf_counter() - t0) * 1e3)
        if offset and resp.status == 416:
            # ranged open at/past EOF: a local file opens fine there
            # and reads b"" — mirror that (S3 416s unsatisfiable
            # ranges; callers like the delimited-range scanners probe
            # exactly-at-EOF offsets legitimately)
            resp.read()
            self._conn.close()
            self._resp = None
            return
        if resp.status not in (200, 206):
            body = resp.read()
            self._conn.close()
            _raise_for_status(resp.status, url, body)
        if offset and resp.status != 206:
            # the server ignored Range: reading from byte 0 here would
            # silently corrupt a resumed stream — fail LOUDLY instead
            # (status 200 classifies permanent, so no retry storm)
            self._conn.close()
            raise HTTPStatusError(
                200, url, f"server ignored Range: bytes={offset}-")
        self._resp = resp

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        if self._resp is None:          # opened at/past EOF
            return b""
        try:
            return self._resp.read(None if n is None or n < 0 else n)
        except http.client.HTTPException as e:
            raise ConnectionResetError(
                f"read {self._url}: {e!r}") from e

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[:len(data)] = data
        return len(data)

    def close(self) -> None:
        try:
            self._conn.close()
        finally:
            super().close()


def http_open_read(url: str, offset: int = 0) -> IO[bytes]:
    return io.BufferedReader(_HttpReadStream(url, offset))


# -- listing (ListObjectsV2) --------------------------------------------
def _split_bucket(url: str) -> Tuple[str, str, str]:
    """``http://host:port/bucket/key...`` → (base, bucket, key)."""
    u = urllib.parse.urlsplit(url)
    base = f"{u.scheme}://{u.netloc}"
    rest = u.path.lstrip("/")
    bucket, _, key = rest.partition("/")
    if not bucket:
        raise ValueError(f"object url has no bucket: {url!r}")
    return base, bucket, key


def _xml_text(elem, tag: str, default: str = "") -> str:
    # S3 XML arrives both with and without the aws namespace; match on
    # the local tag name
    for child in elem.iter():
        if child.tag == tag or child.tag.endswith("}" + tag):
            return child.text or default
    return default


def list_objects(base: str, bucket: str,
                 prefix: str) -> List[Tuple[str, int]]:
    """ListObjectsV2 with pagination: (key, size) for every object
    under ``prefix``, sorted by key."""
    out: List[Tuple[str, int]] = []
    token = None
    policy = default_policy()
    while True:
        q = {"list-type": "2", "prefix": prefix}
        if token:
            q["continuation-token"] = token
        url = f"{base}/{bucket}?{urllib.parse.urlencode(q)}"

        def op(url=url):
            faults.check(_F_LIST, url=url)
            return _request("GET", url)
        _, _, body = policy.run(op, what="vfs.http.list")
        root = ET.fromstring(body)
        for elem in root.iter():
            if elem.tag == "Contents" or elem.tag.endswith("}Contents"):
                k = _xml_text(elem, "Key")
                if k:
                    out.append((k, int(_xml_text(elem, "Size", "0"))))
        if _xml_text(root, "IsTruncated") != "true":
            break
        token = _xml_text(root, "NextContinuationToken")
        if not token:
            break
    out.sort()
    return out


def http_glob(path_or_glob: str) -> List[Tuple[str, int]]:
    """(url, size) matching the path or a single-trailing-'*' prefix
    glob — the s3_glob contract over the REST listing."""
    base, bucket, key = _split_bucket(path_or_glob)
    if "*" in key:
        star = key.index("*")
        if "*" in key[star + 1:]:
            raise ValueError(
                "object-store glob supports a single trailing '*'")
        prefix, suffix = key[:star], key[star + 1:]
    else:
        prefix, suffix = key, ""
    out = [(f"{base}/{bucket}/{k}", sz)
           for k, sz in list_objects(base, bucket, prefix)
           if not suffix or k.endswith(suffix)]
    out.sort()
    return out


# -- streamed writes ----------------------------------------------------
class _ObjectWriteStream(io.RawIOBase):
    """Streamed PUT with bounded RAM and an abort-on-error contract —
    the REST twin of s3_file._S3WriteStream. Below one part: a single
    PUT on close. At or past the part threshold: the S3 multipart
    protocol (initiate / per-part PUT / complete), each request retried
    under the shared policy (a part PUT is idempotent — same part
    number, same bytes). ``abort()`` drops a half-written upload so a
    failed producer never publishes a truncated object."""

    def __init__(self, url: str,
                 part: Optional[int] = None) -> None:
        self._url = url
        self._part_size = part_size() if part is None else max(1 << 16,
                                                               int(part))
        self._pending = bytearray()
        self._upload_id: Optional[str] = None
        self._parts: List[Tuple[int, str]] = []   # (number, etag)
        self._aborted = False
        self._policy = default_policy()

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        if self._aborted:
            return len(b)           # see _S3WriteStream.write
        self._pending += b
        while len(self._pending) >= self._part_size:
            chunk = bytes(self._pending[:self._part_size])
            del self._pending[:self._part_size]
            self._upload_part(chunk)
        return len(b)

    def _put(self, url: str, body: bytes, what: str,
             headers: Optional[dict] = None) -> dict:
        def op():
            faults.check(_F_WRITE, url=url, nbytes=len(body))
            _, hdrs, _ = self._request_put(url, body, headers)
            return hdrs
        hdrs = self._policy.run(op, what=what)
        _IOSTATS.add(remote_puts=1)
        return hdrs

    @staticmethod
    def _request_put(url: str, body: bytes,
                     headers: Optional[dict]) -> Tuple[int, dict, bytes]:
        return _request("PUT", url, body=body, headers=headers,
                        ok=(200, 201, 204))

    def _upload_part(self, data: bytes) -> None:
        if self._upload_id is None:
            def op():
                faults.check(_F_WRITE, url=self._url, op="initiate")
                _, _, body = _request("POST", self._url + "?uploads")
                return _xml_text(ET.fromstring(body), "UploadId")
            self._upload_id = self._policy.run(
                op, what="vfs.http.write")
            if not self._upload_id:
                raise HTTPStatusError(
                    500, self._url, "initiate returned no UploadId")
        num = len(self._parts) + 1
        q = urllib.parse.urlencode(
            {"partNumber": str(num), "uploadId": self._upload_id})
        hdrs = self._put(f"{self._url}?{q}", data, "vfs.http.write")
        self._parts.append((num, hdrs.get("etag", f'"{num}"')))
        # the same part-size growth rule as the boto3 path: past 5000
        # parts, double every 500 so the 10,000-part cap covers the
        # 5 TiB object maximum while pending RAM grows with the object
        if num >= 5000 and num % 500 == 0 \
                and self._part_size < (5 << 30):
            self._part_size = min(self._part_size * 2, 5 << 30)

    def abort(self) -> None:
        self._aborted = True
        self._pending = bytearray()
        if self._upload_id is not None:
            uid, self._upload_id = self._upload_id, None
            try:
                q = urllib.parse.urlencode({"uploadId": uid})
                _request("DELETE", f"{self._url}?{q}", ok=(200, 204))
            except Exception:
                pass                 # best effort; never mask the cause

    def close(self) -> None:
        if self.closed:
            return
        try:
            if self._aborted:
                return
            if self._upload_id is None:
                self._put(self._url, bytes(self._pending),
                          "vfs.http.write")
                self._pending = bytearray()
            else:
                try:
                    if self._pending:
                        self._upload_part(bytes(self._pending))
                        self._pending = bytearray()
                    parts = "".join(
                        f"<Part><PartNumber>{n}</PartNumber>"
                        f"<ETag>{etag}</ETag></Part>"
                        for n, etag in self._parts)
                    body = (f"<CompleteMultipartUpload>{parts}"
                            f"</CompleteMultipartUpload>"
                            ).encode("utf-8")
                    q = urllib.parse.urlencode(
                        {"uploadId": self._upload_id})

                    def op():
                        faults.check(_F_WRITE, url=self._url,
                                     op="complete")
                        _request("POST", f"{self._url}?{q}", body=body)
                    self._policy.run(op, what="vfs.http.write")
                    self._upload_id = None
                except Exception:
                    self.abort()
                    raise
        finally:
            super().close()


class _AbortingWriter(io.BufferedWriter):
    """``with`` block aborts the upload when the body raises — an
    exception must never publish a truncated object as complete."""

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            try:
                self.raw.abort()
            except Exception:
                pass
        return super().__exit__(exc_type, exc, tb)


def http_open_write(url: str) -> IO[bytes]:
    return _AbortingWriter(_ObjectWriteStream(url))


# -- s3:// fallback plumbing --------------------------------------------
def s3_rest_url(path: str) -> str:
    """s3://bucket/key → <endpoint>/bucket/key (path-style REST).
    Raises NotImplementedError when no endpoint is configured — the
    boto3 gate's message stays authoritative in that case."""
    ep = endpoint()
    if ep is None:
        raise NotImplementedError(
            "s3:// REST fallback needs THRILL_TPU_OBJECT_STORE_ENDPOINT")
    assert path.startswith("s3://"), path
    return f"{ep}/{path[len('s3://'):]}"
