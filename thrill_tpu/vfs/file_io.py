"""Virtual file system: glob, ranged reads, compressed streams.

Reference: thrill/vfs/file_io.hpp:79-164 — scheme dispatch (file://,
s3://), ``Glob`` returning a FileList with exclusive size prefix sums
(used to split byte ranges over workers), Read/WriteStream interfaces,
gzip/bzip2/xz filters (sys_file.cpp pipes through external binaries; we
use Python's codecs). S3/HDFS backends are gated stubs until their SDKs
are available in the image.
"""

from __future__ import annotations

import bz2
import dataclasses
import glob as _glob
import gzip
import lzma
import os
from typing import IO, List, Optional

COMPRESSED_SUFFIXES = (".gz", ".bz2", ".xz")


@dataclasses.dataclass
class FileInfo:
    path: str
    size: int              # uncompressed size unknown for compressed
    size_ex_psum: int      # exclusive prefix sum of sizes
    is_compressed: bool


@dataclasses.dataclass
class FileList:
    files: List[FileInfo]

    @property
    def total_size(self) -> int:
        if not self.files:
            return 0
        last = self.files[-1]
        return last.size_ex_psum + last.size

    @property
    def contains_compressed(self) -> bool:
        return any(f.is_compressed for f in self.files)

    def __len__(self) -> int:
        return len(self.files)

    def __getitem__(self, i: int) -> FileInfo:
        return self.files[i]


def _scheme(path: str) -> str:
    if "://" in path:
        return path.split("://", 1)[0]
    return "file"


def Glob(path_or_glob: str) -> FileList:
    """Expand a path/glob into a FileList with size prefix sums.

    Reference: vfs::Glob, file_io.hpp:105; FileList::size_ex_psum :79-99.
    """
    scheme = _scheme(path_or_glob)
    if scheme == "s3":
        from . import s3_file
        files: List[FileInfo] = []
        psum = 0
        for p, sz in s3_file.s3_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme == "hdfs":
        from . import hdfs_file
        files = []
        psum = 0
        for p, sz in hdfs_file.hdfs_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme != "file":
        raise NotImplementedError(
            f"vfs scheme '{scheme}' is not implemented; file://, s3:// "
            f"and hdfs:// are")
    pat = path_or_glob[len("file://"):] if path_or_glob.startswith("file://") \
        else path_or_glob
    if os.path.isdir(pat):
        paths = sorted(
            os.path.join(pat, p) for p in os.listdir(pat)
            if os.path.isfile(os.path.join(pat, p)))
    else:
        paths = sorted(p for p in _glob.glob(pat) if os.path.isfile(p))
    files: List[FileInfo] = []
    psum = 0
    for p in paths:
        sz = os.path.getsize(p)
        files.append(FileInfo(p, sz, psum, p.endswith(COMPRESSED_SUFFIXES)))
        psum += sz
    return FileList(files)


def OpenReadStream(path: str, offset: int = 0) -> IO[bytes]:
    """Open for reading, transparently decompressing by suffix.

    Compressed files do not support nonzero offsets (whole-file
    granularity, like the reference's ReadLines on compressed input).
    """
    if _scheme(path) == "s3":
        if path.endswith(COMPRESSED_SUFFIXES):
            raise ValueError("compressed s3 objects are read whole-file")
        from . import s3_file
        return s3_file.s3_open_read(path, offset)
    if _scheme(path) == "hdfs":
        from . import hdfs_file
        return hdfs_file.hdfs_open_read(path, offset)
    f = _open_filtered(path, "rb")
    if offset:
        if path.endswith(COMPRESSED_SUFFIXES):
            raise ValueError("cannot seek into compressed file")
        f.seek(offset)
    return f


def OpenWriteStream(path: str) -> IO[bytes]:
    if _scheme(path) == "s3":
        from . import s3_file
        return s3_file.s3_open_write(path)
    if _scheme(path) == "hdfs":
        from . import hdfs_file
        return hdfs_file.hdfs_open_write(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return _open_filtered(path, "wb")


def _open_filtered(path: str, mode: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    if path.endswith(".bz2"):
        return bz2.open(path, mode)
    if path.endswith(".xz"):
        return lzma.open(path, mode)
    return open(path, mode)
