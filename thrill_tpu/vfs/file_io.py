"""Virtual file system: glob, ranged reads, compressed streams.

Reference: thrill/vfs/file_io.hpp:79-164 — scheme dispatch (file://,
s3://), ``Glob`` returning a FileList with exclusive size prefix sums
(used to split byte ranges over workers), Read/WriteStream interfaces,
gzip/bzip2/xz filters (sys_file.cpp pipes through external binaries; we
use Python's codecs). S3/HDFS backends are gated stubs until their SDKs
are available in the image.
"""

from __future__ import annotations

import bz2
import collections
import dataclasses
import glob as _glob
import gzip
import lzma
import os
import threading
import time
from typing import IO, List, Optional

from ..common import faults
from ..common.iostats import IO as _IOSTATS
from ..common.retry import default_policy

COMPRESSED_SUFFIXES = (".gz", ".bz2", ".xz")

# ranged reads are idempotent — every stream here can be reopened at
# an absolute offset (posix seek, s3 ranged GET, hdfs seek; compressed
# streams re-skip decompressed bytes) — so transient storage faults
# retry with a fresh handle under the shared backoff policy instead of
# failing a whole pipeline for one flaky read
_F_OPEN = faults.declare("vfs.open_read")
_F_READ = faults.declare("vfs.read")
# latency-injection twin of vfs.read: arm with :delay=<dur> to make
# THIS process's reads deterministically slow (straggler/IO-wait tests)
_F_READ_DELAY = faults.declare("vfs.read.delay")
# background-readahead failure (fires on the reader THREAD): the
# prefetching layer degrades to demand reads at the exact consumed
# position — slower, never wrong data. Bytes already queued before the
# failure were produced by the same retrying reader and stay valid.
_F_PREFETCH = faults.declare("vfs.prefetch")


def prefetch_depth() -> int:
    """THRILL_TPU_PREFETCH: how many blocks the background readahead
    keeps in flight ahead of the consumer. 0 restores today's demand
    reads byte-identically (OpenReadStream returns the plain retrying
    reader); the THRILL_TPU_OVERLAP=0 master switch also disables it."""
    from ..common.config import overlap_enabled
    if not overlap_enabled():
        return 0
    try:
        return max(0, int(os.environ.get("THRILL_TPU_PREFETCH",
                                         "4") or 4))
    except ValueError:
        return 4


def _prefetch_block_bytes() -> int:
    """THRILL_TPU_PREFETCH_BLOCK: readahead block size (default 1 MiB
    — big enough that queue handoff is noise, small enough that depth
    blocks bound RAM)."""
    try:
        return max(1 << 12, int(os.environ.get(
            "THRILL_TPU_PREFETCH_BLOCK", "") or (1 << 20)))
    except ValueError:
        return 1 << 20


@dataclasses.dataclass
class FileInfo:
    path: str
    size: int              # uncompressed size unknown for compressed
    size_ex_psum: int      # exclusive prefix sum of sizes
    is_compressed: bool


@dataclasses.dataclass
class FileList:
    files: List[FileInfo]

    @property
    def total_size(self) -> int:
        if not self.files:
            return 0
        last = self.files[-1]
        return last.size_ex_psum + last.size

    @property
    def contains_compressed(self) -> bool:
        return any(f.is_compressed for f in self.files)

    def __len__(self) -> int:
        return len(self.files)

    def __getitem__(self, i: int) -> FileInfo:
        return self.files[i]


def _scheme(path: str) -> str:
    if "://" in path:
        return path.split("://", 1)[0]
    return "file"


def Glob(path_or_glob: str) -> FileList:
    """Expand a path/glob into a FileList with size prefix sums.

    Reference: vfs::Glob, file_io.hpp:105; FileList::size_ex_psum :79-99.
    """
    scheme = _scheme(path_or_glob)
    if scheme == "s3":
        from . import s3_file
        files: List[FileInfo] = []
        psum = 0
        for p, sz in s3_file.s3_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme == "hdfs":
        from . import hdfs_file
        files = []
        psum = 0
        for p, sz in hdfs_file.hdfs_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme in ("http", "https"):
        from . import object_store
        files = []
        psum = 0
        for p, sz in object_store.http_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme != "file":
        raise NotImplementedError(
            f"vfs scheme '{scheme}' is not implemented; file://, s3://, "
            f"hdfs:// and http(s):// are")
    pat = path_or_glob[len("file://"):] if path_or_glob.startswith("file://") \
        else path_or_glob
    if os.path.isdir(pat):
        paths = sorted(
            os.path.join(pat, p) for p in os.listdir(pat)
            if os.path.isfile(os.path.join(pat, p)))
    else:
        paths = sorted(p for p in _glob.glob(pat) if os.path.isfile(p))
    files: List[FileInfo] = []
    psum = 0
    for p in paths:
        sz = os.path.getsize(p)
        files.append(FileInfo(p, sz, psum, p.endswith(COMPRESSED_SUFFIXES)))
        psum += sz
    return FileList(files)


def _open_at(path: str, offset: int) -> IO[bytes]:
    """One stream positioned at ``offset``, any scheme (the reopenable
    primitive the retrying reader is built on)."""
    faults.check(_F_OPEN, path=path, offset=offset)
    scheme = _scheme(path)
    if scheme == "s3":
        if path.endswith(COMPRESSED_SUFFIXES):
            raise ValueError("compressed s3 objects are read whole-file")
        from . import s3_file
        return s3_file.s3_open_read(path, offset)
    if scheme == "hdfs":
        from . import hdfs_file
        return hdfs_file.hdfs_open_read(path, offset)
    if scheme in ("http", "https"):
        if path.endswith(COMPRESSED_SUFFIXES):
            raise ValueError(
                "compressed http objects are read whole-file")
        from . import object_store
        return object_store.http_open_read(path, offset)
    f = _open_filtered(path, "rb")
    if offset:
        if path.endswith(COMPRESSED_SUFFIXES):
            # whole-file granularity on disk, but the RETRY reopen may
            # legitimately land mid-stream: skip decompressed bytes
            skipped = 0
            while skipped < offset:
                b = f.read(min(offset - skipped, 1 << 20))
                if not b:
                    break
                skipped += len(b)
        else:
            f.seek(offset)
    return f


class RetryingReader:
    """Self-healing read stream: tracks the absolute (decompressed)
    position and, when a read or open fails transiently, reopens the
    source at that position and resumes — the vfs-level recovery the
    reference cannot express (its ReadStream dies with the job,
    vfs/file_io.hpp:140).

    A thin proxy, not an io subclass. Every CONSUMING read
    (``read``/``readinto``/``readline``/``readlines``/``read1``/
    iteration) and ``seek`` are implemented here so ``_pos`` stays
    exact — a delegated consuming read would advance the stream behind
    the tracker and make a post-fault reopen replay bytes.
    Non-consuming attributes delegate to the wrapped stream so
    existing callers (ReadLines' delimiter probing does seek+read on
    posix files) see unchanged behavior."""

    def __init__(self, path: str, offset: int = 0) -> None:
        self._path = path
        self._pos = offset
        self._closed = False
        # one policy per reader, not per read: the env knobs are fixed
        # for a stream's lifetime, and ReadLines drives this per line
        self._policy = default_policy()
        self._f = self._policy.run(
            lambda: _open_at(path, offset), what="vfs.open_read")

    def _consume(self, read_fn) -> bytes:
        """THE retry-and-reopen invariant, in one place: run one
        consuming read under the policy (injection gate, reopen at the
        tracked offset after any failure, advance ``_pos`` by what was
        actually returned). Every consuming method routes here so the
        byte-replay protection cannot silently diverge between them."""
        if self._closed:
            raise ValueError("I/O operation on closed file")

        def op():
            faults.check(_F_READ, path=self._path, pos=self._pos)
            # latency injection (``vfs.read.delay:delay=50ms``): a
            # deterministic slow disk for straggler/IO-wait tests —
            # armed WITHOUT delay= it raises inside the same retry
            # scope as vfs.read (nothing consumed yet)
            faults.check(_F_READ_DELAY, path=self._path, pos=self._pos)
            if self._f is None:       # previous attempt lost the handle
                self._f = _open_at(self._path, self._pos)
            try:
                return read_fn(self._f)
            except Exception:
                # the handle is suspect after ANY failure: drop it so a
                # retry resumes from a fresh stream at self._pos
                self._drop()
                raise
        data = self._policy.run(op, what="vfs.read")
        self._pos += len(data)
        return data

    def read(self, n: int = -1) -> bytes:
        # read-to-EOF is spelled read() for pyarrow streams
        # (read(-1) trips their size check)
        if n is None or n < 0:
            return self._consume(lambda f: f.read())
        return self._consume(lambda f: f.read(n))

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[:len(data)] = data
        return len(data)

    def readline(self, n: int = -1) -> bytes:
        return self._consume(lambda f: f.readline(n))

    def readlines(self, hint: int = -1) -> list:
        out = []
        total = 0
        while True:
            line = self.readline()
            if not line:
                return out
            out.append(line)
            total += len(line)
            if 0 < hint <= total:     # io semantics: hint<=0 = no cap
                return out

    def read1(self, n: int = -1) -> bytes:
        return self.read(n if n is not None and n >= 0 else 1 << 16)

    def __iter__(self) -> "RetryingReader":
        return self

    def __next__(self) -> bytes:
        line = self.readline()
        if not line:
            raise StopIteration
        return line

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        if self._closed:
            raise ValueError("I/O operation on closed file")
        if whence == os.SEEK_CUR:
            pos, whence = self._pos + pos, os.SEEK_SET
        if whence == os.SEEK_SET:
            if pos == self._pos:
                return pos                  # no-op probe, keep handle
            if self._f is not None and self._f.seekable():
                self._pos = self._f.seek(pos)
            else:
                # no live handle, or a ranged-transport stream (http)
                # that cannot seek: reposition the tracker and drop —
                # the next read opens a fresh stream at the target
                # (for http, one ranged GET)
                self._drop()
                self._pos = pos
            return self._pos
        # size-relative (SEEK_END) needs a real handle
        if self._f is None:
            self._f = _open_at(self._path, self._pos)
        out = self._f.seek(pos, whence)
        self._pos = out
        return out

    def tell(self) -> int:
        return self._pos

    def _drop(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except Exception:
                pass

    def close(self) -> None:
        self._closed = True
        self._drop()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "RetryingReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __getattr__(self, name):
        # private names never delegate (and must not recurse through
        # __getattr__ during __init__/unpickling)
        if name.startswith("_"):
            raise AttributeError(name)
        # no handle (closed, or dropped after a fault): AttributeError,
        # not ValueError — hasattr/getattr-with-default probes on a
        # closed reader must behave like on any other object, and a
        # mere attribute probe must never reopen the stream
        f = self.__dict__.get("_f")
        if f is None:
            raise AttributeError(name)
        return getattr(f, name)


class _FillState:
    """One readahead generation: the queue, its lock, and the thread
    that owns them. A reader seek/teardown abandons the whole
    generation atomically — a fill thread stuck in a hung read past
    the join timeout still references only ITS state and can never
    deliver stale bytes into a successor's queue."""

    __slots__ = ("chunks", "cv", "stop", "err", "thread")

    def __init__(self) -> None:
        self.chunks: collections.deque = collections.deque()
        self.cv = threading.Condition()
        self.stop = False
        self.err: Optional[BaseException] = None
        self.thread: Optional[threading.Thread] = None


class PrefetchingReader:
    """Bounded background readahead over a :class:`RetryingReader`.

    A dedicated reader thread streams fixed-size blocks into an
    N-deep queue (``THRILL_TPU_PREFETCH``) so sequential consumers —
    ReadLines byte ranges, ReadBinary record ranges, checkpoint shard
    files — overlap disk/object-store latency with their own decode
    work, the vfs analog of foxxll's async block prefetch (reference:
    thrill/data/block_pool.hpp:177 MaxMergeDegreePrefetch). Contract:

    * bytes delivered are IDENTICAL to demand reads — the thread runs
      the same retrying reader, in order, from the same offset;
    * a background failure (``vfs.prefetch`` site) DEGRADES to demand
      reads at the exact consumed position — never wrong data;
    * ``seek`` outside the buffered window restarts the readahead at
      the target (the delimiter-probe pattern pays two restarts per
      range, then streams).

    Consumption accounting feeds the overlap ledger
    (common/iostats.py): a refill served from the queue is a
    ``prefetch_hit``; blocking on the reader thread is a miss plus
    ``io_wait_s``.
    """

    def __init__(self, path: str, offset: int = 0,
                 depth: Optional[int] = None,
                 tracer=None, readahead_to: Optional[int] = None) -> None:
        self._path = path
        self._pos = offset          # absolute offset of _buf[0]
        self._closed = False
        self._depth = prefetch_depth() if depth is None else depth
        self._block = _prefetch_block_bytes()
        # absolute readahead horizon: the fill thread never reads past
        # it (bounded-range callers know their end, and over-reading
        # depth*block bytes per range would be real wasted I/O on an
        # object store). Bytes BEYOND the horizon are still readable —
        # the reader continues on demand reads, silently (a horizon is
        # a hint, not EOF: ReadLines legitimately extends past its
        # range to finish the last item).
        self._limit = readahead_to
        self._buf = bytearray()     # dequeued, not yet returned
        self._demand: Optional[RetryingReader] = None
        self._tracer = tracer
        self._parent = (tracer.current_id()
                        if tracer is not None and tracer.enabled
                        else None)
        self._hits = 0
        self._misses = 0
        self._wait_s = 0.0
        # the fill thread starts LAZILY on the first consuming read:
        # the delimiter-probe pattern (open, seek, read) would
        # otherwise waste a block read per seek before streaming.
        # Each (re)start gets its OWN _FillState generation: a thread
        # that outlives the teardown join timeout (hung storage) still
        # holds only ITS state object and can never interleave stale
        # blocks into a restarted reader's queue.
        self._st: Optional[_FillState] = None
        self._eof = False

    # -- background fill ------------------------------------------------
    def _start_thread(self, offset: int) -> None:
        st = _FillState()
        self._st = st
        self._eof = False
        st.thread = threading.Thread(target=self._fill,
                                     args=(st, offset), daemon=True,
                                     name="thrill-tpu-prefetch")
        st.thread.start()

    def _fill(self, st: "_FillState", offset: int) -> None:
        inner = None
        tr = self._tracer
        span = (tr.span("io", "prefetch_reader", parent=self._parent,
                        path=self._path)
                if tr is not None and tr.enabled else None)
        try:
            if span is not None:
                span.__enter__()
            inner = RetryingReader(self._path, offset)
            fill_pos = offset
            while True:
                with st.cv:
                    while len(st.chunks) >= self._depth \
                            and not st.stop:
                        st.cv.wait(0.1)
                    if st.stop:
                        return
                take = self._block
                if self._limit is not None:
                    take = min(take, self._limit - fill_pos)
                    if take <= 0:
                        with st.cv:
                            if not st.stop:
                                # horizon reached, NOT EOF: the
                                # consumer continues on demand reads
                                st.chunks.append(None)
                                st.cv.notify_all()
                        return
                if faults.REGISTRY.active():
                    faults.check(_F_PREFETCH, path=self._path)
                t0 = time.perf_counter()
                data = inner.read(take)
                _IOSTATS.add(io_busy_s=time.perf_counter() - t0)
                fill_pos += len(data)
                with st.cv:
                    if st.stop:
                        return
                    st.chunks.append(data)      # b"" = EOF marker
                    st.cv.notify_all()
                if not data:
                    return
        except BaseException as e:
            with st.cv:
                st.err = e
                st.cv.notify_all()
        finally:
            if inner is not None:
                inner.close()
            if span is not None:
                span.__exit__(None, None, None)

    def _teardown_thread(self) -> None:
        st = self._st
        if st is None:
            return
        with st.cv:
            st.stop = True
            st.cv.notify_all()
        # a thread wedged in a hung read past the join timeout is
        # abandoned WITH its state generation — it can only ever touch
        # that orphaned deque, never a successor's
        st.thread.join(timeout=30)
        self._st = None

    def _degrade(self, err: BaseException) -> None:
        """Background read failed: continue on demand reads from the
        first unread byte. Queued bytes stay valid (produced in order
        by the same reader before the failure)."""
        self._teardown_thread()
        faults.note("recovery", what="vfs.prefetch_degraded",
                    path=self._path, error=repr(err)[:200])
        self._demand = RetryingReader(self._path,
                                      self._pos + len(self._buf))

    def _next_chunk(self) -> bytes:
        """One more block for ``_buf`` (b"" at EOF), from the queue,
        the demand fallback, or — after a background failure — the
        degraded reader."""
        if self._demand is not None:
            return self._demand.read(self._block)
        if self._eof:
            return b""
        if self._st is None:
            self._start_thread(self._pos + len(self._buf))
        st = self._st
        waited = False
        with st.cv:
            if not st.chunks:
                err = st.err
                if err is None and st.thread.is_alive():
                    t0 = time.perf_counter()
                    while not st.chunks and st.err is None \
                            and st.thread.is_alive():
                        st.cv.wait(0.1)
                    dt = time.perf_counter() - t0
                    self._wait_s += dt
                    _IOSTATS.add(io_wait_s=dt, prefetch_misses=1)
                    self._misses += 1
                    waited = True
                err = st.err
                if not st.chunks:
                    if err is None:       # thread died silently
                        err = RuntimeError("prefetch thread exited "
                                           "without data or EOF")
                    st.err = None
            if st.chunks:
                data = st.chunks.popleft()
                st.cv.notify_all()
                if data is None:
                    # readahead horizon: continue on demand reads,
                    # silently (no recovery event — nothing failed)
                    horizon = True
                else:
                    if not data:
                        self._eof = True
                    elif not waited:
                        self._hits += 1
                        _IOSTATS.add(prefetch_hits=1)
                    return bytes(data)
            else:
                horizon = False
        if horizon:
            self._teardown_thread()
            self._demand = RetryingReader(self._path,
                                          self._pos + len(self._buf))
            return self._demand.read(self._block)
        self._degrade(err)
        return self._demand.read(self._block)

    # -- consuming API (mirrors RetryingReader) -------------------------
    def read(self, n: int = -1) -> bytes:
        if self._closed:
            raise ValueError("I/O operation on closed file")
        if n is None or n < 0:
            while True:
                data = self._next_chunk()
                if not data:
                    break
                self._buf += data
            out = bytes(self._buf)
            self._buf.clear()
            self._pos += len(out)
            return out
        while len(self._buf) < n:
            data = self._next_chunk()
            if not data:
                break
            self._buf += data
        out = bytes(self._buf[:n])
        del self._buf[:n]
        self._pos += len(out)
        return out

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[:len(data)] = data
        return len(data)

    def readline(self, n: int = -1) -> bytes:
        if self._closed:
            raise ValueError("I/O operation on closed file")
        limit = n if (n is not None and n >= 0) else None
        scanned = 0
        while True:
            idx = self._buf.find(b"\n", scanned)
            if idx >= 0:
                end = idx + 1
                break
            scanned = len(self._buf)
            if limit is not None and scanned >= limit:
                end = limit
                break
            data = self._next_chunk()
            if not data:
                end = len(self._buf)
                break
            self._buf += data
        if limit is not None:
            end = min(end, limit)
        out = bytes(self._buf[:end])
        del self._buf[:end]
        self._pos += len(out)
        return out

    def readlines(self, hint: int = -1) -> list:
        out = []
        total = 0
        while True:
            line = self.readline()
            if not line:
                return out
            out.append(line)
            total += len(line)
            if 0 < hint <= total:
                return out

    def read1(self, n: int = -1) -> bytes:
        return self.read(n if n is not None and n >= 0 else 1 << 16)

    def __iter__(self) -> "PrefetchingReader":
        return self

    def __next__(self) -> bytes:
        line = self.readline()
        if not line:
            raise StopIteration
        return line

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        if self._closed:
            raise ValueError("I/O operation on closed file")
        if whence == os.SEEK_CUR:
            pos, whence = self._pos + pos, os.SEEK_SET
        if whence == os.SEEK_SET \
                and self._pos <= pos <= self._pos + len(self._buf):
            # within the buffered window: consume the prefix
            del self._buf[:pos - self._pos]
            self._pos = pos
            return pos
        # outside the window (or SEEK_END): restart at the target
        if self._demand is None:
            self._teardown_thread()
        self._buf.clear()
        if whence != os.SEEK_SET:
            # size-relative: resolve through a demand reader's seek
            if self._demand is None:
                self._demand = RetryingReader(self._path, 0)
            self._pos = self._demand.seek(pos, whence)
            return self._pos
        self._pos = pos
        self._eof = False
        if self._demand is not None:
            self._demand.seek(pos)
        # else: the readahead restarts lazily at _pos on the next read
        return pos

    def tell(self) -> int:
        return self._pos

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._demand is None:
            self._teardown_thread()
        else:
            self._demand.close()
        if self._hits or self._misses:
            faults.REGISTRY.log_line(
                "prefetch", path=self._path, hits=self._hits,
                misses=self._misses, wait_s=round(self._wait_s, 4),
                depth=self._depth)

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "PrefetchingReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def OpenReadStream(path: str, offset: int = 0,
                   tracer=None,
                   readahead_to: Optional[int] = None) -> IO[bytes]:
    """Open for reading, transparently decompressing by suffix, with
    transient-fault retry (reopen at offset) built in.

    With ``THRILL_TPU_PREFETCH`` > 0 (the default) the stream reads
    ahead of the consumer on a background thread
    (:class:`PrefetchingReader`); ``THRILL_TPU_PREFETCH=0`` restores
    the plain demand reader byte-identically.

    Compressed files do not support nonzero offsets (whole-file
    granularity, like the reference's ReadLines on compressed input).
    """
    if offset and path.endswith(COMPRESSED_SUFFIXES):
        if _scheme(path) in ("file",):
            raise ValueError("cannot seek into compressed file")
    depth = prefetch_depth()
    if depth <= 0:
        return RetryingReader(path, offset)
    return PrefetchingReader(path, offset, depth=depth, tracer=tracer,
                             readahead_to=readahead_to)


def write_file_atomic(path: str, data: bytes) -> None:
    """Write ``data`` so readers see either the old file or the whole
    new one, never a torn prefix: write to a same-directory temp name,
    fsync, then ``os.replace``. The checkpoint manifest commit
    (api/checkpoint.py) rides this — a manifest present on disk IS the
    epoch's commit record, so partial manifests must be impossible.
    Non-posix schemes (s3://, hdfs://) fall back to a plain write (the
    object stores' PUT is already all-or-nothing)."""
    if _scheme(path) != "file":
        with OpenWriteStream(path) as f:
            f.write(data)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def OpenWriteStream(path: str) -> IO[bytes]:
    if _scheme(path) == "s3":
        from . import s3_file
        return s3_file.s3_open_write(path)
    if _scheme(path) == "hdfs":
        from . import hdfs_file
        return hdfs_file.hdfs_open_write(path)
    if _scheme(path) in ("http", "https"):
        from . import object_store
        return object_store.http_open_write(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return _open_filtered(path, "wb")


def _open_filtered(path: str, mode: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    if path.endswith(".bz2"):
        return bz2.open(path, mode)
    if path.endswith(".xz"):
        return lzma.open(path, mode)
    return open(path, mode)
