"""Virtual file system: glob, ranged reads, compressed streams.

Reference: thrill/vfs/file_io.hpp:79-164 — scheme dispatch (file://,
s3://), ``Glob`` returning a FileList with exclusive size prefix sums
(used to split byte ranges over workers), Read/WriteStream interfaces,
gzip/bzip2/xz filters (sys_file.cpp pipes through external binaries; we
use Python's codecs). S3/HDFS backends are gated stubs until their SDKs
are available in the image.
"""

from __future__ import annotations

import bz2
import dataclasses
import glob as _glob
import gzip
import lzma
import os
from typing import IO, List, Optional

from ..common import faults
from ..common.retry import default_policy

COMPRESSED_SUFFIXES = (".gz", ".bz2", ".xz")

# ranged reads are idempotent — every stream here can be reopened at
# an absolute offset (posix seek, s3 ranged GET, hdfs seek; compressed
# streams re-skip decompressed bytes) — so transient storage faults
# retry with a fresh handle under the shared backoff policy instead of
# failing a whole pipeline for one flaky read
_F_OPEN = faults.declare("vfs.open_read")
_F_READ = faults.declare("vfs.read")


@dataclasses.dataclass
class FileInfo:
    path: str
    size: int              # uncompressed size unknown for compressed
    size_ex_psum: int      # exclusive prefix sum of sizes
    is_compressed: bool


@dataclasses.dataclass
class FileList:
    files: List[FileInfo]

    @property
    def total_size(self) -> int:
        if not self.files:
            return 0
        last = self.files[-1]
        return last.size_ex_psum + last.size

    @property
    def contains_compressed(self) -> bool:
        return any(f.is_compressed for f in self.files)

    def __len__(self) -> int:
        return len(self.files)

    def __getitem__(self, i: int) -> FileInfo:
        return self.files[i]


def _scheme(path: str) -> str:
    if "://" in path:
        return path.split("://", 1)[0]
    return "file"


def Glob(path_or_glob: str) -> FileList:
    """Expand a path/glob into a FileList with size prefix sums.

    Reference: vfs::Glob, file_io.hpp:105; FileList::size_ex_psum :79-99.
    """
    scheme = _scheme(path_or_glob)
    if scheme == "s3":
        from . import s3_file
        files: List[FileInfo] = []
        psum = 0
        for p, sz in s3_file.s3_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme == "hdfs":
        from . import hdfs_file
        files = []
        psum = 0
        for p, sz in hdfs_file.hdfs_glob(path_or_glob):
            files.append(FileInfo(p, sz, psum,
                                  p.endswith(COMPRESSED_SUFFIXES)))
            psum += sz
        return FileList(files)
    if scheme != "file":
        raise NotImplementedError(
            f"vfs scheme '{scheme}' is not implemented; file://, s3:// "
            f"and hdfs:// are")
    pat = path_or_glob[len("file://"):] if path_or_glob.startswith("file://") \
        else path_or_glob
    if os.path.isdir(pat):
        paths = sorted(
            os.path.join(pat, p) for p in os.listdir(pat)
            if os.path.isfile(os.path.join(pat, p)))
    else:
        paths = sorted(p for p in _glob.glob(pat) if os.path.isfile(p))
    files: List[FileInfo] = []
    psum = 0
    for p in paths:
        sz = os.path.getsize(p)
        files.append(FileInfo(p, sz, psum, p.endswith(COMPRESSED_SUFFIXES)))
        psum += sz
    return FileList(files)


def _open_at(path: str, offset: int) -> IO[bytes]:
    """One stream positioned at ``offset``, any scheme (the reopenable
    primitive the retrying reader is built on)."""
    faults.check(_F_OPEN, path=path, offset=offset)
    scheme = _scheme(path)
    if scheme == "s3":
        if path.endswith(COMPRESSED_SUFFIXES):
            raise ValueError("compressed s3 objects are read whole-file")
        from . import s3_file
        return s3_file.s3_open_read(path, offset)
    if scheme == "hdfs":
        from . import hdfs_file
        return hdfs_file.hdfs_open_read(path, offset)
    f = _open_filtered(path, "rb")
    if offset:
        if path.endswith(COMPRESSED_SUFFIXES):
            # whole-file granularity on disk, but the RETRY reopen may
            # legitimately land mid-stream: skip decompressed bytes
            skipped = 0
            while skipped < offset:
                b = f.read(min(offset - skipped, 1 << 20))
                if not b:
                    break
                skipped += len(b)
        else:
            f.seek(offset)
    return f


class RetryingReader:
    """Self-healing read stream: tracks the absolute (decompressed)
    position and, when a read or open fails transiently, reopens the
    source at that position and resumes — the vfs-level recovery the
    reference cannot express (its ReadStream dies with the job,
    vfs/file_io.hpp:140).

    A thin proxy, not an io subclass. Every CONSUMING read
    (``read``/``readinto``/``readline``/``readlines``/``read1``/
    iteration) and ``seek`` are implemented here so ``_pos`` stays
    exact — a delegated consuming read would advance the stream behind
    the tracker and make a post-fault reopen replay bytes.
    Non-consuming attributes delegate to the wrapped stream so
    existing callers (ReadLines' delimiter probing does seek+read on
    posix files) see unchanged behavior."""

    def __init__(self, path: str, offset: int = 0) -> None:
        self._path = path
        self._pos = offset
        self._closed = False
        # one policy per reader, not per read: the env knobs are fixed
        # for a stream's lifetime, and ReadLines drives this per line
        self._policy = default_policy()
        self._f = self._policy.run(
            lambda: _open_at(path, offset), what="vfs.open_read")

    def _consume(self, read_fn) -> bytes:
        """THE retry-and-reopen invariant, in one place: run one
        consuming read under the policy (injection gate, reopen at the
        tracked offset after any failure, advance ``_pos`` by what was
        actually returned). Every consuming method routes here so the
        byte-replay protection cannot silently diverge between them."""
        if self._closed:
            raise ValueError("I/O operation on closed file")

        def op():
            faults.check(_F_READ, path=self._path, pos=self._pos)
            if self._f is None:       # previous attempt lost the handle
                self._f = _open_at(self._path, self._pos)
            try:
                return read_fn(self._f)
            except Exception:
                # the handle is suspect after ANY failure: drop it so a
                # retry resumes from a fresh stream at self._pos
                self._drop()
                raise
        data = self._policy.run(op, what="vfs.read")
        self._pos += len(data)
        return data

    def read(self, n: int = -1) -> bytes:
        # read-to-EOF is spelled read() for pyarrow streams
        # (read(-1) trips their size check)
        if n is None or n < 0:
            return self._consume(lambda f: f.read())
        return self._consume(lambda f: f.read(n))

    def readinto(self, b) -> int:
        data = self.read(len(b))
        b[:len(data)] = data
        return len(data)

    def readline(self, n: int = -1) -> bytes:
        return self._consume(lambda f: f.readline(n))

    def readlines(self, hint: int = -1) -> list:
        out = []
        total = 0
        while True:
            line = self.readline()
            if not line:
                return out
            out.append(line)
            total += len(line)
            if 0 < hint <= total:     # io semantics: hint<=0 = no cap
                return out

    def read1(self, n: int = -1) -> bytes:
        return self.read(n if n is not None and n >= 0 else 1 << 16)

    def __iter__(self) -> "RetryingReader":
        return self

    def __next__(self) -> bytes:
        line = self.readline()
        if not line:
            raise StopIteration
        return line

    def seek(self, pos: int, whence: int = os.SEEK_SET) -> int:
        if self._closed:
            raise ValueError("I/O operation on closed file")
        if self._f is None:
            self._f = _open_at(self._path, self._pos)
        out = self._f.seek(pos, whence)
        self._pos = out
        return out

    def tell(self) -> int:
        return self._pos

    def _drop(self) -> None:
        f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except Exception:
                pass

    def close(self) -> None:
        self._closed = True
        self._drop()

    @property
    def closed(self) -> bool:
        return self._closed

    def __enter__(self) -> "RetryingReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __getattr__(self, name):
        # private names never delegate (and must not recurse through
        # __getattr__ during __init__/unpickling)
        if name.startswith("_"):
            raise AttributeError(name)
        # no handle (closed, or dropped after a fault): AttributeError,
        # not ValueError — hasattr/getattr-with-default probes on a
        # closed reader must behave like on any other object, and a
        # mere attribute probe must never reopen the stream
        f = self.__dict__.get("_f")
        if f is None:
            raise AttributeError(name)
        return getattr(f, name)


def OpenReadStream(path: str, offset: int = 0) -> IO[bytes]:
    """Open for reading, transparently decompressing by suffix, with
    transient-fault retry (reopen at offset) built in.

    Compressed files do not support nonzero offsets (whole-file
    granularity, like the reference's ReadLines on compressed input).
    """
    if offset and path.endswith(COMPRESSED_SUFFIXES):
        if _scheme(path) in ("file",):
            raise ValueError("cannot seek into compressed file")
    return RetryingReader(path, offset)


def write_file_atomic(path: str, data: bytes) -> None:
    """Write ``data`` so readers see either the old file or the whole
    new one, never a torn prefix: write to a same-directory temp name,
    fsync, then ``os.replace``. The checkpoint manifest commit
    (api/checkpoint.py) rides this — a manifest present on disk IS the
    epoch's commit record, so partial manifests must be impossible.
    Non-posix schemes (s3://, hdfs://) fall back to a plain write (the
    object stores' PUT is already all-or-nothing)."""
    if _scheme(path) != "file":
        with OpenWriteStream(path) as f:
            f.write(data)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def OpenWriteStream(path: str) -> IO[bytes]:
    if _scheme(path) == "s3":
        from . import s3_file
        return s3_file.s3_open_write(path)
    if _scheme(path) == "hdfs":
        from . import hdfs_file
        return hdfs_file.hdfs_open_write(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    return _open_filtered(path, "wb")


def _open_filtered(path: str, mode: str) -> IO[bytes]:
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    if path.endswith(".bz2"):
        return bz2.open(path, mode)
    if path.endswith(".xz"):
        return lzma.open(path, mode)
    return open(path, mode)
