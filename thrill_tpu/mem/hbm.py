"""HBM budget governor: accounting + device->host spill for DIA results.

Equivalent of the reference's memory-pressure machinery: BlockPool
soft/hard RAM limits with LRU eviction to disk
(reference: thrill/data/block_pool.hpp:42), the malloc_tracker
``memory_exceeded`` flag operators consult
(reference: thrill/mem/malloc_tracker.hpp:36-43, consulted by Sort at
api/sort.hpp:679), and the per-stage RAM distribution of the
StageBuilder (reference: thrill/api/dia_base.cpp:121-270).

TPU translation: the scarce resource is HBM, and the dominant HBM
consumers are the cached EXECUTED node results (columnar DeviceShards).
The governor keeps an LRU over nodes holding device-resident shards and
a byte counter with a limit (``MemoryManager.exceeded``); when the
budget is exceeded the coldest nodes' shards are fetched to host and
parked in the native block store (which itself spills to disk past its
soft limit — the HBM -> host DRAM -> disk ladder). A spilled node's
next pull re-uploads transparently.

Transient arrays inside a running operator program are XLA-managed and
not tracked here, matching the reference's split between tracked block
memory and the floating heap.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import faults
from ..common.retry import default_policy
from .manager import MemoryManager

# spill is BEST-EFFORT: a failed spill keeps the node device-resident
# (over budget but correct) and logs a recovery event — memory
# pressure must never turn into data loss. restore is MANDATORY: it
# retries transient storage faults and only then surfaces the error.
_F_SPILL = faults.declare("mem.hbm.spill")
_F_RESTORE = faults.declare("mem.hbm.restore")


class SpilledShards:
    """Host-parked form of a DeviceShards: raw leaf bytes in the block
    store plus the metadata to rebuild the sharded device arrays.

    Spilling and restoring operate on the *addressable* shards of each
    leaf (one block per local device), so a multi-controller process
    parks and re-uploads exactly its own slice of the mesh — fetching a
    globally-sharded array with np.asarray would raise on multi-host.
    """

    def __init__(self, mesh_exec, treedef, counts: np.ndarray,
                 pool, leaf_blocks: List[List[Tuple[int, int]]],
                 leaf_meta: List[Tuple[Any, Tuple[int, ...]]]) -> None:
        self.mesh_exec = mesh_exec
        self.treedef = treedef
        self.counts = counts
        self.pool = pool
        # per leaf: [(device_position_in_mesh, block_id), ...]
        self.leaf_blocks = leaf_blocks
        self.leaf_meta = leaf_meta   # (dtype, global shape) per leaf

    def restore(self):
        """Rebuild the sharded device arrays, double-buffered: while
        block k's host array uploads (``jax.device_put`` dispatches
        asynchronously), block k+1's bytes are already being fetched
        from the spill store on a readahead thread — the HBM-pressure
        analog of the prefetching vfs reader, reusing the same
        surgical policy (RAM-resident blocks read inline) and degrade
        contract (a background failure falls back to the demand read,
        which owns the retry machinery). ``THRILL_TPU_PREFETCH=0``
        restores the strictly sequential ladder."""
        from ..data.shards import DeviceShards
        from ..data.writeback import make_readahead, overlapped_fetch
        from ..vfs.file_io import prefetch_depth
        from ..common.iostats import IO as _IOSTATS
        import jax
        mex = self.mesh_exec

        def fetch(item):
            li, dev_pos, bid = item
            # injection-only site (real storage faults retry inside
            # pool.get, data.blockstore.get — wrapping it here would
            # nest two backoff budgets), so the disarmed steady state
            # skips the policy machinery
            if faults.REGISTRY.active():
                default_policy().run(
                    lambda: faults.check(_F_RESTORE, block=bid),
                    what="hbm.restore")
            return self.pool.get(bid)

        flat = [(li, dev_pos, bid)
                for li, blocks in enumerate(self.leaf_blocks)
                for dev_pos, bid in blocks]
        depth = prefetch_depth()
        pl = getattr(mex, "planner", None)
        if pl is not None and pl.enabled and len(flat) > 1:
            # consult (and possibly grow) the learned depth only when
            # a readahead pool will actually run — a 1-block restore
            # must not consume a replan mark it cannot exercise
            depth = pl.io_prefetch_depth("hbm.restore", depth)
        ra = make_readahead(depth) if len(flat) > 1 else None
        singles_per_leaf = [[] for _ in self.leaf_blocks]
        st: dict = {}
        tr = getattr(mex, "tracer", None)
        from ..common.trace import span_of
        from ..common.decisions import record_of, resolve_io_prefetch
        io0 = _IOSTATS.snapshot()
        rec = None
        if ra is not None:
            rec = record_of(mex, "io_prefetch", "hbm.restore",
                            f"depth={depth}", predicted=1.0,
                            reason="overlap next block's read with the "
                                   "current upload",
                            blocks=len(flat), depth=depth)
        try:
            with span_of(tr, "io", "hbm_restore", blocks=len(flat),
                         depth=depth if ra is not None else 0):
                for (li, dev_pos, _bid), raw in overlapped_fetch(
                        flat, fetch, "hbm.restore", ra,
                        skip_fn=lambda it: self.pool.resident(it[2]),
                        stats=st):
                    dt, shape = self.leaf_meta[li]
                    arr = np.frombuffer(raw, dtype=dt).reshape(
                        (1,) + tuple(shape[1:]))
                    singles_per_leaf[li].append(
                        jax.device_put(arr, mex.devices[dev_pos]))
        finally:
            if ra is not None:
                ra.shutdown(wait=True, cancel_futures=True)
        # audit join (shared formula, common/decisions.py): measured
        # hit rate against the perfect-rate prediction — the signal the
        # planner's learned per-site depth grows from
        resolve_io_prefetch(mex, rec,
                            _IOSTATS.delta(_IOSTATS.snapshot(), io0))
        overlapped = st.get("prefetched", 0)
        if overlapped:
            _IOSTATS.add(restore_overlaps=1)
            log = getattr(mex, "logger", None)
            if log is not None and log.enabled:
                log.line(event="restore_overlap", kind="hbm",
                         blocks=len(flat), prefetched=overlapped)
        leaves = [jax.make_array_from_single_device_arrays(
                      tuple(shape), mex.sharded, singles)
                  for singles, (dt, shape) in zip(singles_per_leaf,
                                                  self.leaf_meta)]
        tree = jax.tree.unflatten(self.treedef, leaves)
        return DeviceShards(mex, tree, self.counts)

    def free(self) -> None:
        for blocks in self.leaf_blocks:
            for _, bid in blocks:
                self.pool.drop(bid)
        self.leaf_blocks = []


class HbmGovernor:
    """LRU of nodes with device-cached results + spill under pressure."""

    def __init__(self, context, limit: int = 0) -> None:
        self.context = context
        self.mem = MemoryManager(name="hbm", limit=limit)
        self._lru: Dict[int, Any] = {}   # node id -> node (insertion = LRU)
        self._pool = None
        self.spill_count = 0
        self.restore_count = 0
        # service plane (service/tenancy.py): per-tenant byte ledger
        # next to the global one. Nodes carry the tenant active when
        # they were created (Context.current_tenant, set by the
        # scheduler around each job); a tenant crossing ITS budget
        # spills its own LRU-coldest shards — never another tenant's —
        # so one tenant's pressure rides its own restore/ladder costs
        # while its neighbors' cached results stay device-resident.
        self.tenant_budgets: Dict[str, int] = {}
        self.tenant_bytes: Dict[str, int] = {}
        self.tenant_peaks: Dict[str, int] = {}
        self.tenant_spill_count = 0

    # -- pool -----------------------------------------------------------
    def _spill_pool(self):
        if self._pool is None:
            from ..data.block_pool import BlockPool
            from .manager import MemoryConfig
            cfg = self.context.config
            host_ram = cfg.host_ram
            if not host_ram:
                try:
                    host_ram = (os.sysconf("SC_PAGE_SIZE")
                                * os.sysconf("SC_PHYS_PAGES"))
                except (ValueError, OSError):
                    host_ram = 8 << 30
            # past this soft limit the store evicts to disk: the
            # HBM -> host DRAM -> disk ladder
            # (THRILL_TPU_SPILL_RESIDENT pins it for tests/bench)
            from ..data.block_pool import resident_override
            soft = resident_override()
            if soft is None:
                soft = MemoryConfig.split(host_ram).ram_block_pool_soft
            self._pool = BlockPool(spill_dir=cfg.spill_dir,
                                   soft_limit=soft)
        return self._pool

    # -- node lifecycle hooks (called by DIABase.materialize) -----------
    @staticmethod
    def _device_bytes(shards) -> int:
        from ..data.shards import DeviceShards
        if not isinstance(shards, DeviceShards):
            return 0
        import jax
        return sum(int(l.nbytes) for l in jax.tree.leaves(shards.tree))

    def _tenant_add(self, node, nb: int) -> None:
        t = getattr(node, "_tenant", None)
        if t is None or not nb:
            return
        b = self.tenant_bytes.get(t, 0) + nb
        self.tenant_bytes[t] = b
        if b > self.tenant_peaks.get(t, 0):
            self.tenant_peaks[t] = b

    def _tenant_sub(self, node, nb: int) -> None:
        t = getattr(node, "_tenant", None)
        if t is None or not nb:
            return
        self.tenant_bytes[t] = max(self.tenant_bytes.get(t, 0) - nb, 0)

    def maybe_spill_tenant(self, node) -> None:
        """Per-tenant budget enforcement: while ``node``'s tenant is
        over ITS budget, spill that tenant's LRU-coldest nodes — and
        ONLY that tenant's. Best-effort like the global path (a tenant
        whose working set is all hot stays over budget; its next
        dispatches then pay the PR-5 ladder under real HBM limits)."""
        t = getattr(node, "_tenant", None)
        if t is None:
            return
        budget = self.tenant_budgets.get(t)
        if not budget or self.tenant_bytes.get(t, 0) <= budget:
            return
        spilled = 0
        for nid in list(self._lru.keys()):
            if nid == node.id:
                continue
            cand = self._lru.get(nid)
            if cand is None or getattr(cand, "_tenant", None) != t:
                continue
            before = self.tenant_bytes.get(t, 0)
            self.spill(cand)
            # spill() is best-effort and may DECLINE (pending check,
            # failed serialization) leaving the node resident — count
            # only spills that actually moved the tenant's bytes
            if self.tenant_bytes.get(t, 0) < before:
                spilled += 1
            if self.tenant_bytes.get(t, 0) <= budget:
                break
        if spilled:
            self.tenant_spill_count += spilled
            log = self.context.logger
            if log.enabled:
                log.line(event="tenant_spill", tenant=t, nodes=spilled,
                         bytes=self.tenant_bytes.get(t, 0),
                         budget=budget)

    def on_cache(self, node) -> None:
        """A node just cached freshly computed shards."""
        nb = self._device_bytes(node._shards)
        if nb == 0:
            return
        node._hbm_bytes = nb
        self.mem.add(nb)
        self._tenant_add(node, nb)
        self._lru[node.id] = node
        self.maybe_spill_tenant(node)
        self.maybe_spill(exclude=node.id)

    def touch(self, node) -> None:
        """A cached node was pulled again: LRU bump + restore if spilled."""
        if isinstance(node._shards, SpilledShards):
            spilled = node._shards
            node._shards = spilled.restore()
            spilled.free()
            self.restore_count += 1
            nb = self._device_bytes(node._shards)
            node._hbm_bytes = nb
            self.mem.add(nb)
            self._tenant_add(node, nb)
            log = self.context.logger
            if log.enabled:
                log.line(event="hbm_restore", node=node.label,
                         dia_id=node.id, bytes=nb)
        if node.id in self._lru:
            self._lru[node.id] = self._lru.pop(node.id)  # move to end
        elif getattr(node, "_hbm_bytes", 0):
            self._lru[node.id] = node
        self.maybe_spill_tenant(node)
        self.maybe_spill(exclude=node.id)

    def on_release(self, node, dropped) -> None:
        """A node's cached result (``dropped``) was disposed."""
        if isinstance(dropped, SpilledShards):
            dropped.free()
        nb = getattr(node, "_hbm_bytes", 0)
        if nb:
            self.mem.subtract(nb)
            self._tenant_sub(node, nb)
            node._hbm_bytes = 0
        self._lru.pop(node.id, None)

    # -- spilling -------------------------------------------------------
    def maybe_spill(self, exclude: Optional[int] = None) -> None:
        """Consult the exceeded flag; spill coldest nodes until under
        budget (the analog of memory_exceeded-triggered spilling)."""
        if not self.mem.exceeded:
            return
        for nid in list(self._lru.keys()):
            if nid == exclude:
                continue
            # spill() can recurse into maybe_spill (a hinted-join
            # validation recovering mid-spill resyncs + re-checks the
            # budget), so entries from this snapshot may already be
            # gone
            node = self._lru.get(nid)
            if node is None:
                continue
            self.spill(node)
            if not self.mem.exceeded:
                break

    def spill(self, node) -> None:
        from ..data.shards import DeviceShards
        import jax
        shards = node._shards
        if not isinstance(shards, DeviceShards):
            return
        if getattr(shards, "_counts_check", None) is not None:
            # run the deferred validation BEFORE serializing: a
            # recovering check (hinted-join overflow) swaps
            # shards.tree, and spilling first would park the
            # pre-recovery columns in the block store.
            if getattr(shards.mesh_exec, "num_processes", 1) > 1:
                # spilling is a PER-PROCESS decision; the validation
                # fetch would be a cross-process collective (counts
                # span non-addressable devices) and could hang against
                # a controller that didn't choose to spill. Keep the
                # node resident instead — same degraded mode as a
                # failed spill.
                return
            try:
                shards.validate_pending()
            except Exception:
                # sticky no-recover overflow: leave the error for the
                # CONSUMER to surface (spill must not raise out of an
                # unrelated node's materialize) and never serialize
                # the truncated columns
                return
            if node._shards is not shards:
                # validation recursed into maybe_spill and a nested
                # pass already parked THIS node — serializing again
                # would leak the first SpilledShards' blocks
                return
        pool = self._spill_pool()
        mex = shards.mesh_exec
        dev_pos = {d: i for i, d in enumerate(mex.devices)}
        leaves, treedef = jax.tree.flatten(shards.tree)
        leaf_blocks, meta = [], []
        try:
            for leaf in leaves:
                blocks: List[Tuple[int, int]] = []
                # registered BEFORE filling: a failure mid-leaf must
                # see (and free) this leaf's already-written blocks
                leaf_blocks.append(blocks)
                for sh in leaf.addressable_shards:
                    faults.check(_F_SPILL, node=node.label)
                    arr = np.ascontiguousarray(np.asarray(sh.data))
                    # the array goes to the store by POINTER (native
                    # Put copies with the GIL released) — no
                    # interpreter-side tobytes() copy per leaf shard
                    blocks.append((dev_pos[sh.device], pool.put(arr)))
                meta.append((leaf.dtype, tuple(leaf.shape)))
        except Exception as e:
            # spill failed mid-way: free the partial blocks and keep
            # the node DEVICE-RESIDENT — over budget beats data loss.
            # The LRU entry stays so a later pass can try again.
            for written in leaf_blocks:
                for _, bid in written:
                    try:
                        pool.drop(bid)
                    except Exception:
                        pass
            # ONE emission: note() counts the recovery and forwards to
            # the Context's JSON logger
            faults.note("recovery", what="hbm.spill_skipped",
                        node=node.label, dia_id=node.id, error=repr(e))
            return
        node._shards = SpilledShards(mex, treedef, shards.counts.copy(),
                                     pool, leaf_blocks, meta)
        nb = getattr(node, "_hbm_bytes", 0)
        if nb:
            self.mem.subtract(nb)
            self._tenant_sub(node, nb)
            node._hbm_bytes = 0
        self._lru.pop(node.id, None)
        self.spill_count += 1
        log = self.context.logger
        if log.enabled:
            log.line(event="hbm_spill", node=node.label, dia_id=node.id,
                     bytes=nb)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.close()
            self._pool = None
