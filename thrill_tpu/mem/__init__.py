from .manager import MemoryManager, MemoryConfig  # noqa: F401
from .pressure import (PressureMonitor, SimulatedOom,  # noqa: F401
                       is_oom_error)
