from .manager import MemoryManager, MemoryConfig  # noqa: F401
