"""Hierarchical memory accounting.

Equivalent of the reference's mem::Manager
(reference: thrill/mem/manager.hpp:28) and the RAM-splitting MemoryConfig
(reference: thrill/api/context.cpp:1082-1093, which splits total RAM into
1/3 BlockPool, 1/3 DIA operation workspace, 1/3 floating heap).

On TPU the scarce resource is HBM: the block pool budget governs how many
device-resident DIA blocks may stay pinned before cold blocks are spilled
to host DRAM (the analog of the reference's foxxll disk spill).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


class MemoryManager:
    """Thread-safe byte counter forming a tree of subsystems."""

    def __init__(self, parent: Optional["MemoryManager"] = None,
                 name: str = "root", limit: int = 0) -> None:
        self.parent = parent
        self.name = name
        self.limit = limit  # 0 = unlimited
        self.total = 0
        self.peak = 0
        self.allocs = 0
        self._lock = threading.Lock()

    def add(self, nbytes: int) -> None:
        with self._lock:
            self.total += nbytes
            self.allocs += 1
            if self.total > self.peak:
                self.peak = self.total
        if self.parent is not None:
            self.parent.add(nbytes)

    def subtract(self, nbytes: int) -> None:
        with self._lock:
            self.total -= nbytes
        if self.parent is not None:
            self.parent.subtract(nbytes)

    @property
    def exceeded(self) -> bool:
        """Analog of malloc_tracker's memory_exceeded flag
        (reference: thrill/mem/malloc_tracker.hpp:36-43) which operators
        consult to trigger spilling (e.g. api/sort.hpp:679)."""
        return self.limit > 0 and self.total > self.limit


@dataclasses.dataclass
class MemoryConfig:
    """RAM split between the block pool, operator workspace and float heap.

    Reference: thrill/api/context.cpp:1082-1093 (1/3 each).
    """

    ram: int
    ram_block_pool_hard: int
    ram_block_pool_soft: int
    ram_workers: int
    ram_floating: int

    @staticmethod
    def split(total_ram: int) -> "MemoryConfig":
        third = total_ram // 3
        return MemoryConfig(
            ram=total_ram,
            ram_block_pool_hard=third,
            ram_block_pool_soft=int(third * 0.9),
            ram_workers=third,
            ram_floating=total_ram - 2 * third,
        )
