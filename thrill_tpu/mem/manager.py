"""Hierarchical memory accounting.

Equivalent of the reference's mem::Manager
(reference: thrill/mem/manager.hpp:28) and the RAM-splitting MemoryConfig
(reference: thrill/api/context.cpp:1082-1093, which splits total RAM into
1/3 BlockPool, 1/3 DIA operation workspace, 1/3 floating heap).

On TPU the scarce resource is HBM: the block pool budget governs how many
device-resident DIA blocks may stay pinned before cold blocks are spilled
to host DRAM (the analog of the reference's foxxll disk spill).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def process_rss() -> int:
    """Resident set size of this process in bytes, from
    /proc/self/statm — the ground truth the reference's malloc_tracker
    approximates by interposing allocators
    (reference: thrill/mem/malloc_tracker.cpp:89-95). Monkeypatchable
    in tests. Returns 0 where /proc is unavailable."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


class MemoryManager:
    """Thread-safe byte counter forming a tree of subsystems."""

    def __init__(self, parent: Optional["MemoryManager"] = None,
                 name: str = "root", limit: int = 0) -> None:
        self.parent = parent
        self.name = name
        self.limit = limit  # 0 = unlimited
        self.total = 0
        self.peak = 0
        self.allocs = 0
        self._lock = threading.Lock()

    def add(self, nbytes: int) -> None:
        with self._lock:
            self.total += nbytes
            self.allocs += 1
            if self.total > self.peak:
                self.peak = self.total
        if self.parent is not None:
            self.parent.add(nbytes)

    def subtract(self, nbytes: int) -> None:
        with self._lock:
            self.total -= nbytes
        if self.parent is not None:
            self.parent.subtract(nbytes)

    @property
    def exceeded(self) -> bool:
        """Analog of malloc_tracker's memory_exceeded flag
        (reference: thrill/mem/malloc_tracker.hpp:36-43) which operators
        consult to trigger spilling (e.g. api/sort.hpp:679)."""
        return self.limit > 0 and self.total > self.limit

    def sample_rss(self) -> int:
        """Fold the process RSS into this manager's peak so reported
        peaks reflect REAL interpreter memory, not just the bytes ops
        accounted explicitly."""
        rss = process_rss()
        with self._lock:
            if rss > self.peak:
                self.peak = rss
        return rss


class RssBudget:
    """Real-memory spill trigger for EM operators.

    The reference's operators consult ``mem::memory_exceeded`` — a flag
    fed by allocator interposition — to decide when to spill
    (reference: thrill/api/sort.hpp:679, malloc_tracker.hpp:36-43).
    Python cannot interpose malloc, but /proc gives the same truth:
    a budget snapshots RSS at the start of an accumulation phase and
    ``exceeded()`` compares actual growth against the negotiated grant.
    Polling /proc costs ~1us; callers check every ``stride`` items."""

    def __init__(self, grant_bytes: int, stride: int = 1024) -> None:
        self.grant = int(grant_bytes)
        self.stride = max(int(stride), 1)
        self.base = process_rss()
        self._n = 0

    def exceeded(self) -> bool:
        """True when RSS has grown past the grant since construction
        (checked every ``stride`` calls; cheap in the item loop)."""
        self._n += 1
        if self._n % self.stride:
            return False
        return self.exceeded_now()

    def exceeded_now(self) -> bool:
        """Unconditional /proc check (~1us) — for BATCH loops, where
        one call covers thousands of items and the call-count
        decimation of :meth:`exceeded` would defeat the trigger."""
        if self.grant <= 0 or self.base <= 0:
            return False
        rss = process_rss()
        return rss > 0 and rss - self.base > self.grant

    def reset(self) -> None:
        """Re-snapshot after a spill released the accumulated items."""
        self.base = process_rss()
        self._n = 0


@dataclasses.dataclass
class MemoryConfig:
    """RAM split between the block pool, operator workspace and float heap.

    Reference: thrill/api/context.cpp:1082-1093 (1/3 each).
    """

    ram: int
    ram_block_pool_hard: int
    ram_block_pool_soft: int
    ram_workers: int
    ram_floating: int

    @staticmethod
    def split(total_ram: int) -> "MemoryConfig":
        third = total_ram // 3
        return MemoryConfig(
            ram=total_ram,
            ram_block_pool_hard=third,
            ram_block_pool_soft=int(third * 0.9),
            ram_workers=third,
            ram_floating=total_ram - 2 * third,
        )
