"""Memory-pressure resilience: admission control, OOM classification
and the escalation ladder.

The reference framework's defining robustness property is that
operators degrade to external memory instead of dying when data
outgrows RAM (reference: thrill/data/block_pool.hpp:42 pin/spill
against a hard budget; Sort/Reduce consult ``mem::memory_exceeded``
and fall back to EM algorithms, api/sort.hpp:679). The TPU port's
scarce resource is HBM, and its failure mode is a dispatch dying with
``RESOURCE_EXHAUSTED`` — this module makes that a recoverable,
observable event instead of a job killer.

Four rungs, each louder and slower than the last, none ever wrong:

1. **Admission control** (:meth:`PressureMonitor.admit`, called at the
   ``_CountedJit`` dispatch choke point): a cost model estimates the
   dispatch's output+workspace bytes from its argument shapes (plus a
   learned per-program output size and explicit plan-shape hints from
   api/fusion.py / api/device_exec.py), adds the HbmGovernor's
   live-bytes ledger, and when the sum crosses the watermark fraction
   of the HBM budget, preemptively spills cold cached shards BEFORE
   dispatching (``event=mem_spill``).
2. **OOM-retry** (:func:`recover_dispatch`): a dispatch that still
   dies with device OOM is classified (:func:`is_oom_error`), cold
   cached nodes are spilled, and the dispatch re-runs under the shared
   bounded-backoff budget (``event=oom_retry``) — with donation
   DISARMED on the retry: a donating twin re-dispatches through its
   non-donating base, and carry buffers already consumed by the failed
   dispatch surface as a clean error instead of a deleted-array crash.
3. **Spill-and-split** (api/fusion.py ``FusionPlan`` degraded path):
   when retry is exhausted, a row-local fused segment chain re-plans
   as K row-range sub-dispatches over ``common/partition.py`` bounds
   and reassembles the result (``event=segment_split`` — lineage-level
   like the hinted-join overflow re-run: loud, never wrong data).
4. **Host fallback**: the last rung runs the chain's host-engine form
   (the reference's EM degradation) when even split chunks OOM.

The HBM budget seeds from ``jax.local_devices()[i].memory_stats()``
where the backend reports one (TPU/GPU); ``THRILL_TPU_HBM_LIMIT``
overrides (and is the only way to arm admission on CPU, which reports
no stats — the off path is one attribute read per dispatch).
``THRILL_TPU_OOM_RETRY=0`` disables the whole ladder: every rung
falls away and an OOM surfaces exactly as before this module existed.

Injection sites (CPU-testable without a real OOM):

* ``mem.oom`` — raises :class:`SimulatedOom` at the dispatch choke
  point with a ``RESOURCE_EXHAUSTED`` message, exercising the REAL
  classifier and the real ladder. Declared with kind ``"oom"`` so the
  generic transient dispatch retry (common/retry.py classifies
  injected faults by their declared kind) never absorbs it — the OOM
  ladder owns it end to end.
* ``mem.spill`` — a pressure-triggered spill fails; the ladder
  degrades to dispatch-anyway (over budget beats data loss).
* ``mem.estimate`` — the cost model fails; admission is skipped for
  that dispatch (estimation is advisory, never load-bearing).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

from ..common import faults
from ..common.retry import RetryPolicy, _env_float, default_policy

OOM_KIND = "oom"


class SimulatedOom(faults.InjectedFault, RuntimeError):
    """Injected device OOM. The message mimics the runtime's
    RESOURCE_EXHAUSTED text so :func:`is_oom_error`'s string matcher —
    the one real XlaRuntimeErrors go through — is what classifies it."""

    def __init__(self, site: str, kind: str = OOM_KIND) -> None:
        faults.InjectedFault.__init__(self, site, kind)
        self.args = (f"RESOURCE_EXHAUSTED: injected out of memory "
                     f"at site '{site}'",)


_F_OOM = faults.declare("mem.oom", kind=OOM_KIND, exc=SimulatedOom)
_F_SPILL = faults.declare("mem.spill")
_F_EST = faults.declare("mem.estimate")

# substrings the accelerator runtimes put in allocation-failure errors
# (PJRT RESOURCE_EXHAUSTED, TFRT/SE allocator messages). Deliberately
# narrow: a generic "OOM" token would false-positive on user errors.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "Out of memory", "out of memory",
                "Failed to allocate", "failed to allocate",
                "Attempting to allocate")


def is_oom_error(exc: BaseException) -> bool:
    """Is this exception a device/allocator out-of-memory failure?"""
    if isinstance(exc, SimulatedOom):
        return True
    if isinstance(exc, faults.InjectedFault):
        return False            # other injections simulate other faults
    if isinstance(exc, MemoryError):
        return True
    if not isinstance(exc, (RuntimeError, ValueError, OSError)):
        return False            # XlaRuntimeError is a RuntimeError
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def retry_enabled() -> bool:
    """THRILL_TPU_OOM_RETRY=0 disables the whole escalation ladder."""
    return os.environ.get("THRILL_TPU_OOM_RETRY", "1") not in (
        "0", "off", "false")


def split_k(cap: int) -> int:
    """THRILL_TPU_SPLIT_K clamped to [2, cap]: the rung-3 row-range
    sub-dispatch count. ONE implementation shared by the reactive
    ladder (api/fusion.py _execute_degraded) and the adaptive
    planner's proactive split (api/planner.py), so the two paths
    always produce the same sub-plan."""
    try:
        k = int(os.environ.get("THRILL_TPU_SPLIT_K", "4") or 4)
    except ValueError:
        k = 4
    return max(2, min(k, cap))


def detect_hbm_budget() -> int:
    """Per-device HBM budget in bytes; 0 = unknown (admission off).

    ``THRILL_TPU_HBM_LIMIT`` overrides; otherwise the smallest
    ``bytes_limit`` any local device reports (TPU/GPU backends; CPU
    reports nothing, so admission needs the env var there)."""
    env = os.environ.get("THRILL_TPU_HBM_LIMIT")
    if env:
        from ..common.config import parse_si_iec_units
        try:
            return parse_si_iec_units(env)
        except (ValueError, TypeError):
            import sys
            print(f"thrill_tpu: bad THRILL_TPU_HBM_LIMIT={env!r}; "
                  f"ignoring", file=sys.stderr)
    import jax
    limits = []
    try:
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms and ms.get("bytes_limit"):
                limits.append(int(ms["bytes_limit"]))
    except Exception:
        return 0
    return min(limits) if limits else 0


class PressureMonitor:
    """Per-mesh memory-pressure state: the cost model, the watermark,
    and the ladder's counters. Owned by the Context (one per
    HbmGovernor) and attached as ``mesh_exec.pressure`` so the
    dispatch choke point reaches it in one attribute read."""

    def __init__(self, mesh_exec, governor=None,
                 budget: Optional[int] = None) -> None:
        self.mex = mesh_exec
        self.governor = governor
        self.budget = detect_hbm_budget() if budget is None else budget
        self.watermark = _env_float("THRILL_TPU_HBM_WATERMARK", 0.85)
        if not (0.0 < self.watermark <= 1.0):
            self.watermark = 0.85
        # admission runs only with BOTH a budget and a live-bytes
        # ledger; plain bool so the per-dispatch gate is two attribute
        # reads on the off path
        self.enabled = bool(self.budget > 0 and governor is not None)
        self.est_factor = _env_float("THRILL_TPU_MEM_EST_FACTOR", 2.0)
        # escalation-ladder counters (ctx.overall_stats surfaces them)
        self.oom_retries = 0
        self.segment_splits = 0
        self.host_fallbacks = 0
        self.admission_spills = 0
        self.spilled_bytes = 0
        self.high_watermark = 0     # max (ledger + estimate) observed
        # one-slot output-bytes hint for the NEXT dispatch, set by the
        # planners (api/fusion.py, api/device_exec.py) that know the
        # plan's output shapes before the program runs
        self._out_hint: Optional[int] = None

    # -- cost model -----------------------------------------------------
    def hint_output_bytes(self, nbytes: int) -> None:
        self._out_hint = int(nbytes)

    def estimate_call_bytes(self, fn, args) -> int:
        """Output+workspace estimate for one dispatch: argument bytes
        plus the best available output prediction — an explicit plan
        hint, the program's learned output size from a previous run,
        or ``est_factor`` times the inputs as the cold-start guess."""
        if faults.REGISTRY.active():
            faults.check(_F_EST)
        import jax
        in_bytes = 0
        for a in args:
            for l in jax.tree.leaves(a):
                in_bytes += int(getattr(l, "nbytes", 0) or 0)
        hint = self._out_hint
        self._out_hint = None
        if hint is None:
            hint = getattr(fn, "_out_bytes", None)
        if hint is not None:
            est = in_bytes + int(hint)
        else:
            est = int(in_bytes * self.est_factor)
        if getattr(fn, "_out_bytes", None) is None:
            # first (cold or hinted) estimate for this program: stash
            # it for the decision ledger's predicted-vs-actual join at
            # the dispatch choke point once the real output bytes are
            # measured (parallel/mesh.py; common/decisions.py)
            try:
                fn._adm_est = (est, in_bytes)
            except AttributeError:
                pass               # bare stubs refusing attributes
        return est

    def inadmissible(self, est_bytes: int) -> bool:
        """True when ``est_bytes`` cannot fit under the watermark at
        ANY spill level — the estimate exceeds the watermark fraction
        of the whole budget, so no amount of cold-shard eviction can
        admit it. The adaptive planner (api/planner.py) uses this as
        the cost model's HBM term: such a plan is chosen around
        (proactive fusion split) instead of dispatched into a certain
        rung-2/3 escalation."""
        return self.enabled and est_bytes > self.budget * self.watermark

    # -- rung 1: admission ----------------------------------------------
    def admit(self, fn, args) -> None:
        """Pre-dispatch admission: spill cold cached shards until the
        ledger plus this dispatch's estimate fits under the watermark.
        Estimation/spill failures degrade to dispatch-anyway — rung 2
        still guards the actual OOM."""
        try:
            est = self.estimate_call_bytes(fn, args)
        except Exception as e:
            faults.note("recovery", what="mem.estimate_skipped",
                        error=repr(e)[:200])
            return
        gov = self.governor
        live = gov.mem.total
        if live + est > self.high_watermark:
            self.high_watermark = live + est
        limit = int(self.budget * self.watermark)
        if live + est <= limit:
            return
        # never spill the dispatch's OWN input nodes: their device
        # arrays stay alive through `args` for the whole dispatch, so
        # evicting them decrements the ledger without freeing any HBM
        # (and buys a pointless spill+restore round trip)
        import jax
        live_bufs = {id(l) for a in args for l in jax.tree.leaves(a)}
        try:
            freed = self.spill_cold(need=live + est - limit,
                                    exclude_buffers=live_bufs)
        except Exception as e:
            faults.note("recovery", what="mem.pressure_spill_skipped",
                        error=repr(e)[:200])
            return
        if freed:
            faults.note("mem_spill", freed=freed, estimate=est,
                        live=live, budget=self.budget)
            self._trace_rung("admission_spill", freed=freed)

    def admit_stage(self, node) -> None:
        """Stage-level admission (api/dia_base.py): before a node's
        compute, bring the cached-results ledger back under the
        watermark — the pull-model analog of the reference's per-stage
        RAM distribution clearing room before a stage runs."""
        if not self.enabled:
            return
        gov = self.governor
        live = gov.mem.total
        limit = int(self.budget * self.watermark)
        if live > self.high_watermark:
            self.high_watermark = live
        if live <= limit:
            return
        try:
            freed = self.spill_cold(need=live - limit,
                                    exclude=getattr(node, "id", None))
        except Exception as e:
            faults.note("recovery", what="mem.pressure_spill_skipped",
                        error=repr(e)[:200])
            return
        if freed:
            faults.note("mem_spill", freed=freed, live=live,
                        budget=self.budget, node=node.label)
            self._trace_rung("admission_spill", freed=freed)

    def spill_cold(self, need: Optional[int] = None,
                   exclude: Optional[int] = None,
                   exclude_buffers: Optional[set] = None,
                   admission: bool = True) -> int:
        """Unconditionally spill LRU-coldest cached nodes (restorable
        state only — a spilled node's next pull re-uploads) until
        ``need`` bytes are freed or nothing cold remains. Nodes whose
        shard buffers appear in ``exclude_buffers`` (the in-flight
        dispatch's argument leaves) are skipped — evicting them cannot
        free HBM while the dispatch holds the arrays.
        ``admission=False`` (the OOM-retry rung) keeps the freed bytes
        in ``pressure_spilled_bytes`` but out of ``admission_spills``,
        so the stats attribute each spill to the rung that caused it.
        Returns the bytes actually freed."""
        import jax
        gov = self.governor
        if gov is None:
            return 0
        freed = 0
        for nid in list(gov._lru.keys()):
            if nid == exclude:
                continue
            node = gov._lru.get(nid)
            if node is None:
                continue            # a nested pass already handled it
            if exclude_buffers:
                shards = getattr(node, "_shards", None)
                tree = getattr(shards, "tree", None)
                if tree is not None and any(
                        id(l) in exclude_buffers
                        for l in jax.tree.leaves(tree)):
                    continue
            faults.check(_F_SPILL, node=getattr(node, "label", "?"))
            before = gov.mem.total
            gov.spill(node)
            freed += max(before - gov.mem.total, 0)
            if need is not None and freed >= need:
                break
        if freed:
            if admission:
                self.admission_spills += 1
            self.spilled_bytes += freed
            # the eviction choice in the decision ledger: which policy
            # (LRU-cold) ran, what it was asked to free, what it freed
            # — ctx.explain()'s I/O coverage alongside io_prefetch
            from ..common.decisions import record_of, resolve_of
            rec = record_of(self.mex, "io_evict", "mem.pressure",
                            "spill-lru-cold",
                            predicted=need if need else None,
                            reason="admission watermark" if admission
                            else "oom-retry ladder")
            resolve_of(self.mex, rec, freed)
        return freed

    def _trace_rung(self, rung: str, **attrs) -> None:
        """Ladder-rung marker on the "mem" trace lane (common/trace.py)
        — a Perfetto timeline shows WHEN each escalation fired relative
        to the dispatch/exchange spans around it."""
        from ..common.trace import instant_of
        instant_of(getattr(self.mex, "tracer", None), "mem", rung,
                   **attrs)

    def stats(self) -> dict:
        return {
            "hbm_high_watermark": self.high_watermark,
            "oom_retries": self.oom_retries,
            "segment_splits": self.segment_splits,
            "host_fallbacks": self.host_fallbacks,
            "admission_spills": self.admission_spills,
            "pressure_spilled_bytes": self.spilled_bytes,
        }

def _monitor_for(mex) -> PressureMonitor:
    """The mesh's monitor; a bare mesh (no Context yet) gets a
    ledger-less one so the OOM ladder can still count and retry."""
    pres = getattr(mex, "pressure", None)
    if pres is None:
        pres = PressureMonitor(mex)
        mex.pressure = pres
    return pres


# ----------------------------------------------------------------------
# rung 2: OOM-retry at the dispatch choke point
# ----------------------------------------------------------------------

class _OomRetryPolicy(RetryPolicy):
    """The shared policy with OOM-specific classification: device OOM
    is the transient class this rung retries (the base classify would
    call an XlaRuntimeError permanent and a SimulatedOom by its 'oom'
    kind); everything else surfaces on first raise."""

    def classify(self, exc: BaseException) -> str:
        return faults.TRANSIENT if is_oom_error(exc) else faults.PERMANENT


def recover_dispatch(fn, args, kwargs, exc: BaseException):
    """Handle a device OOM raised by ``fn``'s jitted dispatch: spill
    cold cached nodes and re-dispatch under the shared bounded-backoff
    policy (common/retry.py — same budget/backoff env knobs as every
    other retry layer), donation disarmed. Re-raises the last OOM when
    the budget is exhausted (the caller — the fusion planner — owns
    the next rung). ``fn`` is the ``_CountedJit`` whose dispatch
    failed; non-OOM errors never reach here."""
    mex = fn._mex
    if getattr(mex, "num_processes", 1) > 1:
        # per-process degradation on a multi-controller mesh would
        # desynchronize the collective schedule: this process would
        # spill and re-enter the SPMD program alone while a peer whose
        # dispatch failed differently (or succeeded) never does —
        # turning a clean OOM abort into a watchdog-timeout hang. Same
        # reasoning as the governor's multi-process spill guard and
        # the fusion planner's split/host-rung guard: re-raise.
        raise exc
    pres = _monitor_for(mex)

    # donation disarm: a donating twin must not re-donate buffers the
    # failed dispatch may already have consumed — retry through the
    # non-donating base program, and if donation DID consume an input,
    # surface a clean error instead of a deleted-array crash.
    base = getattr(fn, "_donate_base", None)
    target = fn._jitted if base is None else base._jitted
    if base is not None:
        import jax
        for a in args:
            for l in jax.tree.leaves(a):
                if isinstance(l, jax.Array) and l.is_deleted():
                    raise RuntimeError(
                        "device OOM after a donated input buffer was "
                        "consumed by the failed dispatch; cannot "
                        "retry in place (re-run with "
                        "THRILL_TPU_LOOP_DONATE=0)") from exc

    shared = default_policy()
    # the failed dispatch already consumed one attempt of the shared
    # budget, so this rung gets max_attempts-1 re-dispatches. run()
    # always makes at least one attempt, so "no retries left" (a
    # 1-attempt budget, or the THRILL_TPU_RETRY=0 kill switch run()
    # would otherwise clamp to one attempt) must re-raise HERE
    if shared.max_attempts <= 1 \
            or os.environ.get("THRILL_TPU_RETRY", "1") == "0":
        raise exc
    policy = _OomRetryPolicy(
        max_attempts=shared.max_attempts - 1,
        base_delay_s=shared.base_delay_s,
        max_delay_s=shared.max_delay_s)
    state = {"last": exc}

    def attempt():
        try:
            freed = pres.spill_cold(admission=False)
        except Exception as e:
            faults.note("recovery", what="mem.pressure_spill_skipped",
                        error=repr(e)[:200])
            freed = 0
        pres.oom_retries += 1
        faults.note("oom_retry", freed=freed,
                    donating=base is not None,
                    error=repr(state["last"])[:200])
        pres._trace_rung("oom_retry", freed=freed)
        try:
            if faults.REGISTRY.active():
                # the injection site rides every RETRY too, so a
                # multi-fire arming can exhaust this rung on demand
                # and hand the failure to the split rung
                faults.check(_F_OOM, retry=True)
            out = target(*args, **kwargs)
        except Exception as e:
            state["last"] = e
            raise
        faults.note("recovery", what="mem.oom", _quiet=True)
        return out

    return policy.run(attempt, what="mem.oom_retry")
