"""thrill_tpu — a TPU-native distributed batch-processing framework.

A ground-up redesign of the capabilities of Thrill (reference:
https://github.com/thrill/thrill, C++14/TCP/MPI) for TPUs: DIA
(Distributed Immutable Array) pipelines whose local operation chains are
fused by XLA tracing instead of C++ template stacks, whose shuffles are
all-to-all collectives over the ICI mesh instead of socket streams, and
whose hot operator phases (sample sort, reduce aggregation) run as
jitted/Pallas device programs over HBM-resident columnar blocks.

64-bit note: a data-processing framework needs 64-bit keys, sizes and
hashes end-to-end, so importing thrill_tpu enables JAX x64 mode. Device
kernels specify narrow dtypes (bf16/int32) explicitly where it matters
for MXU/VPU throughput.
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

from . import common, mem, net  # noqa: E402,F401

__version__ = "0.1.0"

#: top-level convenience surface (the reference exposes thrill::Run /
#: thrill::DIA the same way); resolved lazily so importing thrill_tpu
#: stays light
_API_NAMES = ("Bind", "Context", "DIA", "FieldReduce", "PipelineError",
              "Planner",
              "Run", "RunDistributed", "RunLocalMock", "RunLocalTests",
              "RunSupervised",
              "Concat", "InnerJoin", "Iterate", "Merge", "Union", "Zip",
              "ZipWindow")


def __getattr__(name):
    if name in _API_NAMES:
        from . import api
        return getattr(api, name)
    raise AttributeError(f"module 'thrill_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_API_NAMES))
