"""Mesh execution: the device-side worker model.

The reference's worker model is host processes x worker threads connected
by a TCP/MPI full mesh (reference: thrill/api/context.hpp:90-243). The
TPU-native equivalent is a ``jax.sharding.Mesh`` over a 1-D ``'w'``
(worker) axis: one logical Thrill worker per device. Per-worker state is
the device shard of globally-sharded arrays; communication is XLA
collectives over ICI/DCN inside jitted SPMD programs built with
``jax.shard_map``.

Multi-host scaling: initialize ``jax.distributed`` and pass the global
device list — the same jitted programs then span hosts, with XLA routing
collectives over ICI within a slice and DCN across slices. Nothing in the
operator layer changes, which is the point of designing single-controller
SPMD from the start.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import faults
from ..common.retry import default_policy
from ..mem import pressure as _pressure
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:                                    # jax >= 0.6: top-level export,
    from jax import shard_map as _shard_map   # replication kwarg is
    _SM_CHECK_KW = "check_vma"                # 'check_vma'
except ImportError:                     # 0.4.x: experimental module,
    from jax.experimental.shard_map import (  # kwarg is 'check_rep'
        shard_map as _shard_map)
    _SM_CHECK_KW = "check_rep"


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable jax.shard_map (one shim for both spellings)."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_SM_CHECK_KW: check_vma})


AXIS = "w"

# device dispatch is PURE (jitted functional program over immutable
# buffers), so a transient runtime/transport fault — a dropped tunnel
# RPC, a preempted PJRT stream — retries safely under the shared
# backoff policy before surfacing
_F_DISPATCH = faults.declare("api.mesh.dispatch")

# Trace-time back-channel: while a program dispatches (including its
# FIRST call, when jax traces the python builder), the owning mesh and
# the _CountedJit being run are visible here. Plan choke points that
# live INSIDE traced builders (core/device_sort.py's engine choice)
# use this to reach the decision ledger / planner without threading a
# mex handle through every functional signature.
_TL = threading.local()


def current_mex() -> Optional["MeshExec"]:
    """The MeshExec whose program is currently dispatching (or being
    traced) on this thread; None outside a dispatch."""
    return getattr(_TL, "mex", None)


def current_program() -> Optional["_CountedJit"]:
    """The _CountedJit currently dispatching on this thread."""
    return getattr(_TL, "prog", None)


class _CountedJit:
    """Dispatch-counting proxy around a ``jax.jit`` callable.

    Every attribute other than ``__call__`` delegates to the jitted
    function (``.lower``, ``.trace``, ``.clone``, cost analysis...), so
    AOT/introspection callers see the real jit object — only calls gain
    the dispatch counter and the fault-injected retry.

    ``raw`` keeps the pre-jit callable (the shard_map program) so the
    loop-replay layer (api/loop.py) can build DONATING twins
    (``jax.jit(raw, donate_argnums=...)``) and trace the program into a
    whole-loop ``lax.fori_loop`` body."""

    def __init__(self, mex: "MeshExec", jitted: Callable,
                 raw: Optional[Callable] = None) -> None:
        self._mex = mex
        self._jitted = jitted
        self.raw = raw
        # the MeshExec.cached key this program was built under (stamped
        # by cached()); the loop layer keys derived whole-loop programs
        # on it so equal tapes share ONE compiled fori_loop
        self.cache_key: Optional[Tuple] = None
        self._donating: Dict[Tuple[int, ...], Callable] = {}
        # memory-pressure cost model (mem/pressure.py): the program's
        # measured output bytes, learned on the first successful call;
        # the donating-twin back-pointer lets the OOM ladder re-dispatch
        # with donation disarmed
        self._out_bytes: Optional[int] = None
        # (estimate, input_bytes) stashed by admission for the decision
        # ledger's predicted-vs-actual join on the first measured call
        # (common/decisions.py; plain attr — __getattr__ delegates
        # unknown names to the jitted function, so it must exist here)
        self._adm_est: Optional[Tuple[int, int]] = None
        self._donate_base: Optional["_CountedJit"] = None
        self._trace_label: Optional[str] = None
        # sort-engine decisions recorded while THIS program traced
        # (core/device_sort.py via current_program()); resolved with
        # the first post-compile dispatch latency (the tracing call's
        # wall time is compile, not dispatch)
        self._engine_recs: list = []
        self._engine_armed = False
        functools.update_wrapper(self, jitted, updated=())

    def _label(self) -> str:
        lbl = self._trace_label
        if lbl is None:
            key = self.cache_key
            if isinstance(key, tuple) and key \
                    and isinstance(key[0], str):
                lbl = key[0]                # "fused", "xchg_chunk"...
            else:
                lbl = getattr(self._jitted, "__name__", None) or "jit"
            self._trace_label = lbl
        return lbl

    def __call__(self, *args, **kwargs):
        # tracing fast path (the pinned overhead contract,
        # tests/common/test_trace.py): THRILL_TPU_TRACE=0 costs one
        # attribute read plus one predicate — no span objects, no
        # context managers, nothing else
        tr = self._mex.tracer
        if tr is None or not tr.enabled:
            return self._dispatch(args, kwargs)
        with tr.span("dispatch", self._label()):
            return self._dispatch(args, kwargs)

    def _dispatch(self, args, kwargs):
        mex = self._mex
        mex.stats_dispatches += 1
        pres = mex.pressure
        if pres is not None and pres.enabled:
            # rung 1, admission control: estimate this dispatch's
            # output+workspace bytes and pre-spill cold cached shards
            # when the governor ledger says HBM is near the watermark
            pres.admit(self, args)
        prev_mex = getattr(_TL, "mex", None)
        prev_prog = getattr(_TL, "prog", None)
        _TL.mex, _TL.prog = mex, self
        t0 = time.perf_counter()
        try:
            try:
                if not faults.REGISTRY.active():
                    # disarmed hot path: dispatch-per-iteration is the
                    # budgeted cost in this codebase — no policy
                    # construction, no env reads beyond active()'s one
                    out = self._jitted(*args, **kwargs)
                else:
                    def dispatch():
                        faults.check(_F_DISPATCH)
                        faults.check(_pressure._F_OOM)
                        return self._jitted(*args, **kwargs)

                    out = default_policy().run(dispatch,
                                               what="mesh.dispatch")
            except Exception as e:
                # rung 2, OOM-retry: device RESOURCE_EXHAUSTED spills
                # the LRU cache and re-dispatches (donation disarmed)
                # under the shared backoff budget; anything else — and
                # every error with the ladder disabled — re-raises
                # unchanged
                if not (_pressure.retry_enabled()
                        and _pressure.is_oom_error(e)):
                    raise
                out = _pressure.recover_dispatch(self, args, kwargs, e)
        finally:
            _TL.mex, _TL.prog = prev_mex, prev_prog
        # Dispatch-latency spine (ROADMAP planner edge (b)): the
        # running MIN over calls converges on the pure launch overhead
        # (trace/compile calls are strictly slower, so min excludes
        # them); data/exchange.py calibrates bytes_eq from it once
        # enough samples accumulate. Two perf_counter reads per
        # dispatch — no allocation, no env reads.
        dt = time.perf_counter() - t0
        if dt < mex._disp_lat_min:
            mex._disp_lat_min = dt
        mex._disp_lat_n += 1
        if self._engine_recs:
            if not self._engine_armed:
                # this call traced the program (and recorded the
                # engine decision); its wall time is compile time
                self._engine_armed = True
            else:
                led = mex.decisions
                if led is not None and led.enabled:
                    for erec in self._engine_recs:
                        led.resolve(erec, dt * 1e6)
                self._engine_recs = []
        if pres is not None and pres.enabled and self._out_bytes is None:
            self._out_bytes = sum(
                int(getattr(l, "nbytes", 0) or 0)
                for l in jax.tree.leaves(out))
            # decision-ledger join at the dispatch choke point: the
            # admission cost model predicted this program's bytes
            # before its first run; the measured output is the truth.
            # THRILL_TPU_DECISIONS=0 pays exactly one attribute read
            # plus one predicate here and allocates nothing (pinned by
            # tests/common/test_decisions.py via RECORDS_CREATED).
            led = mex.decisions
            if led is not None and led.enabled \
                    and self._adm_est is not None:
                est, in_bytes = self._adm_est
                self._adm_est = None
                rec = led.record(
                    "admission", site="jit:" + self._label(),
                    chosen="admit", predicted=est,
                    reason="first estimate for this program",
                    in_bytes=in_bytes)
                led.resolve(rec, in_bytes + self._out_bytes)
        rec = mex.loop_recorder
        if rec is not None:
            rec.on_call(self, args, kwargs, out)
        return out

    def donating(self, donate_argnums: Tuple[int, ...]) -> Callable:
        """A twin executable that donates the given argument buffers
        (loop-carried HBM reuse on replayed dispatches). Compiled once
        per donation signature; requires ``raw``."""
        fn = self._donating.get(donate_argnums)
        if fn is None:
            if self.raw is None:
                raise ValueError("no raw program retained; cannot "
                                 "build a donating twin")
            fn = _CountedJit(self._mex,
                             jax.jit(self.raw,
                                     donate_argnums=donate_argnums))
            # the OOM ladder (mem/pressure.py) retries a failed
            # donating dispatch through THIS base so the retry never
            # re-donates buffers the failed attempt may have consumed
            fn._donate_base = self
            self._donating[donate_argnums] = fn
        return fn

    def __getattr__(self, name):
        return getattr(self._jitted, name)


class MeshExec:
    """Owns the worker mesh and caches compiled SPMD programs."""

    def __init__(self, devices: Optional[Sequence[Any]] = None,
                 num_workers: int = 0, backend: Optional[str] = None) -> None:
        if devices is None:
            devices = jax.devices(backend) if backend else jax.devices()
            if num_workers:
                if num_workers > len(devices):
                    raise ValueError(
                        f"requested {num_workers} workers but only "
                        f"{len(devices)} devices available")
                devices = devices[:num_workers]
        self.devices = list(devices)
        self.num_workers = len(self.devices)
        self.mesh = Mesh(np.asarray(self.devices), (AXIS,))
        self._cache: Dict[Any, Callable] = {}
        # cumulative data-plane traffic (cross-worker items/bytes)
        self.stats_exchanges = 0
        self.stats_items_moved = 0
        self.stats_bytes_moved = 0
        # padded rows allocated by exchange plans (skew diagnostics)
        self.stats_padded_rows = 0
        # overlapped-exchange data plane (data/exchange.py): exchanges
        # dispatched optimistically on a cached capacity plan (no
        # mid-shuffle host sync), capacity-plan cache hits/misses, and
        # the bytes that actually cross the fabric/wire — padded rows
        # on the device plane, serialized frames on the host plane
        # (the baseline for ROADMAP's shrink-the-wire item)
        self.stats_exchanges_overlapped = 0
        self.stats_cap_cache_hits = 0
        self.stats_cap_cache_misses = 0
        self.stats_bytes_wire_device = 0
        self.stats_bytes_wire_host = 0
        # shrink-the-wire layer: what full-width device rows would have
        # shipped (actual is bytes_wire_device, narrowed), and host
        # frame bytes saved by the column codec (net/wire.py) — the
        # two halves of wire_compress_ratio in overall_stats
        self.stats_bytes_wire_device_raw = 0
        self.stats_bytes_wire_host_saved = 0
        # chunked-exchange accumulator donation (data/exchange.py
        # _dispatch_chunked): dispatches that actually armed
        # donate_argnums on the chunk accumulator — 0 on CPU where
        # aliasing is never real, >0 on TPU where the HBM reuse pays
        self.stats_xchg_donated = 0
        # per-exchange-site plan kind ('dense' = optimistic-eligible,
        # 'sync' = the site needs the host plan step every time); the
        # capacity values themselves live in _sticky_caps
        self._xchg_plan: Dict[Any, str] = {}
        # device-program dispatch / host<->device transfer counters.
        # On a tunneled chip every dispatch pays the link round trip
        # (measured 140.7 ms on the axon tunnel, BASELINE.md round 5),
        # so DISPATCH COUNT — not FLOPs or bytes — is the governing
        # cost model for small-to-medium pipelines; these counters make
        # it observable and testable (tests/api/test_dispatch_budget.py)
        self.stats_dispatches = 0
        self.stats_uploads = 0
        self.stats_fetches = 0
        self.stats_upload_cache_hits = 0
        # program stitching (api/fusion.py): dispatches launched by the
        # fused runner, total DOp segments they carried, and per-stage
        # composition (tuple of op labels -> launch count) — the
        # dispatch budget's observability surface
        self.stats_fused_dispatches = 0
        self.stats_fused_ops = 0
        self.fused_stage_counts: Dict[Tuple[str, ...], int] = {}
        # iteration execution layer (api/loop.py): LoopPlan captures,
        # tape replays (iterations that paid ZERO graph construction /
        # planning), whole-loop fori_loop dispatches, loud replay
        # fallbacks to full re-planning, and HBM bytes donated back to
        # XLA on replayed dispatches
        self.stats_loop_plan_builds = 0
        self.stats_loop_replays = 0
        self.stats_loop_fori_iters = 0
        self.stats_loop_fallbacks = 0
        self.stats_loop_donated_bytes = 0
        # active tape recorder (None = zero-overhead fast path); set by
        # api/loop.py around a capture iteration's body run
        self.loop_recorder = None
        # memory-pressure monitor (mem/pressure.py), attached by the
        # Context once the HbmGovernor exists; None = the dispatch
        # choke point pays one attribute read and no admission runs
        self.pressure = None
        # tracing spine (common/trace.py), attached by the Context;
        # None (bare mesh) or tracer.enabled False (THRILL_TPU_TRACE=0)
        # = the dispatch choke point pays one attribute read plus one
        # predicate and allocates nothing
        self.tracer = None
        # decision ledger (common/decisions.py), attached by the
        # Context; same off-path contract as the tracer — None or
        # THRILL_TPU_DECISIONS=0 means every plan-choice choke point
        # pays one attribute read plus one predicate
        self.decisions = None
        # adaptive cost-based planner (api/planner.py), attached by
        # the Context; None or THRILL_TPU_PLANNER=0 means every plan
        # choice takes its legacy per-site heuristic branch exactly
        self.planner = None
        # per-Iterate reports (phase timings, replay hit rate) for
        # bench.py / tools/loop_report.py
        self.loop_reports: list = []
        self._put_small_cache: Dict[Any, jax.Array] = {}
        # deferred device-side validations (e.g. InnerJoin
        # out_size_hint overflow): ops that skip a blocking host sync
        # enqueue a check here; every host fetch drains the queue, so
        # no pipeline can reach its action egress past a failed check
        self._pending_checks: list = []
        # lineage recoveries: hinted joins transparently re-run without
        # their hint after a detected overflow (api/ops/join.py)
        self.stats_join_overflow_retries = 0
        # service plane (service/): data-driven host plan constructions
        # — synced exchange capacity plans (data/exchange.py
        # _exchange_planned) and pre-shuffle cost-model evaluations
        # (core/preshuffle.py) — versus plan-store seeds consumed
        # instead. A warm restart of a known pipeline against a
        # populated store runs with stats_plan_builds == 0 (the
        # acceptance counter of the persistent plan store; the Context
        # owns the store handle, service/plan_store.py)
        self.stats_plan_builds = 0
        self.stats_plan_store_hits = 0
        # ICI-vs-DCN split of bytes_moved (multi-slice meshes; equal to
        # bytes_moved/0 on a single slice)
        self.stats_bytes_ici = 0
        self.stats_bytes_dcn = 0
        # exchange implementation ('dense' | 'onefactor' | 'ragged');
        # Context sets it from Config.exchange, THRILL_TPU_EXCHANGE
        # env overrides ('dense' auto-switches to 1-factor under skew).
        # The env override is read ONCE here: resolve_mode() used to
        # pay an os.environ lookup on every exchange plan step — set
        # the variable before constructing the mesh
        self.exchange_mode = "dense"
        import os as _os
        self._env_exchange = _os.environ.get("THRILL_TPU_EXCHANGE")
        # Pallas kernel tier knob, resolved ONCE here (same contract
        # as _env_exchange above): core/pallas_kernels.pallas_enabled()
        # used to pay an os.environ lookup per call, and it runs inside
        # traced builders — set THRILL_TPU_PALLAS before constructing
        # the mesh
        self._env_pallas = _os.environ.get("THRILL_TPU_PALLAS")
        # dispatch-latency spine for the planner's live bytes_eq
        # calibration (edge (b)): running min + sample count, updated
        # at the _CountedJit choke point
        self._disp_lat_min = float("inf")
        self._disp_lat_n = 0
        # slice topology: collectives between same-slice workers ride
        # ICI, cross-slice DCN. Detected from the device objects'
        # slice_index (real multi-slice pods); THRILL_TPU_SLICES=k
        # overrides with k contiguous blocks (virtual-mesh testing).
        self.slice_id = self._detect_slices()
        self.num_slices = int(self.slice_id.max()) + 1 \
            if len(self.slice_id) else 1
        # controller topology: which PROCESS owns each worker's device.
        # The host-storage data plane (data/multiplexer.py) keeps each
        # process holding only its own workers' items and ships the
        # rest over the host control plane (the reference's Multiplexer
        # moving serialized Blocks between hosts,
        # thrill/data/multiplexer.cpp:282-440).
        self.worker_process = np.array(
            [getattr(d, "process_index", 0) for d in self.devices],
            dtype=np.int64)
        self.process_index = int(jax.process_index())
        self.num_processes = len(set(self.worker_process.tolist())) or 1
        # host-plane collectives between processes (FlowControlChannel
        # over the authenticated TCP group); Context wires it so the
        # host-storage layer can reach the other controllers
        self.host_net = None

    def _detect_slices(self) -> np.ndarray:
        import os
        import sys
        W = self.num_workers
        k = os.environ.get("THRILL_TPU_SLICES")
        if k:
            try:
                k = int(k)
            except ValueError:
                print(f"thrill_tpu: THRILL_TPU_SLICES={k!r} is not an "
                      f"integer; ignoring (single-slice topology)",
                      file=sys.stderr)
                k = 0
            if k == 1:                  # explicit single-slice override
                return np.zeros(W, dtype=np.int64)
            if k > 1:
                if W % k == 0:
                    return np.repeat(np.arange(k), W // k)
                print(f"thrill_tpu: THRILL_TPU_SLICES={k} does not "
                      f"divide {W} workers; ignoring (single-slice "
                      f"topology)", file=sys.stderr)
        ids = [getattr(d, "slice_index", None) for d in self.devices]
        if all(i is not None for i in ids) and len(set(ids)) > 1:
            # normalize to dense 0..nS-1 preserving device order
            uniq = {s: n for n, s in enumerate(dict.fromkeys(ids))}
            return np.array([uniq[i] for i in ids], dtype=np.int64)
        return np.zeros(W, dtype=np.int64)

    # -- controller topology -------------------------------------------
    @property
    def multiprocess(self) -> bool:
        return self.num_processes > 1

    @property
    def local_workers(self):
        """Worker ids whose device this process owns (all of them in a
        single-controller run)."""
        return [w for w in range(self.num_workers)
                if self.worker_process[w] == self.process_index]

    # -- shardings ------------------------------------------------------
    @property
    def sharded(self) -> NamedSharding:
        """Sharding that splits axis 0 across workers."""
        return NamedSharding(self.mesh, P(AXIS))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def put(self, arr) -> jax.Array:
        """Place a host array (leading dim == num_workers) sharded.

        Multi-controller: assembled from per-device addressable shards
        (jax.device_put with a sharded sharding ASSERTS value equality
        across processes — but builds like ReadWordsPacked/ReadBinary
        legitimately hold real data only for their own workers' rows,
        with agreed shapes/counts and zero padding elsewhere)."""
        self.stats_uploads += 1
        if self.num_processes > 1:
            arr = np.asarray(arr)
            assert arr.shape[0] % self.num_workers == 0, arr.shape
            k = arr.shape[0] // self.num_workers   # rows per worker
            local = [jax.device_put(arr[w * k:(w + 1) * k],
                                    self.devices[w])
                     for w in self.local_workers]
            return self._bless(jax.make_array_from_single_device_arrays(
                arr.shape, self.sharded, local))
        return self._bless(jax.device_put(arr, self.sharded))

    def _bless(self, buf: jax.Array) -> jax.Array:
        """Mark a host-uploaded buffer as a legitimate tape constant.
        The loop recorder (api/loop.py) rejects device arrays CREATED
        during a capture iteration — they could be eager host math over
        loop data, which a tape would freeze at iteration-1 values.
        put() is the one host->device choke point, and its numpy input
        is already covered by the fetch-taint + numpy-argument guards,
        so its outputs are safe constants."""
        rec = self.loop_recorder
        if rec is not None:
            rec.bless(buf)
        return buf

    def asarray_blessed(self, leaves):
        """``jnp.asarray`` each non-jax leaf of a dispatch's bound
        operands, blessing the conversions as tape constants. Host
        plan leaves (np bounds/sizes, scalars) converted right before
        a dispatch are legitimate constants by the same argument as
        :meth:`put` uploads — fetched loop-variant values are already
        rejected by the recorder's fetch taint and numpy-argument
        guards. Device leaves pass through with identity preserved so
        the recorder can classify them as carry/val."""
        rec = self.loop_recorder
        out = []
        for l in leaves:
            if not isinstance(l, jax.Array):
                l = jnp.asarray(l)
                if rec is not None:
                    rec.bless(l)
            out.append(l)
        return out

    def put_tree(self, tree):
        return jax.tree.map(self.put, tree)

    def put_small(self, arr, replicated: bool = False) -> jax.Array:
        """Content-cached ``put`` for small recurring plan arrays
        (shard counts, zip offsets, range bounds). Iterative pipelines
        re-upload identical tiny arrays every iteration — on a tunneled
        chip each is a link round trip (BASELINE.md r5) — and device
        buffers are immutable, so sharing one upload per distinct value
        is safe. Falls through to plain put() above 4 KiB.

        ``replicated=True`` places the whole array on every worker
        (P() operand — the exchange plans' [W, W] send matrix form)
        instead of splitting axis 0."""
        arr = np.asarray(arr)
        if arr.nbytes > 4096:
            return self._put_replicated(arr) if replicated \
                else self.put(arr)
        key = (arr.shape, arr.dtype.str, arr.tobytes(), replicated)
        buf = self._put_small_cache.get(key)
        if buf is None:
            if len(self._put_small_cache) >= 4096:   # unbounded-growth cap
                self._put_small_cache.clear()
            buf = self._put_replicated(arr) if replicated \
                else self.put(arr)
            self._put_small_cache[key] = buf
        else:
            self.stats_upload_cache_hits += 1
        return buf

    def _put_replicated(self, arr) -> jax.Array:
        """Upload one identical copy per device (values must already
        agree across processes — exchange plan arrays derive from the
        replicated send matrix, so they do)."""
        self.stats_uploads += 1
        return self._bless(jax.device_put(np.asarray(arr),
                                          self.replicated))

    def fetch(self, arr) -> np.ndarray:
        """Device -> host fetch that is multi-controller safe.

        ``np.asarray`` raises on arrays spanning non-addressable
        devices (other processes' chips); those are gathered across
        processes first. Single-process meshes take the direct path.
        """
        if isinstance(arr, jax.Array):
            self.stats_fetches += 1
        self.drain_checks()
        return self._fetch_raw(arr)

    def drain_checks(self) -> None:
        """Run every queued deferred validation (hinted-join overflow
        recovery and the like). Called by fetch() and by every action
        egress — AllGatherArrays, Sum/_device_reduce(keep_device=True),
        Gather — so no pipeline output can be consumed past an unrun
        check, whatever path it leaves the device by."""
        if not self._pending_checks:
            return
        checks, self._pending_checks = self._pending_checks, []
        try:
            while checks:
                checks.pop(0)()
        except BaseException:
            # a raising check must not discard the unrun tail —
            # a second hinted join's overflow still gets detected
            # at the next fetch even if the caller swallows this one
            self._pending_checks.extend(checks)
            raise

    def reset_run_state(self) -> int:
        """Abandon the aborted pipeline's per-run execution state: the
        deferred-check queue (their producer shards are being
        disposed; a surviving older node's shards still re-validate at
        their own pull — the queue is only the backstop) and any live
        loop-capture recorder. Learned, value-independent state —
        compiled programs, sticky exchange capacities, narrow specs,
        plan kinds — survives: the next pipeline reuses it and stays
        bit-identical to a fresh-Context run by construction. Returns
        the number of checks dropped."""
        dropped = len(self._pending_checks)
        self._pending_checks.clear()
        self.loop_recorder = None
        return dropped

    def _fetch_raw(self, arr) -> np.ndarray:
        """fetch() without stats or check-draining — for the deferred
        checks themselves (their transfers are tiny, ride a completed
        program, and must not read as mid-pipeline syncs in the
        dispatch-budget accounting)."""
        rec = self.loop_recorder
        if rec is not None:
            # a capture is watching: host plan logic reading a value a
            # recorded dispatch produced may bake loop-VARIANT plan
            # data (exchange send matrices) into the tape — the
            # recorder checks the producer's carry-dependence and
            # rejects such captures (api/loop.py)
            rec.on_fetch(arr)
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(arr,
                                                            tiled=True))

    def fetch_tree(self, tree):
        return jax.tree.map(self.fetch, tree)

    # -- compiled SPMD programs ----------------------------------------
    def smap(self, fn: Callable, num_args: int, out_specs=P(AXIS),
             in_specs=None, check_vma: bool = False) -> Callable:
        """jit(shard_map(fn)) with all-sharded inputs by default.

        Inside ``fn`` every array argument has its leading worker axis
        sliced to size 1 (this worker's shard); collectives use AXIS.
        """
        if in_specs is None:
            in_specs = (P(AXIS),) * num_args
        sm = shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)
        # full attribute delegation (not a copied .lower): AOT and
        # introspection callers (.trace, .clone, cost analysis) see
        # the real jit object through the counting proxy; the raw
        # shard_map program rides along for loop-replay donation twins
        # and whole-loop fori lowering (api/loop.py)
        return _CountedJit(self, jax.jit(sm), raw=sm)

    def jit_cached(self, key: Tuple, fn: Callable) -> Callable:
        """A cached plain-``jax.jit`` program behind the counting
        proxy: replicated (non-shard_map) device math — an iterative
        driver's small update step — becomes a RECORDABLE dispatch the
        loop layer (api/loop.py) can tape and replay, instead of eager
        ops the capture must reject."""
        return self.cached(key, lambda: _CountedJit(self, jax.jit(fn),
                                                    raw=fn))

    def counted_jit(self, fn: Callable) -> "_CountedJit":
        """``jax.jit`` behind the counting proxy, uncached — for
        callers managing their own cache entry (the whole-loop
        fori_loop program, api/loop.py). This and the two methods
        above are the ONLY places the codebase constructs a jit:
        admission control, the OOM ladder and the dispatch counters
        depend on every device entry passing through _CountedJit
        (pinned by tests/common/test_tracing.py's source audit)."""
        return _CountedJit(self, jax.jit(fn), raw=fn)

    def cached(self, key: Tuple, builder: Callable[[], Callable]) -> Callable:
        """Memoize a compiled program per (mesh, key).

        DOp implementations use module-level builder functions plus a
        static-parameter key, so re-running a pipeline reuses compiled
        XLA executables (first compile 20-40s on TPU, then cached).
        Trace-time environment knobs that change generated code (the
        sort engine selection) are folded into every key so toggling
        them mid-process takes effect instead of hitting stale programs.
        """
        import os
        key = key + (os.environ.get("THRILL_TPU_SORT_IMPL", "auto"),
                     os.environ.get("THRILL_TPU_SORT_U32"),
                     os.environ.get("THRILL_TPU_PACK_MOVE", "auto"))
        fn = self._cache.get(key)
        if fn is None:
            fn = builder()
            target = fn[0] if isinstance(fn, tuple) else fn
            if isinstance(target, _CountedJit):
                target.cache_key = key
                seed = getattr(self, "_out_bytes_seed", None)
                if seed:
                    # warm restart (service/plan_store.py): the
                    # admission cost model's learned output size for
                    # this program survives the restart — first
                    # dispatches admit on measured bytes instead of
                    # the est_factor cold-start guess
                    from ..data.exchange import _ident_digest
                    v = seed.pop(_ident_digest(key), None)
                    if v is not None:
                        # a bad store value may only cost recompiles,
                        # never a dispatch failure
                        try:
                            target._out_bytes = int(v)
                            self.stats_plan_store_hits += 1
                        except (TypeError, ValueError):
                            pass
                        else:
                            led = self.decisions
                            if led is not None and led.enabled:
                                led.record(
                                    "store_seed",
                                    site="jit:" + target._label(),
                                    chosen="out_bytes",
                                    predicted=target._out_bytes,
                                    reason="warm-start learned size")
            self._cache[key] = fn
        return fn

    # -- plan-state persistence (service/plan_store.py) -----------------
    def export_learned_sizes(self) -> dict:
        """Learned per-program output sizes (the admission cost
        model's ``_out_bytes``) keyed by cache-key digest, plus any
        unconsumed imported seeds."""
        from ..data.exchange import _ident_digest
        out = {}
        for key, fn in self._cache.items():
            target = fn[0] if isinstance(fn, tuple) else fn
            ob = getattr(target, "_out_bytes", None)
            if ob:
                out[_ident_digest(key)] = int(ob)
        for dg, v in (getattr(self, "_out_bytes_seed", None)
                      or {}).items():
            out.setdefault(dg, v)
        return out

    def import_learned_sizes(self, m: dict) -> int:
        seed = getattr(self, "_out_bytes_seed", None)
        if seed is None:
            seed = self._out_bytes_seed = {}
        seed.update({str(k): v for k, v in m.items()})
        return len(m)

    # -- elastic resize (api/context.py Context.resize) -----------------
    def _w_state_attrs(self) -> Tuple[str, ...]:
        """Lazily-created attributes whose values are W-shaped and must
        swap with the worker count: exchange plan state (capacity
        vectors, plan kinds, narrow ranges, store seeds), pre-shuffle
        verdicts, loop tapes (their donation twins are compiled against
        W-sharded buffers), learned output sizes, and the compiled
        program cache itself (every program closes over the mesh)."""
        from ..data.exchange import W_STATE_ATTRS
        return W_STATE_ATTRS + ("_prune_decisions", "_prune_history",
                                "_loop_tapes", "_out_bytes_seed",
                                "_cache")

    def resize(self, devices: Sequence[Any]) -> None:
        """Re-point the executor at a new device set (a new W) at a
        generation boundary. The old W's learned and compiled state is
        ARCHIVED, not discarded, and any state learned the last time
        the new W was active is restored — a W=2→3→2 cycle returns to
        warm plans instead of cold ones. Per-run content caches
        (replicated small uploads, deferred checks, an in-flight loop
        recorder) are device-addressed and simply dropped.

        The caller owns everything above the executor: live shards
        must already be extracted for re-partitioning (the old mesh's
        arrays stay readable — jax arrays carry their sharding — but
        nothing new may be laid out against it), and the host group's
        membership changes through ``net.Group.resize``."""
        devices = list(devices)
        new_w = len(devices)
        if new_w < 1:
            raise ValueError("cannot resize to an empty device set")
        old_w = self.num_workers
        if new_w == old_w and devices == self.devices:
            return
        arch = getattr(self, "_w_archive", None)
        if arch is None:
            arch = self._w_archive = {}
        saved = {}
        for a in self._w_state_attrs():
            if a in self.__dict__:
                saved[a] = self.__dict__.pop(a)
        arch[old_w] = saved
        for a, v in arch.pop(new_w, {}).items():
            setattr(self, a, v)
        if "_cache" not in self.__dict__:
            self._cache = {}
        if "_xchg_plan" not in self.__dict__:
            self._xchg_plan = {}
        self.devices = devices
        self.num_workers = new_w
        self.mesh = Mesh(np.asarray(self.devices), (AXIS,))
        self.slice_id = self._detect_slices()
        self.num_slices = int(self.slice_id.max()) + 1 \
            if len(self.slice_id) else 1
        self.worker_process = np.array(
            [getattr(d, "process_index", 0) for d in self.devices],
            dtype=np.int64)
        self.num_processes = len(set(self.worker_process.tolist())) or 1
        self._put_small_cache.clear()
        self._pending_checks.clear()
        self.loop_recorder = None
