"""Loop-replay report over the iterative example pipelines.

Runs PageRank and k-means with the iteration execution layer on
(default) and with THRILL_TPU_LOOP_REPLAY=0, checks exact result
parity, and prints per-loop replay hit rate, plan builds, whole-loop
fori iterations, donated loop-carry bytes, and the wall-clock split
between the capture iteration (graph build + planning + dispatch) and
the replayed iterations (pure dispatch). The mirror of
``fusion_report`` one layer up: where that report counts dispatches a
stitched program saves, this one counts the PLANNING work a replayed
loop never does.

Usage::

    python -m thrill_tpu.tools.loop_report [--pages N] [--edges M]
        [--iters K] [--points N] [--clusters K]

(or ``run-scripts/loop_report.sh``).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _examples_path() -> None:
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", "..", "examples")
    if p not in sys.path:
        sys.path.insert(0, p)


def _measure(name, job, mex):
    """job() under replay on/off (one warm run each); returns the
    row + the loop report captured from the replayed run."""
    import numpy as np
    results, wall = {}, {}
    report = None
    prev = os.environ.get("THRILL_TPU_LOOP_REPLAY")
    try:
        for replay in ("1", "0"):
            os.environ["THRILL_TPU_LOOP_REPLAY"] = replay
            job()                                # warm: compile+cache
            n0 = len(mex.loop_reports)
            t0 = time.perf_counter()
            results[replay] = np.asarray(job(), dtype=np.float64)
            wall[replay] = time.perf_counter() - t0
            if replay == "1":
                reps = [r for r in mex.loop_reports[n0:]
                        if r["name"] == name]
                report = reps[-1] if reps else None
    finally:
        # restore the caller's setting even when a leg raises (the
        # module-level pop in main() only covered the clean path)
        if prev is None:
            os.environ.pop("THRILL_TPU_LOOP_REPLAY", None)
        else:
            os.environ["THRILL_TPU_LOOP_REPLAY"] = prev
    assert np.array_equal(results["1"], results["0"]), \
        f"{name}: replayed and per-iteration results diverge"
    return (name, report, wall["1"], wall["0"])


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pages", type=int, default=1024)
    ap.add_argument("--edges", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--points", type=int, default=8192)
    ap.add_argument("--clusters", type=int, default=16)
    args = ap.parse_args()

    import numpy as np
    from thrill_tpu.api import Context
    from thrill_tpu.parallel.mesh import MeshExec

    mex = MeshExec()
    ctx = Context(mex)
    _examples_path()
    import k_means as km
    import page_rank as pr

    edges = pr.zipf_graph(args.pages, args.edges)
    rng = np.random.default_rng(7)
    points = rng.normal(size=(args.points, 8))

    rows = [
        _measure("page_rank",
                 lambda: pr.page_rank(ctx, edges, args.pages,
                                      iterations=args.iters), mex),
        _measure("k_means",
                 lambda: km.k_means(ctx, points, args.clusters,
                                    iterations=args.iters), mex),
    ]
    print(f"{'loop':<10} {'iters':>5} {'hit':>5} {'plans':>5} "
          f"{'fori':>5} {'donatedB':>9} {'capture_s':>10} "
          f"{'replay_s':>9} {'wall':>7} {'noreplay':>9}")
    for name, r, w1, w0 in rows:
        if r is None:
            print(f"{name:<10} (no LoopPlan captured — see "
                  f"event=loop_capture_miss)")
            continue
        hit = (r["replays"] + r["fori_iters"]) / max(r["iters"], 1)
        print(f"{name:<10} {r['iters']:>5} {hit:>5.0%} "
              f"{r['captures']:>5} {r['fori_iters']:>5} "
              f"{r['donated_bytes']:>9} {r['capture_s']:>10.4f} "
              f"{r['replay_s']:>9.4f} {w1:>7.3f} {w0:>9.3f}")
    stats = ctx.overall_stats()
    print(f"\nprocess totals: {stats['loop_plan_builds']} plan builds, "
          f"{stats['loop_replays']} replays + "
          f"{stats['loop_fori_iters']} fori iters, "
          f"{stats['loop_replay_fallbacks']} fallbacks, "
          f"{stats['loop_donated_bytes']} B donated")
    ctx.close()


if __name__ == "__main__":
    main()
