"""Render the plan observatory from JSON event logs.

The offline twin of ``ctx.explain()`` (common/decisions.py): rebuilds
the physical-plan tree from ``node_execute_start`` / ``node_fused``
events, joins every ``event=decision`` record with its
``event=decision_audit`` line, and prints the annotated tree plus the
audited accuracy ledger (per-kind mean |log2(predicted/actual)| and
the worst-audited sites). Usage:

    python -m thrill_tpu.tools.plan_report LOG.json [LOG2.json ...]

Multiple logs (one per host of a multi-controller run) merge on the
shared timestamp axis; decision seqs are joined per host (each host's
ledger numbers its own records).
"""

from __future__ import annotations

import sys
from typing import Dict, List, Tuple

from ..common.decisions import render_accuracy, render_plan
from ..common.stats import Aggregate
from .json2profile import load_many


def collect(events: List[dict]) -> Tuple[List[dict], List[dict]]:
    """(nodes, decisions) in render_plan's input form."""
    nodes: Dict[int, dict] = {}
    for e in events:
        ev = e.get("event")
        if ev in ("node_execute_start", "node_fused"):
            nid = e.get("dia_id")
            if nid is None:
                continue
            n = nodes.setdefault(int(nid), {"id": int(nid)})
            n["label"] = e.get("node", "?")
            n["parents"] = [int(p) for p in (e.get("parents") or ())]
            n["state"] = "FUSED" if ev == "node_fused" else "EXECUTED"
    decisions: List[dict] = []
    by_seq: Dict[Tuple[int, int], dict] = {}
    for e in events:
        ev = e.get("event")
        if ev == "decision":
            d = dict(e)
            decisions.append(d)
            if "seq" in e:
                by_seq[(e.get("host", 0), e["seq"])] = d
        elif ev == "decision_audit" and "seq" in e:
            d = by_seq.get((e.get("host", 0), e["seq"]))
            if d is not None:
                for k in ("actual", "err_log2", "verdict"):
                    if e.get(k) is not None:
                        d[k] = e[k]
    return list(nodes.values()), decisions


def accuracy_of(decisions: List[dict]) -> Tuple[dict, List[dict]]:
    """Recompute the per-kind accuracy ledger and worst-site table
    from joined decision dicts (the offline form of
    ``DecisionLedger.accuracy`` / ``worst_sites``)."""
    acc: Dict[str, Aggregate] = {}
    counts: Dict[str, int] = {}
    joined: Dict[str, int] = {}
    site_err: Dict[Tuple[str, str], List[float]] = {}
    for d in decisions:
        kind = d.get("kind", "?")
        counts[kind] = counts.get(kind, 0) + 1
        if d.get("verdict") is None:
            continue
        joined[kind] = joined.get(kind, 0) + 1
        err = d.get("err_log2")
        if err is None:
            continue
        acc.setdefault(kind, Aggregate()).add(abs(err))
        se = site_err.setdefault((kind, d.get("site", "?")), [0, 0.0])
        se[0] += 1
        se[1] += abs(err)
    table = {}
    for kind, n in sorted(counts.items()):
        agg = acc.get(kind)
        table[kind] = {
            "n": n, "joined": joined.get(kind, 0),
            "mae_log2": round(agg.mean, 4) if agg is not None else None,
            "stdev_log2": round(agg.stdev, 4)
            if agg is not None else None}
    worst = [{"kind": k, "site": s, "n": n,
              "mae_log2": round(tot / n, 4)}
             for (k, s), (n, tot) in site_err.items() if n]
    worst.sort(key=lambda r: -r["mae_log2"])
    return table, worst[:5]


def render(events: List[dict]) -> str:
    nodes, decisions = collect(events)
    workers = next((e.get("workers") for e in events
                    if e.get("workers") is not None), None)
    out = [render_plan(nodes, decisions, W=workers,
                       title="plan report")]
    table, worst = accuracy_of(decisions)
    if table:
        out.append("")
        out.append(render_accuracy(table, worst))
    else:
        out.append("\n(no event=decision lines in this log — run with "
                   "THRILL_TPU_DECISIONS=1 and THRILL_TPU_LOG set)")
    return "\n".join(out)


def main() -> None:
    if len(sys.argv) < 2:
        print(__doc__, file=sys.stderr)
        raise SystemExit(2)
    print(render(load_many(sys.argv[1:])))


if __name__ == "__main__":
    main()
