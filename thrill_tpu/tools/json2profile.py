"""Render a JSON event log into a standalone HTML timeline report.

Equivalent of the reference's misc/json2profile.cpp (1.5k LoC C++ that
parses JsonLogger output into an HTML report with CPU/net/disk/stage
timelines). Usage:

    python -m thrill_tpu.tools.json2profile LOG.json > report.html
"""

from __future__ import annotations

import html
import json
import sys
from typing import List


def load_events(path: str) -> List[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return events


def render_html(events: List[dict]) -> str:
    nodes = {}
    profiles = []
    exchanges = []
    memory = []        # hbm_spill / hbm_restore / mem_negotiate / demotion
    t0 = min((e["ts"] for e in events), default=0)
    for e in events:
        t = (e["ts"] - t0) / 1e6
        if e.get("event") == "node_execute_start":
            nodes.setdefault(e.get("dia_id"), {}).update(
                start=t, label=e.get("node"))
        elif e.get("event") == "node_execute_done":
            nodes.setdefault(e.get("dia_id"), {}).update(
                end=t, items=e.get("items"))
        elif e.get("event") == "profile":
            profiles.append((t, e))
        elif e.get("event") == "exchange":
            exchanges.append((t, e))
        elif e.get("event") in ("hbm_spill", "hbm_restore",
                                "mem_negotiate", "device_to_host"):
            memory.append((t, e))

    rows = []
    for nid in sorted(k for k in nodes if k is not None):
        n = nodes[nid]
        if "start" not in n or "end" not in n:
            continue
        dur = n["end"] - n["start"]
        rows.append((nid, n.get("label", "?"), n["start"], dur,
                     n.get("items")))
    total = max((r[2] + r[3] for r in rows), default=1.0)

    bars = []
    for nid, label, start, dur, items in rows:
        left = 100.0 * start / total
        width = max(100.0 * dur / total, 0.2)
        bars.append(
            f'<div class="row"><span class="lbl">#{nid} '
            f'{html.escape(str(label))}</span>'
            f'<div class="track"><div class="bar" style="left:{left:.2f}%;'
            f'width:{width:.2f}%"></div></div>'
            f'<span class="dur">{dur * 1e3:.1f} ms'
            f'{f" · {items} items" if items is not None else ""}</span>'
            f'</div>')

    cpu_pts = [(t, e.get("cpu_util")) for t, e in profiles
               if e.get("cpu_util") is not None]
    cpu_line = ""
    if cpu_pts:
        pts = " ".join(f"{100 * t / total:.2f},{40 - 40 * u:.1f}"
                       for t, u in cpu_pts)
        cpu_line = (f'<h2>host CPU utilization</h2>'
                    f'<svg viewBox="0 0 100 40" class="cpu">'
                    f'<polyline fill="none" stroke="#07c" stroke-width="0.5"'
                    f' points="{pts}"/></svg>')

    return f"""<!doctype html><html><head><meta charset="utf-8">
<title>thrill_tpu profile</title><style>
body {{ font: 13px monospace; margin: 2em; }}
.row {{ display: flex; align-items: center; margin: 2px 0; }}
.lbl {{ width: 22em; }}
.track {{ position: relative; flex: 1; height: 14px; background: #eee; }}
.bar {{ position: absolute; top: 0; height: 100%; background: #07c; }}
.mark {{ position: absolute; top: 0; height: 100%; background: #e60; }}
.dur {{ width: 16em; text-align: right; color: #666; }}
.cpu {{ width: 100%; height: 80px; background: #f7f7f7; }}
.vol {{ width: 100%; height: 120px; background: #f7f7f7; }}
</style></head><body>
<h1>thrill_tpu execution profile</h1>
<p>{len(rows)} executed nodes, total span {total:.3f}s,
{len(profiles)} profile samples, {len(exchanges)} exchanges</p>
<h2>stage timeline</h2>
{''.join(bars)}
{_render_exchange_volume(exchanges, total)}
{_render_worker_lanes(exchanges, total)}
{_render_memory_events(memory, total)}
{cpu_line}
</body></html>"""


def _render_memory_events(memory, total: float) -> str:
    """Memory-pressure timeline: HBM spills/restores, device->host
    demotions and negotiation grants as ticks on one lane each
    (reference: BlockPool occupancy in the profile report)."""
    if not memory:
        return ""
    kinds = ["hbm_spill", "hbm_restore", "device_to_host",
             "mem_negotiate"]
    lanes = []
    for kind in kinds:
        evs = [(t, e) for t, e in memory if e.get("event") == kind]
        if not evs:
            continue
        vol = sum(e.get("bytes", 0) or 0 for _, e in evs)
        marks = "".join(
            f'<div class="mark" style="left:{100 * t / total:.2f}%;'
            f'width:0.4%;height:100%"></div>' for t, _ in evs)
        extra = f" · {vol / 1e6:.1f} MB" if vol else ""
        lanes.append(
            f'<div class="row"><span class="lbl">{kind}</span>'
            f'<div class="track">{marks}</div>'
            f'<span class="dur">{len(evs)} events{extra}</span></div>')
    if not lanes:
        return ""
    return "<h2>memory pressure</h2>" + "".join(lanes)


def _render_exchange_volume(exchanges, total: float) -> str:
    """Cumulative cross-worker bytes over time, with the DCN share as a
    second line on multi-slice meshes."""
    if not exchanges:
        return ""
    cum = cum_dcn = 0
    pts, pts_dcn = [(0.0, 0)], [(0.0, 0)]
    for t, e in exchanges:
        cum += e.get("bytes", 0)
        cum_dcn += e.get("bytes_dcn", 0)
        pts.append((t, cum))
        pts_dcn.append((t, cum_dcn))
    top = max(cum, 1)

    def line(p, color):
        s = " ".join(f"{100 * t / total:.2f},{118 - 110 * v / top:.1f}"
                     for t, v in p)
        return (f'<polyline fill="none" stroke="{color}" '
                f'stroke-width="0.6" points="{s}"/>')

    dcn = line(pts_dcn, "#e60") if cum_dcn else ""
    return (f'<h2>exchange volume (cumulative {cum / 1e6:.1f} MB'
            f'{f", DCN {cum_dcn / 1e6:.1f} MB" if cum_dcn else ""})</h2>'
            f'<svg viewBox="0 0 100 120" class="vol" '
            f'preserveAspectRatio="none">{line(pts, "#07c")}{dcn}</svg>')


def _render_worker_lanes(exchanges, total: float) -> str:
    """One lane per worker: each exchange draws a tick whose height is
    that worker's share of the shipped items (send side) — skew between
    lanes is load imbalance in the data plane."""
    pairs = [(t, e["per_worker_sent"]) for t, e in exchanges
             if e.get("per_worker_sent")]
    if not pairs:
        return ""
    W = max(len(p) for _, p in pairs)
    # tolerate appended logs from runs with different worker counts
    pairs = [(t, p) for t, p in pairs if len(p) == W]
    peak = max((max(p) for _, p in pairs), default=1) or 1
    lanes = []
    for w in range(W):
        sent_total = sum(p[w] for _, p in pairs)
        marks = []
        for t, p in pairs:
            h = max(100.0 * p[w] / peak, 2.0) if p[w] else 0.0
            if h:
                marks.append(
                    f'<div class="mark" style="left:'
                    f'{100 * t / total:.2f}%;width:0.4%;height:{h:.0f}%;'
                    f'top:{100 - h:.0f}%"></div>')
        lanes.append(
            f'<div class="row"><span class="lbl">worker {w}</span>'
            f'<div class="track">{"".join(marks)}</div>'
            f'<span class="dur">{sent_total} items sent</span></div>')
    return "<h2>per-worker exchange lanes</h2>" + "".join(lanes)


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: json2profile LOG.json > report.html", file=sys.stderr)
        sys.exit(2)
    sys.stdout.write(render_html(load_events(sys.argv[1])))


if __name__ == "__main__":
    main()
