"""Render JSON event logs into a standalone HTML timeline report.

Equivalent of the reference's misc/json2profile.cpp (the HTML report
with CPU/net/disk/stage timelines). Sections: stage timeline, stage
summary table (duration/items/rate/per-worker balance), stage x worker
item matrix, exchange volume, per-worker exchange lanes, memory
pressure, host CPU + RAM + HBM overlay. Usage:

    python -m thrill_tpu.tools.json2profile LOG.json [LOG2.json ...] \
        > report.html

Multiple logs (one per host of a multi-controller run) merge on the
shared timestamp axis; per-host samples are tagged by file order.
"""

from __future__ import annotations

import html
import json
import sys
from typing import List


def load_events(path: str, host: int = 0) -> List[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    e = json.loads(line)
                    e.setdefault("host", host)
                    events.append(e)
                except json.JSONDecodeError:
                    continue
    return events


def load_many(paths: List[str]) -> List[dict]:
    events = []
    for h, p in enumerate(paths):
        events.extend(load_events(p, host=h))
    events.sort(key=lambda e: e.get("ts", 0))
    return events


def _merge_host_nodes(per_host: dict) -> dict:
    """Merge per-(dia_id, host) node records into one per dia_id.

    Every controller of a multi-host run logs the same stages, so the
    records must MERGE, not overwrite: span = [min start, max end];
    counts that agree on every host are one replicated global value
    (device-path stages), disagreeing counts are per-host partials
    (host-storage stages hold only local workers' items) and sum."""
    merged: dict = {}
    for nid, by_host in per_host.items():
        m: dict = {}
        starts = [d["start"] for d in by_host.values() if "start" in d]
        ends = [d["end"] for d in by_host.values() if "end" in d]
        if starts:
            m["start"] = min(starts)
        if ends:
            m["end"] = max(ends)
        labels = [d.get("label") for d in by_host.values()
                  if d.get("label")]
        if labels:
            m["label"] = labels[0]
        items = [d["items"] for d in by_host.values()
                 if d.get("items") is not None]
        pws = [d["per_worker"] for d in by_host.values()
               if d.get("per_worker")]
        # ONE replicated-vs-partial decision for both count fields: the
        # per-worker split is the more discriminating signal (per-host
        # partials can coincide in total while owning different
        # workers), fall back to the scalar only without it
        if pws:
            replicated = all(p == pws[0] for p in pws)
        elif items:
            replicated = all(x == items[0] for x in items)
        else:
            replicated = True
        if items:
            m["items"] = items[0] if replicated else sum(items)
        if pws:
            if replicated:
                m["per_worker"] = pws[0]
            else:
                W = max(len(p) for p in pws)
                m["per_worker"] = [
                    sum(p[w] if w < len(p) else 0 for p in pws)
                    for w in range(W)]
        merged[nid] = m
    return merged


def render_html(events: List[dict]) -> str:
    per_host_nodes: dict = {}
    profiles = []
    exchanges = []
    fused = []         # fused_dispatch (api/fusion.py program stitching)
    jobs = []          # job_submit / job_done (service/scheduler.py)
    loops = []         # iteration / loop_* (api/loop.py LoopPlan replay)
    ckpt = []          # checkpoint / ckpt_restore / resume (durability)
    overall = []       # overall_stats summary lines
    device_xchg: dict = {}   # host -> ordered device-plane exchanges
    memory = []        # hbm_spill / hbm_restore / mem_negotiate / demotion
    io_events = []     # prefetch / writeback / restore_overlap (ISSUE 13)
    faults = []        # fault_injected / retry / recovery / abort
    decisions = []     # decision / decision_audit (common/decisions.py)
    t0 = min((e["ts"] for e in events), default=0)
    for e in events:
        t = (e["ts"] - t0) / 1e6
        h = e.get("host", 0)
        if e.get("event") == "node_execute_start":
            per_host_nodes.setdefault(e.get("dia_id"), {}).setdefault(
                h, {}).update(start=t, label=e.get("node"))
        elif e.get("event") == "node_execute_done":
            per_host_nodes.setdefault(e.get("dia_id"), {}).setdefault(
                h, {}).update(end=t, items=e.get("items"),
                              per_worker=e.get("per_worker"))
        elif e.get("event") == "profile":
            profiles.append((t, e))
        elif e.get("event") == "exchange":
            # device-plane exchanges log GLOBAL bytes (derived from the
            # replicated send matrix) in the same deterministic order
            # on every controller: keep ONE host's sequence — the most
            # complete one, so a truncated host-0 log cannot hide
            # exchanges other hosts recorded
            device_xchg.setdefault(h, []).append((t, e))
        elif e.get("event") == "host_exchange":
            # host-plane counters are per-process partials: keep all
            exchanges.append((t, e))
        elif e.get("event") in ("hbm_spill", "hbm_restore",
                                "mem_negotiate", "device_to_host",
                                "host_replicate", "mem_spill",
                                "oom_retry", "segment_split"):
            memory.append((t, e))
        elif e.get("event") in ("prefetch", "writeback",
                                "restore_overlap"):
            io_events.append((t, e))
        elif e.get("event") in ("fault_injected", "retry", "recovery",
                                "abort", "pipeline_abort", "heal"):
            # the abort/heal lane: scoped pipeline failures and their
            # generation heals render chronologically alongside the
            # faults that caused them (reconnects arrive as
            # event=recovery what=net.reconnect)
            faults.append((t, e))
        elif e.get("event") == "fused_dispatch":
            fused.append(e)
        elif e.get("event") in ("job_submit", "job_done",
                                "plan_store_load", "plan_store_save"):
            jobs.append((t, e))
        elif e.get("event") in ("iteration", "loop_replay", "loop_plan",
                                "loop_capture_miss",
                                "loop_replay_fallback", "loop_done",
                                "loop_fori_unavailable"):
            loops.append((t, e))
        elif e.get("event") in ("checkpoint", "ckpt_restore", "resume"):
            ckpt.append((t, e))
        elif e.get("event") in ("decision", "decision_audit"):
            decisions.append(e)
        elif e.get("event") == "overall_stats":
            overall.append(e)
    if device_xchg:
        best = max(sorted(device_xchg), key=lambda h: len(device_xchg[h]))
        exchanges.extend(device_xchg[best])
        exchanges.sort(key=lambda te: te[0])
    nodes = _merge_host_nodes(per_host_nodes)

    rows = []
    for nid in sorted(k for k in nodes if k is not None):
        n = nodes[nid]
        if "start" not in n or "end" not in n:
            continue
        dur = n["end"] - n["start"]
        rows.append((nid, n.get("label", "?"), n["start"], dur,
                     n.get("items")))
    total = max((r[2] + r[3] for r in rows), default=1.0)

    bars = []
    for nid, label, start, dur, items in rows:
        left = 100.0 * start / total
        width = max(100.0 * dur / total, 0.2)
        bars.append(
            f'<div class="row"><span class="lbl">#{nid} '
            f'{html.escape(str(label))}</span>'
            f'<div class="track"><div class="bar" style="left:{left:.2f}%;'
            f'width:{width:.2f}%"></div></div>'
            f'<span class="dur">{dur * 1e3:.1f} ms'
            f'{f" · {items} items" if items is not None else ""}</span>'
            f'</div>')

    return f"""<!doctype html><html><head><meta charset="utf-8">
<title>thrill_tpu profile</title><style>
body {{ font: 13px monospace; margin: 2em; }}
.row {{ display: flex; align-items: center; margin: 2px 0; }}
.lbl {{ width: 22em; }}
.track {{ position: relative; flex: 1; height: 14px; background: #eee; }}
.bar {{ position: absolute; top: 0; height: 100%; background: #07c; }}
.mark {{ position: absolute; top: 0; height: 100%; background: #e60; }}
.dur {{ width: 16em; text-align: right; color: #666; }}
.cpu {{ width: 100%; height: 80px; background: #f7f7f7; }}
.vol {{ width: 100%; height: 120px; background: #f7f7f7; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #ccc; padding: 2px 8px; text-align: right; }}
th {{ background: #eee; }}
td.l, th.l {{ text-align: left; }}
td.hm {{ min-width: 3em; }}
</style></head><body>
<h1>thrill_tpu execution profile</h1>
<p>{len(rows)} executed nodes, total span {total:.3f}s,
{len(profiles)} profile samples, {len(exchanges)} exchanges</p>
<h2>stage timeline</h2>
{''.join(bars)}
{_render_stage_table(rows, exchanges, nodes)}
{_render_stage_worker_matrix(nodes)}
{_render_exchange_volume(exchanges, total)}
{_render_overlap_lane(exchanges, overall, total)}
{_render_wire_lane(overall)}
{_render_worker_lanes(exchanges, total)}
{_render_skew_lane(exchanges, overall)}
{_render_memory_events(memory, total)}
{_render_io_lane(io_events, overall)}
{_render_fused_dispatches(fused, overall)}
{_render_decisions(decisions, overall)}
{_render_service_jobs(jobs, overall, total)}
{_render_loop_iterations(loops, overall)}
{_render_checkpoint_events(ckpt, overall)}
{_render_fault_events(faults)}
{_render_host_overlay(profiles, total)}
</body></html>"""


def _render_fused_dispatches(fused, overall) -> str:
    """Program-stitching table: per-stage fused-op lists with launch
    counts, and the fused-vs-unfused dispatch budget. Each fused
    dispatch carrying k ops saved k-1 link round trips versus the
    per-op dispatch model (THRILL_TPU_FUSE=0), so the 'saved' column
    IS the dispatch delta the fusion planner bought."""
    if not fused and not overall:
        return ""
    by_stage: dict = {}
    for e in fused:
        ops = tuple(e.get("ops") or ())
        by_stage[ops] = by_stage.get(ops, 0) + 1
    rows = []
    tot_disp = tot_ops = 0
    for ops, n in sorted(by_stage.items(),
                         key=lambda kv: -kv[1] * len(kv[0])):
        tot_disp += n
        tot_ops += n * len(ops)
        rows.append(
            f"<tr><td class=l>{html.escape(' + '.join(ops))}</td>"
            f"<td>{len(ops)}</td><td>{n}</td>"
            f"<td>{n * (len(ops) - 1)}</td></tr>")
    summary = ""
    if overall:
        o = overall[-1]
        fd = o.get("fused_dispatches", tot_disp)
        fo = o.get("fused_ops", tot_ops)
        dd = o.get("device_dispatches")
        summary = (f"<p>device dispatches: <b>{dd}</b> total, "
                   f"{fd} launched by the fusion runner carrying "
                   f"{fo} DOp segments (unfused they would have cost "
                   f"{(dd or 0) + max(fo - fd, 0)} dispatches)</p>")
    elif tot_disp:
        summary = (f"<p>{tot_disp} fused dispatches carrying "
                   f"{tot_ops} DOp segments "
                   f"({tot_ops - tot_disp} dispatches saved)</p>")
    return f"""
<h2>fused dispatches (program stitching)</h2>
{summary}
<table><tr><th class=l>stage composition</th><th>ops</th>
<th>dispatches</th><th>saved</th></tr>{''.join(rows)}</table>"""


def _render_decisions(decisions, overall) -> str:
    """Plan-observatory lane (common/decisions.py): chosen-strategy
    counts per decision kind, the optimistic exchange's hit/heal
    record, and the top-5 worst-audited sites by mean
    |log2(predicted/actual)| — where the cost model lies the most."""
    if not decisions:
        return ""
    chosen: dict = {}
    hits = misses = 0
    site_err: dict = {}
    joined = 0
    for e in decisions:
        if e.get("event") == "decision":
            key = (e.get("kind", "?"), e.get("chosen", "?"))
            chosen[key] = chosen.get(key, 0) + 1
            continue
        joined += 1
        if e.get("verdict") == "hit":
            hits += 1
        elif e.get("verdict") == "miss":
            misses += 1
        err = e.get("err_log2")
        if err is not None:
            se = site_err.setdefault(
                (e.get("kind", "?"), e.get("site", "?")), [0, 0.0])
            se[0] += 1
            se[1] += abs(err)
    rows = [f"<tr><td class=l>{html.escape(kind)}</td>"
            f"<td class=l>{html.escape(str(ch))}</td><td>{n}</td></tr>"
            for (kind, ch), n in sorted(chosen.items(),
                                        key=lambda kv: -kv[1])]
    n_dec = sum(chosen.values())
    summary = (f"<p>{n_dec} decisions recorded, {joined} with joined "
               f"actuals; optimistic-exchange audit: {hits} hits, "
               f"{misses} misses healed</p>")
    if overall:
        acc = overall[-1].get("decision_accuracy") or {}
        if isinstance(acc, dict) and acc:
            summary += ("<p>accuracy (mean |log2 pred/actual|): "
                        + ", ".join(f"{html.escape(str(k))}={v}"
                                    for k, v in sorted(acc.items()))
                        + "</p>")
    worst = [(k, s, n, tot / n)
             for (k, s), (n, tot) in site_err.items() if n]
    worst.sort(key=lambda r: -r[3])
    wrows = [f"<tr><td class=l>{html.escape(k)}</td>"
             f"<td class=l>{html.escape(s)}</td><td>{n}</td>"
             f"<td>{mae:.3f}</td></tr>"
             for k, s, n, mae in worst[:5]]
    wtable = ""
    if wrows:
        wtable = (f"<h3>worst-audited sites</h3>"
                  f"<table><tr><th class=l>kind</th>"
                  f"<th class=l>site</th><th>joins</th>"
                  f"<th>mae log2</th></tr>{''.join(wrows)}</table>")
    return f"""
<h2>plan decisions (decision ledger)</h2>
{summary}
<table><tr><th class=l>kind</th><th class=l>chosen</th>
<th>count</th></tr>{''.join(rows)}</table>
{wtable}"""


def _render_service_jobs(jobs, overall, total: float) -> str:
    """Per-job service timeline (service/scheduler.py): one row per
    submitted job — queue wait rendered as the orange span, execution
    as the blue one — plus the admission counters and plan-store
    events, so serving latency decomposes visually into waiting vs
    running the way the stage timeline decomposes a single pipeline."""
    if not jobs:
        return ""
    # pair job_submit/job_done by job id (per host)
    by_id: dict = {}
    store_rows = []
    for t, e in jobs:
        if e.get("event") == "job_submit":
            by_id.setdefault((e.get("host", 0), e.get("job")),
                             {})["submit"] = (t, e)
        elif e.get("event") == "job_done":
            by_id.setdefault((e.get("host", 0), e.get("job")),
                             {})["done"] = (t, e)
        else:
            store_rows.append(
                f"<tr><td>{t:8.3f}s</td><td class=l>"
                f"{html.escape(str(e.get('event')))}</td><td class=l>"
                f"{html.escape(str(e.get('path', '')))}</td>"
                f"<td>{e.get('entries', '')}</td></tr>")
    bars = []
    rows = []
    for (h, jid), rec in sorted(by_id.items(),
                                key=lambda kv: kv[1].get(
                                    "submit", kv[1].get("done"))[0]):
        sub = rec.get("submit")
        done = rec.get("done")
        t0 = sub[0] if sub else (done[0] - (done[1].get("run_s") or 0)
                                 - (done[1].get("queue_wait_s") or 0))
        e = done[1] if done else sub[1]
        wait = float(e.get("queue_wait_s") or 0)
        run = float(e.get("run_s") or 0)
        name = e.get("name") or f"job-{jid}"
        tenant = e.get("tenant") or "?"
        ok = e.get("ok")
        span = max(total, 1e-9)
        left = 100.0 * t0 / span
        ww = max(100.0 * wait / span, 0.1)
        rw = max(100.0 * run / span, 0.1)
        bars.append(
            f'<div class="row"><span class="lbl">{html.escape(str(name))}'
            f' [{html.escape(str(tenant))}]</span>'
            f'<div class="track">'
            f'<div class="mark" style="left:{left:.2f}%;width:{ww:.2f}%">'
            f'</div>'
            f'<div class="bar" style="left:{left + ww:.2f}%;'
            f'width:{rw:.2f}%"></div></div>'
            f'<span class="dur">{wait * 1e3:.1f} ms queued · '
            f'{run * 1e3:.1f} ms run'
            f'{" · FAILED" if ok is False else ""}</span></div>')
        rows.append(
            f"<tr><td>{t0:8.3f}s</td><td class=l>"
            f"{html.escape(str(name))}</td><td class=l>"
            f"{html.escape(str(tenant))}</td>"
            f"<td>{wait * 1e3:.1f}</td><td>{run * 1e3:.1f}</td>"
            f"<td class=l>{'ok' if ok else html.escape(str(e.get('error') or ('?' if ok is None else 'failed')))}"
            f"</td><td>{e.get('generation', '')}</td></tr>")
    summary = ""
    if overall:
        o = overall[-1]
        if o.get("jobs_submitted") is not None:
            peaks = o.get("tenant_hbm_peaks") or {}
            peak_s = ", ".join(f"{t}: {b}" for t, b in
                               sorted(peaks.items())) or "none"
            summary = (
                f"<p><b>{o.get('jobs_submitted')}</b> jobs submitted, "
                f"{o.get('jobs_failed')} failed, queue depth peak "
                f"{o.get('queue_depth_peak')}; plan builds "
                f"{o.get('plan_builds')}, plan-store hits "
                f"{o.get('plan_store_hits')}; tenant HBM peaks: "
                f"{html.escape(peak_s)}</p>")
    store_tbl = ""
    if store_rows:
        store_tbl = (f"<table><tr><th class=l>t</th><th class=l>event"
                     f"</th><th class=l>path</th><th>entries</th></tr>"
                     f"{''.join(store_rows)}</table>")
    return f"""
<h2>service jobs (queue wait + run)</h2>
{summary}
{''.join(bars)}
<table><tr><th class=l>t</th><th class=l>job</th><th class=l>tenant</th>
<th>wait ms</th><th>run ms</th><th class=l>outcome</th>
<th>gen</th></tr>{''.join(rows)}</table>
{store_tbl}"""


def _render_loop_iterations(loops, overall) -> str:
    """Iteration timeline (api/loop.py): one row per loop iteration —
    capture/plain/replay/fori mode, dispatches issued, wall seconds —
    plus plan-build/capture-miss/fallback markers and the loop_done
    summaries, so replay hit rate and donated HBM are visible next to
    the dispatch budget they bought."""
    if not loops:
        return ""
    trs = []
    for t, e in loops:
        kind = e.get("event")
        loop = e.get("loop") or e.get("name") or ""
        if kind == "iteration":
            row = (e.get("mode", "plain"), e.get("iter"),
                   e.get("dispatches"), e.get("seconds"))
        elif kind == "loop_replay":
            mode = "fori" if e.get("fori") else "replay"
            it = e.get("iter")
            if e.get("iters"):
                it = f"{it}..{it + e['iters'] - 1}"
            row = (mode, it, e.get("dispatches", 1), e.get("seconds"))
        elif kind == "loop_done":
            hit = ((e.get("replays", 0) + e.get("fori_iters", 0))
                   / max(e.get("iters", 1), 1))
            row = (f"done: {e.get('iters')} iters, "
                   f"{e.get('captures')} captures, "
                   f"replay hit {hit:.0%}, "
                   f"{e.get('fallbacks')} fallbacks, "
                   f"{e.get('donated_bytes', 0)} B donated",
                   "", "", round(e.get("capture_s", 0)
                                 + e.get("replay_s", 0), 4))
        elif kind == "loop_plan":
            row = (f"plan: {e.get('calls')} calls, "
                   f"{e.get('pruned_invariant')} invariant + "
                   f"{e.get('pruned_dead')} dead pruned, "
                   f"{e.get('donatable')} donatable"
                   f"{', fori' if e.get('fori') else ''}", "", "", "")
        else:
            row = (f"{kind}: "
                   f"{e.get('reason') or e.get('error') or ''}",
                   e.get("iter", ""), "", "")
        mode, it, disp, secs = row
        trs.append(f"<tr><td class=l>{t:8.3f}s</td>"
                   f"<td class=l>{html.escape(str(loop))}</td>"
                   f"<td class=l>{html.escape(str(mode))}</td>"
                   f"<td>{it}</td><td>{disp if disp is not None else ''}"
                   f"</td><td>{secs if secs is not None else ''}</td>"
                   f"</tr>")
    summary = ""
    if overall:
        o = overall[-1]
        if o.get("loop_plan_builds") is not None:
            summary = (f"<p>loop plans built: "
                       f"<b>{o.get('loop_plan_builds')}</b>, "
                       f"replayed iterations: {o.get('loop_replays')}"
                       f" + {o.get('loop_fori_iters')} in whole-loop "
                       f"fori dispatches, "
                       f"{o.get('loop_replay_fallbacks')} fallbacks, "
                       f"{o.get('loop_donated_bytes')} bytes of "
                       f"loop-carry HBM donated</p>")
    return f"""
<h2>iteration timeline (loop replay)</h2>
{summary}
<table><tr><th class=l>t</th><th class=l>loop</th><th class=l>mode</th>
<th>iter</th><th>dispatches</th><th>seconds</th></tr>{''.join(trs)}
</table>"""


def _render_checkpoint_events(ckpt, overall) -> str:
    """Durability timeline (api/checkpoint.py): every epoch commit,
    resume decision, and restore, with the overall checkpoint/recovery
    counters — rendered alongside the fused-dispatch table so the cost
    of durability sits next to the dispatch budget it rides on."""
    if not ckpt and not (overall and any(
            "checkpoint_epochs" in o for o in overall)):
        return ""
    trs = []
    for t, e in ckpt:
        kind = e.get("event")
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(e.items())
            if k not in ("ts", "event", "host"))
        trs.append(
            f'<tr><td>{t * 1e3:.1f}</td>'
            f'<td class="l">{html.escape(str(kind))}</td>'
            f'<td class="l">{html.escape(detail)}</td></tr>')
    summary = ""
    if overall:
        o = overall[-1]
        if "checkpoint_epochs" in o:
            summary = (
                f"<p>{o.get('checkpoint_epochs', 0)} epochs committed, "
                f"{o.get('ckpt_bytes_written', 0)} bytes sealed; resume "
                f"skipped {o.get('resume_skipped_ops', 0)} ops in "
                f"{o.get('recovery_time_s', 0)}s of recovery</p>")
    if not trs and not summary:
        return ""
    return f"""
<h2>checkpoint &amp; recovery</h2>
{summary}
<table><tr><th>ms</th><th class="l">event</th>
<th class="l">detail</th></tr>{''.join(trs)}</table>"""


def _render_fault_events(faults) -> str:
    """Robustness-layer timeline: every injected fault, retry sleep,
    recovery and coordinated abort as a chronological table (the
    observability half of the fault-injection harness in
    common/faults.py)."""
    if not faults:
        return ""
    trs = []
    for t, e in faults:
        what = e.get("site") or e.get("what") or ""
        detail = ", ".join(
            f"{k}={v}" for k, v in sorted(e.items())
            if k not in ("ts", "event", "site", "what", "host",
                         "program", "workers"))
        trs.append(
            f'<tr><td>{t * 1e3:.1f}</td>'
            f'<td class="l">{html.escape(str(e.get("event")))}</td>'
            f'<td class="l">{html.escape(str(what))}</td>'
            f'<td class="l">{html.escape(detail)}</td></tr>')
    counts = {}
    for _, e in faults:
        counts[e.get("event")] = counts.get(e.get("event"), 0) + 1
    head = ", ".join(f"{v} {k}" for k, v in sorted(counts.items()))
    return (f'<h2>faults &amp; recovery ({html.escape(head)})</h2>'
            '<table><tr><th>ms</th><th class="l">event</th>'
            '<th class="l">site</th><th class="l">detail</th></tr>'
            + "".join(trs) + "</table>")


def _render_stage_table(rows, exchanges, nodes) -> str:
    """Per-stage summary (reference: the stage table of
    misc/json2profile.cpp): duration, items, throughput, bytes shipped
    by exchanges during the stage, and worker balance (max/mean of the
    per-worker item counts — 1.0 is perfectly even)."""
    if not rows:
        return ""
    # attribute each exchange to exactly ONE stage: merged multi-host
    # records widen stage spans until they overlap, and summing every
    # exchange into every covering window counted the same bytes in
    # multiple rows. The tightest (latest-starting) covering stage wins.
    per_stage_bytes: dict = {}
    for t, e in exchanges:
        best = None
        for nid, _label, start, dur, _items in rows:
            if start <= t <= start + dur and (
                    best is None or (start, -dur) > (best[1], -best[2])):
                best = (nid, start, dur)
        if best is not None:
            per_stage_bytes[best[0]] = (per_stage_bytes.get(best[0], 0)
                                        + (e.get("bytes", 0) or 0))
    trs = []
    for nid, label, start, dur, items in rows:
        xb = per_stage_bytes.get(nid, 0)
        rate = f"{items / dur / 1e6:.2f}" if items and dur > 0 else ""
        pw = nodes.get(nid, {}).get("per_worker")
        bal = ""
        if pw and sum(pw):
            mean = sum(pw) / len(pw)
            bal = f"{max(pw) / mean:.2f}" if mean else ""
        trs.append(
            f'<tr><td class="l">#{nid} {html.escape(str(label))}</td>'
            f'<td>{dur * 1e3:.1f}</td>'
            f'<td>{items if items is not None else ""}</td>'
            f'<td>{rate}</td><td>{xb / 1e6:.2f}</td><td>{bal}</td></tr>')
    return ('<h2>stage summary</h2><table><tr><th class="l">stage</th>'
            '<th>ms</th><th>items</th><th>Mitems/s</th>'
            '<th>exchange MB</th><th>balance</th></tr>'
            + "".join(trs) + "</table>")


def _render_stage_worker_matrix(nodes) -> str:
    """Stage x worker item matrix: one row per executed stage, one cell
    per worker shaded by that worker's share of the stage's items —
    the reference report's per-worker lanes, in matrix form."""
    entries = [(nid, n) for nid, n in sorted(nodes.items(),
                                             key=lambda kv: (kv[0] is None,
                                                             kv[0]))
               if nid is not None and n.get("per_worker")]
    if not entries:
        return ""
    W = max(len(n["per_worker"]) for _, n in entries)
    head = "".join(f"<th>w{w}</th>" for w in range(W))
    trs = []
    for nid, n in entries:
        pw = n["per_worker"]
        peak = max(pw) or 1
        cells = []
        for w in range(W):
            v = pw[w] if w < len(pw) else 0
            alpha = v / peak if peak else 0
            cells.append(
                f'<td class="hm" style="background:rgba(0,119,204,'
                f'{alpha:.2f})">{v}</td>')
        trs.append(f'<tr><td class="l">#{nid} '
                   f'{html.escape(str(n.get("label", "?")))}</td>'
                   + "".join(cells) + "</tr>")
    return ('<h2>stage x worker items</h2><table>'
            f'<tr><th class="l">stage</th>{head}</tr>'
            + "".join(trs) + "</table>")


def _render_host_overlay(profiles, total: float) -> str:
    """Host CPU utilization, host RAM in use and device HBM in use on
    one time axis, one polyline set per host (multi-controller logs
    merge into one report)."""
    if not profiles:
        return ""
    hosts = sorted({e.get("host", 0) for _, e in profiles})
    palette = ["#07c", "#e60", "#2a4", "#a3c", "#888"]
    out = []

    def series(pred, norm, title):
        lines, legend = [], []
        for i, h in enumerate(hosts):
            pts = [(t, pred(e)) for t, e in profiles
                   if e.get("host", 0) == h and pred(e) is not None]
            if not pts:
                continue
            top = norm(pts)
            if not top:
                continue
            s = " ".join(f"{100 * t / total:.2f},"
                         f"{78 - 74 * min(v / top, 1.0):.1f}"
                         for t, v in pts)
            color = palette[i % len(palette)]
            lines.append(f'<polyline fill="none" stroke="{color}" '
                         f'stroke-width="0.5" points="{s}"/>')
            legend.append(f'<span style="color:{color}">host{h}</span>')
        if not lines:
            return ""
        return (f'<h2>{title} ({" ".join(legend)})</h2>'
                f'<svg viewBox="0 0 100 80" class="cpu" '
                f'preserveAspectRatio="none">{"".join(lines)}</svg>')

    out.append(series(lambda e: e.get("cpu_util"), lambda p: 1.0,
                      "host CPU utilization"))
    out.append(series(
        lambda e: (e["host_mem_total"] - e["host_mem_available"])
        if e.get("host_mem_total") and e.get("host_mem_available")
        is not None else None,
        lambda p: max(v for _, v in p),
        "host RAM in use"))
    out.append(series(lambda e: e.get("bytes_in_use"),
                      lambda p: max((v for _, v in p), default=0) or None,
                      "device HBM in use"))
    return "".join(out)


def _render_memory_events(memory, total: float) -> str:
    """Memory-pressure timeline: HBM spills/restores, device->host
    demotions, negotiation grants, and the escalation-ladder events
    (admission spills, OOM retries, segment splits — mem/pressure.py)
    as ticks on one lane each (reference: BlockPool occupancy in the
    profile report)."""
    if not memory:
        return ""
    kinds = ["hbm_spill", "hbm_restore", "device_to_host",
             "mem_negotiate", "mem_spill", "oom_retry",
             "segment_split"]
    lanes = []
    for kind in kinds:
        evs = [(t, e) for t, e in memory if e.get("event") == kind]
        if not evs:
            continue
        vol = sum(e.get("bytes", 0) or 0 for _, e in evs)
        marks = "".join(
            f'<div class="mark" style="left:{100 * t / total:.2f}%;'
            f'width:0.4%;height:100%"></div>' for t, _ in evs)
        extra = f" · {vol / 1e6:.1f} MB" if vol else ""
        lanes.append(
            f'<div class="row"><span class="lbl">{kind}</span>'
            f'<div class="track">{marks}</div>'
            f'<span class="dur">{len(evs)} events{extra}</span></div>')
    if not lanes:
        return ""
    return "<h2>memory pressure</h2>" + "".join(lanes)


def _render_io_lane(io_events, overall) -> str:
    """Out-of-core I/O lane (ISSUE 13): per-site prefetch summaries
    (hits/misses/wait), write-behind flush summaries (bytes/jobs), and
    restore-overlap markers, with the run's overlap ledger from
    overall_stats (hit rate, io_wait vs io_busy, write-behind volume,
    queue high-water mark)."""
    if not io_events and not any(
            o.get("io_busy_s") for o in overall):
        return ""
    rows = []
    for _, e in io_events:
        kind = e.get("event")
        if kind == "prefetch":
            detail = (f"hits {e.get('hits', 0)} · misses "
                      f"{e.get('misses', 0)} · wait "
                      f"{e.get('wait_s', 0):.3f}s · depth "
                      f"{e.get('depth', '?')}")
            where = e.get("what") or e.get("path", "?")
        elif kind == "writeback":
            detail = (f"{(e.get('bytes', 0) or 0) / 1e6:.1f} MB · "
                      f"{e.get('jobs', 0)} jobs"
                      f"{' · SYNC' if e.get('sync') else ''}")
            where = e.get("what", "?")
        else:                                   # restore_overlap
            detail = (f"{e.get('prefetched', 0)} of "
                      f"{e.get('blocks', e.get('files', '?'))} blocks "
                      f"prefetched")
            where = e.get("kind", "?")
        rows.append(f"<tr><td class='l'>{kind}</td>"
                    f"<td class='l'>{html.escape(str(where))}</td>"
                    f"<td class='l'>{detail}</td></tr>")
    # one aggregate over every host's overall_stats line (flows sum,
    # the queue peak maxes), through the ONE formula definition
    # (common/iostats.py) so report and stats can never diverge
    from ..common.iostats import hit_rate, overlap_frac
    agg = {"prefetch_hits": 0, "prefetch_misses": 0, "io_wait_s": 0.0,
           "io_busy_s": 0.0, "writeback_bytes": 0,
           "writeback_queue_peak": 0, "restore_overlaps": 0}
    for o in overall:
        for k in agg:
            v = o.get(k, 0) or 0
            agg[k] = max(agg[k], v) if k == "writeback_queue_peak" \
                else agg[k] + v
    summary = ""
    if agg["io_busy_s"]:
        n = agg["prefetch_hits"] + agg["prefetch_misses"]
        summary = (
            f"<p>prefetch hit rate {hit_rate(agg):.2f} "
            f"({agg['prefetch_hits']}/{n})"
            f" · io_wait {agg['io_wait_s']:.3f}s of "
            f"{agg['io_busy_s']:.3f}s busy "
            f"(overlap {overlap_frac(agg):.2f})"
            f" · write-behind {agg['writeback_bytes'] / 1e6:.1f} MB, "
            f"queue peak {agg['writeback_queue_peak']}"
            f" · {agg['restore_overlaps']} overlapped restores</p>")
    if not rows and not summary:
        return ""
    table = ("<table><tr><th class='l'>event</th><th class='l'>site"
             "</th><th class='l'>detail</th></tr>"
             + "".join(rows) + "</table>") if rows else ""
    return "<h2>out-of-core I/O</h2>" + summary + table


def _render_exchange_volume(exchanges, total: float) -> str:
    """Cumulative cross-worker bytes over time, with the DCN share as a
    second line on multi-slice meshes."""
    if not exchanges:
        return ""
    cum = cum_dcn = 0
    pts, pts_dcn = [(0.0, 0)], [(0.0, 0)]
    for t, e in exchanges:
        cum += e.get("bytes", 0)
        cum_dcn += e.get("bytes_dcn", 0)
        pts.append((t, cum))
        pts_dcn.append((t, cum_dcn))
    top = max(cum, 1)

    def line(p, color):
        s = " ".join(f"{100 * t / total:.2f},{118 - 110 * v / top:.1f}"
                     for t, v in p)
        return (f'<polyline fill="none" stroke="{color}" '
                f'stroke-width="0.6" points="{s}"/>')

    dcn = line(pts_dcn, "#e60") if cum_dcn else ""
    return (f'<h2>exchange volume (cumulative {cum / 1e6:.1f} MB'
            f'{f", DCN {cum_dcn / 1e6:.1f} MB" if cum_dcn else ""})</h2>'
            f'<svg viewBox="0 0 100 120" class="vol" '
            f'preserveAspectRatio="none">{line(pts, "#07c")}{dcn}</svg>')


def _render_overlap_lane(exchanges, overall, total: float) -> str:
    """Exchange-overlap lane (data/exchange.py overlapped data plane):
    one tick per device-plane exchange — overlapped dispatches
    (capacity-cache hit, no mid-shuffle host sync) vs synced plans —
    rendered next to the exchange-volume lanes, with the run's overlap
    fraction and capacity-plan cache hit rate from overall_stats."""
    dev = [(t, e) for t, e in exchanges if e.get("event") == "exchange"]
    if not dev:
        return ""
    lanes = []
    for kind, pred in (("overlapped", lambda e: e.get("overlapped")),
                       ("synced plan", lambda e: not e.get("overlapped"))):
        evs = [(t, e) for t, e in dev if pred(e)]
        marks = "".join(
            f'<div class="mark" style="left:{100 * t / total:.2f}%;'
            f'width:0.4%;height:100%"></div>' for t, _ in evs)
        lanes.append(
            f'<div class="row"><span class="lbl">{kind}</span>'
            f'<div class="track">{marks}</div>'
            f'<span class="dur">{len(evs)} exchanges</span></div>')
    summary = ""
    if overall:
        o = overall[-1]
        ex = o.get("exchanges") or 0
        ov = o.get("exchanges_overlapped", 0)
        h, m = o.get("cap_cache_hits", 0), o.get("cap_cache_misses", 0)
        wire = o.get("bytes_on_wire", 0)
        summary = (
            f"<p>overlap fraction <b>{(ov / ex if ex else 0):.0%}</b>"
            f" ({ov}/{ex} exchanges dispatched with no mid-shuffle "
            f"host sync), capacity-plan cache "
            f"{(h / (h + m) if h + m else 0):.0%} hit "
            f"({h} hits / {m} misses), "
            f"{wire / 1e6:.2f} MB on the wire</p>")
    return ("<h2>exchange overlap (capacity-plan cache)</h2>"
            + summary + "".join(lanes))


def _render_wire_lane(overall) -> str:
    """Bytes-on-wire lane (ISSUE 7 shrink-the-wire): actual vs
    raw-equivalent wire volume per plane with the run's compression
    ratio — a wire regression (ratio sliding toward 1.0 on a workload
    that used to compress, or absolute bytes growing) is as loud here
    as a dispatch-budget slip."""
    if not overall:
        return ""
    o = overall[-1]
    wire = o.get("bytes_on_wire", 0)
    raw = o.get("bytes_on_wire_raw", wire)
    if not raw:
        return ""
    ratio = o.get("wire_compress_ratio",
                  round(raw / wire, 3) if wire else 1.0)
    dev = o.get("bytes_wire_device", 0)
    dev_raw = o.get("bytes_wire_device_raw", dev)
    host = o.get("bytes_wire_host", 0)
    host_saved = o.get("bytes_wire_host_saved", 0)
    width = max(wire, raw, 1)
    rows = []
    for label, actual, raw_eq in (
            ("device rows", dev, dev_raw),
            ("host frames", host, host + host_saved)):
        if not raw_eq:
            continue
        pct = 100.0 * actual / width
        pct_raw = 100.0 * raw_eq / width
        rows.append(
            f'<div class="row"><span class="lbl">{label}</span>'
            f'<div class="track">'
            f'<div class="mark" style="left:0;width:{pct_raw:.1f}%;'
            f'height:35%;top:0;background:#ccc"></div>'
            f'<div class="mark" style="left:0;width:{pct:.1f}%;'
            f'height:35%;top:55%"></div></div>'
            f'<span class="dur">{actual / 1e6:.2f} of '
            f'{raw_eq / 1e6:.2f} MB</span></div>')
    return (
        f"<h2>bytes on wire (shrink-the-wire)</h2>"
        f"<p><b>{wire / 1e6:.2f} MB</b> shipped of "
        f"{raw / 1e6:.2f} MB raw-equivalent — compression ratio "
        f"<b>{ratio}x</b> (grey = raw, colored = shipped)</p>"
        + "".join(rows))


def _render_worker_lanes(exchanges, total: float) -> str:
    """One lane per worker: each exchange draws a tick whose height is
    that worker's share of the shipped items (send side) — skew between
    lanes is load imbalance in the data plane."""
    pairs = [(t, e["per_worker_sent"]) for t, e in exchanges
             if e.get("per_worker_sent")]
    if not pairs:
        return ""
    W = max(len(p) for _, p in pairs)
    # tolerate appended logs from runs with different worker counts
    pairs = [(t, p) for t, p in pairs if len(p) == W]
    peak = max((max(p) for _, p in pairs), default=1) or 1
    lanes = []
    for w in range(W):
        sent_total = sum(p[w] for _, p in pairs)
        marks = []
        for t, p in pairs:
            h = max(100.0 * p[w] / peak, 2.0) if p[w] else 0.0
            if h:
                marks.append(
                    f'<div class="mark" style="left:'
                    f'{100 * t / total:.2f}%;width:0.4%;height:{h:.0f}%;'
                    f'top:{100 - h:.0f}%"></div>')
        lanes.append(
            f'<div class="row"><span class="lbl">worker {w}</span>'
            f'<div class="track">{"".join(marks)}</div>'
            f'<span class="dur">{sent_total} items sent</span></div>')
    return "<h2>per-worker exchange lanes</h2>" + "".join(lanes)


def _render_skew_lane(exchanges, overall) -> str:
    """Partition-skew lane (common/doctor.py): per exchange SITE, the
    worst receive-side max/mean ratio and the hot worker it lands on —
    the per-site table behind the run's ``skew_ratio`` summary. A HOT
    verdict (ratio past THRILL_TPU_SKEW_HOT) is the signal to re-key
    or pre-aggregate that operator."""
    from ..common.doctor import fold_skew_sites
    sites = fold_skew_sites(e for _, e in exchanges)
    if not sites:
        return ""
    head = ("<tr><th class='l'>exchange site</th><th>exchanges</th>"
            "<th>items moved</th><th>max skew</th><th>hot worker</th>"
            "<th class='l'>verdict</th></tr>")
    rows = []
    for site, st in sorted(sites.items(), key=lambda kv: -kv[1]["ratio"]):
        verdict = (f"HOT ({st['ratio']:.1f}x the mean on worker "
                   f"{st['worker']})" if st["hot"]
                   else "balanced")
        rows.append(
            f"<tr><td class='l'>{html.escape(site)}</td>"
            f"<td>{st['exchanges']}</td><td>{st['items']}</td>"
            f"<td>{st['ratio']:.2f}x</td><td>{st['worker']}</td>"
            f"<td class='l'>{verdict}</td></tr>")
    summary = ""
    if overall:
        o = overall[-1]
        if o.get("skew_ratio") is not None:
            summary = (f"<p>run skew_ratio {o.get('skew_ratio')} · "
                       f"collective_wait_s "
                       f"{o.get('collective_wait_s', 0)}s (net "
                       f"{o.get('wait_net_s', 0)} / exchange "
                       f"{o.get('wait_exchange_s', 0)} / io "
                       f"{o.get('wait_io_s', 0)} / skew "
                       f"{o.get('wait_skew_s', 0)})</p>")
    return ("<h2>partition skew</h2>" + summary
            + "<table>" + head + "".join(rows) + "</table>")


def main() -> None:
    if len(sys.argv) < 2:
        print("usage: json2profile LOG.json [LOG2.json ...] "
              "> report.html", file=sys.stderr)
        sys.exit(2)
    sys.stdout.write(render_html(load_many(sys.argv[1:])))


if __name__ == "__main__":
    main()
