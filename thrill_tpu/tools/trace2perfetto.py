"""Export thrill_tpu span logs as Chrome-trace-event JSON.

Reads the JSON-lines event logs the tracing spine emits
(``event=span`` records from common/trace.py — the same files
json2profile renders, and flight-recorder dumps work too) and writes
the Chrome trace-event format that loads directly in Perfetto
(ui.perfetto.dev) or chrome://tracing:

* one **pid lane per rank** (multi-controller logs merge into one
  timeline — pass every host's log; the span records carry their
  ``rank``, and the generation/job tags they share are what correlates
  work across controllers);
* one **tid lane per subsystem** (dispatch / fusion / exchange / host /
  net / mem / loop / service), named via thread_name metadata;
* spans become complete (``ph="X"``) events with their correlation
  tags (``trace``/``span``/``parent``, generation, tenant, job) in
  ``args``; instants (ladder rungs, exchange verdicts) become ``ph="i"``
  marks; every OTHER log event (exchange, pipeline_abort, heal,
  job_submit...) lands as an instant on a per-rank ``log`` lane so the
  flat event stream stays visible next to the spans it correlates with.

Usage::

    python -m thrill_tpu.tools.trace2perfetto [--merge] \
        LOG.json [LOG2.json ...] > trace.json

``--merge`` is the explicit multi-host spelling: every rank's log
merges into ONE timeline on the shared timestamp axis — one pid lane
per rank, correlated by the generation/job tags the spans carry.
Records without a ``rank``/``host`` tag take their FILE's index as
the pid lane (an untagged rank's events must not collapse onto rank
0's lane). Passing several logs without the flag behaves identically
— one merge implementation serves both spellings.

(or ``run-scripts/trace_report.sh`` for the one-command demo).
"""

from __future__ import annotations

import json
import sys
from typing import List

from .json2profile import load_many

#: fixed tid per category so lanes are stable across runs/ranks
_LANES = ("service", "loop", "fusion", "dispatch", "exchange", "host",
          "net", "mem", "log")

_TAGS = ("trace", "span", "parent", "generation", "tenant", "job")


def _tid(cat: str) -> int:
    try:
        return _LANES.index(cat)
    except ValueError:
        return len(_LANES)


def _args(e: dict, skip=("event", "ts", "dur_us", "cat", "name",
                         "rank", "host", "kind", "program",
                         "workers")) -> dict:
    return {k: v for k, v in e.items()
            if k not in skip and v is not None}


def to_chrome(events: List[dict]) -> dict:
    """Event dicts (json2profile.load_events/load_many shape) ->
    Chrome trace-event document."""
    out = []
    seen_lanes = set()          # (pid, tid, name) metadata emitted once
    seen_pids = set()
    for e in events:
        ev = e.get("event")
        ts = e.get("ts")
        if ts is None:
            continue
        pid = int(e.get("rank", e.get("host", 0)) or 0)
        if ev == "span":
            cat = str(e.get("cat", "log"))
            name = str(e.get("name", "?"))
            instant = e.get("kind") == "instant" \
                or not e.get("dur_us")
        else:
            # flat log events ride a per-rank "log" lane so aborts,
            # heals and exchanges line up against the spans
            cat, name, instant = "log", str(ev), True
        tid = _tid(cat)
        if pid not in seen_pids:
            seen_pids.add(pid)
            out.append({"ph": "M", "pid": pid, "tid": 0,
                        "name": "process_name",
                        "args": {"name": f"rank {pid}"}})
        if (pid, tid) not in seen_lanes:
            seen_lanes.add((pid, tid))
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": cat}})
        rec = {"pid": pid, "tid": tid, "ts": int(ts), "name": name,
               "cat": cat, "args": _args(e)}
        if instant:
            rec.update(ph="i", s="t")
        else:
            rec.update(ph="X", dur=int(e.get("dur_us", 0)))
        out.append(rec)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def main() -> None:
    # --merge is the explicit multi-host spelling; the merge itself is
    # load_many's contract either way (per-file host default -> one
    # pid lane per rank even in hand-rolled logs; ts-sorted axis) —
    # ONE implementation, so the two spellings cannot drift
    argv = sys.argv[1:]
    if argv and argv[0] == "--merge":
        argv = argv[1:]
    if not argv:
        print("usage: trace2perfetto [--merge] LOG.json "
              "[LOG2.json ...] > trace.json", file=sys.stderr)
        sys.exit(2)
    doc = to_chrome(load_many(argv))
    json.dump(doc, sys.stdout)
    sys.stdout.write("\n")


if __name__ == "__main__":
    main()
