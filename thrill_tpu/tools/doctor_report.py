"""Offline performance-doctor report over JSON event logs.

The in-process doctor (common/doctor.py) diagnoses a live Context;
this tool rebuilds the same report from the event logs a run left
behind — pass every rank's log (multi-controller runs merge by the
``rank`` field exactly like tools/trace2perfetto.py):

* **critical path** — recomputed from the merged ``event=span``
  records (parent chains across job -> exchange -> dispatch), naming
  the top edges by exclusive time;
* **partition skew** — per-site max ``skew_ratio`` / hot worker folded
  from the ``event=exchange`` lines' per-worker receive columns;
* **wait attribution** — the ``collective_wait_s`` decomposition and
  straggler waits from the ``event=overall_stats`` lines: ONE
  cluster-merged line when the run produced one (multi-host ranks
  each log the identical merged stats — summing them would inflate
  P-fold), per-rank local views summed otherwise.

Usage::

    python -m thrill_tpu.tools.doctor_report LOG.json [LOG2.json ...]
"""

from __future__ import annotations

import sys
from typing import List

from ..common.doctor import (critical_path, fold_skew_sites,
                             render_report)
from .json2profile import load_many

_WAIT_KEYS = ("collective_wait_s", "wait_net_s", "wait_exchange_s",
              "wait_io_s", "wait_skew_s")


def build_report(events: List[dict], k: int = 5) -> dict:
    """Doctor-report dict (the common/doctor.py ``report()`` shape)
    from merged event logs."""
    report: dict = {key: 0.0 for key in _WAIT_KEYS}
    waits: dict = {}
    # multi-rank stats dedup: on a P-host run every rank logs the
    # CLUSTER-MERGED overall_stats (the merge stamps "hosts"), so
    # summing all P identical lines would inflate the waits P-fold —
    # use ONE merged line when any exists; per-rank LOCAL views (no
    # "hosts" field: single-host runs, aborted/serving ranks) are
    # genuine partials and sum
    stats_lines = [e for e in events
                   if e.get("event") == "overall_stats"]
    merged = [e for e in stats_lines if e.get("hosts")]
    for e in (merged[:1] if merged else stats_lines):
        for key in _WAIT_KEYS:
            try:
                report[key] += float(e.get(key, 0) or 0)
            except (TypeError, ValueError):
                pass
        for p, w in (e.get("straggler_waits") or {}).items():
            try:
                waits[str(p)] = waits.get(str(p), 0.0) + float(w)
            except (TypeError, ValueError):
                pass
    skew_sites = fold_skew_sites(events)
    report["straggler_waits"] = {
        p: round(w, 4) for p, w in sorted(waits.items())}
    if waits:
        floor = min(waits.values()) if len(waits) > 1 else 0.0
        scores = {p: round(w - floor, 4) for p, w in waits.items()}
        report["straggler_scores"] = dict(sorted(scores.items()))
        best = max(sorted(scores), key=lambda p: scores[p])
        report["straggler_rank"] = (int(best)
                                    if scores[best] > 0 else None)
    report["skew_sites"] = sorted(
        ({"site": s, **st} for s, st in skew_sites.items()),
        key=lambda d: -d["ratio"])
    report["critical_path"] = critical_path(events, k=k)
    return report


def main() -> None:
    if len(sys.argv) < 2:
        print("usage: doctor_report LOG.json [LOG2.json ...]",
              file=sys.stderr)
        sys.exit(2)
    report = build_report(load_many(sys.argv[1:]))
    sys.stdout.write(render_report(report))


if __name__ == "__main__":
    main()
