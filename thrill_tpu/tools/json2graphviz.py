"""Render the DIA DAG from a JSON event log as graphviz dot.

Equivalent of the reference's misc/json2graphviz.py. Usage:

    python -m thrill_tpu.tools.json2graphviz LOG.json > dag.dot
"""

from __future__ import annotations

import sys

from .json2profile import load_events


def render_dot(events) -> str:
    nodes = {}
    edges = set()
    for e in events:
        if e.get("event") == "node_execute_start":
            nid = e.get("dia_id")
            nodes[nid] = e.get("node", "?")
            for p in e.get("parents", []) or []:
                edges.add((p, nid))
        elif e.get("event") == "node_execute_done":
            nid = e.get("dia_id")
            if e.get("items") is not None and nid in nodes:
                nodes[nid] = f"{nodes[nid]}\\n{e['items']} items"
    lines = ["digraph dia {", "  rankdir=TB;",
             '  node [shape=box, fontname="monospace"];']
    for nid, label in sorted(nodes.items()):
        lines.append(f'  n{nid} [label="#{nid} {label}"];')
    for a, b in sorted(edges):
        lines.append(f"  n{a} -> n{b};")
    lines.append("}")
    return "\n".join(lines) + "\n"


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: json2graphviz LOG.json > dag.dot", file=sys.stderr)
        sys.exit(2)
    sys.stdout.write(render_dot(load_events(sys.argv[1])))


if __name__ == "__main__":
    main()
