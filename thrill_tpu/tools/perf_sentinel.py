"""Deterministic perf-contract sentinel.

Wall-clock bench ratios swing 2-7x on shared rigs, so perf regressions
hide in the noise — but the counters the framework already maintains
are DETERMINISTIC for a fixed program: device dispatches (fusion
breaking shows up as a dispatch-count jump), data-driven plan builds
(plan-store/optimism regressions), exchange counts and overlap,
tracked fetches, and the bytes-on-wire totals (the wire codec
silently disabling doubles them). This tool snapshots those counters
per bench-shaped workload into ``PERF_CONTRACT.json`` and diffs a
fresh run against the snapshot:

* **counters** compare EXACTLY — any drift is a contract violation;
* **byte totals** compare ratio-banded (``THRILL_TPU_SENTINEL_BAND``,
  default 0.25): padded capacities may legally wiggle with pow2
  ratcheting, silent 2x regressions may not.

Usage::

    python -m thrill_tpu.tools.perf_sentinel --snapshot [PATH]
    python -m thrill_tpu.tools.perf_sentinel --check    [PATH]

(``run-scripts/perf_sentinel.sh`` wraps both with the env pinned.)
``--check`` exits 1 with a loud per-field diff on any violation. The
contract assumes default knobs: warm plan stores / armed faults are
scrubbed around the measurement (never a legitimate sentinel state),
while counter-relevant knobs like THRILL_TPU_FUSE are deliberately
honored — a knob-skewed run failing on its counters is exactly the
silent-regression class this tool exists to catch (the snapshot's
``env`` note tells the human what the contract ran under).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, List

import numpy as np

#: counters that must match EXACTLY between contract and fresh run.
#: The out-of-core row (ISSUE 15): spilled runs, write-behind bytes,
#: readahead submissions and native-record blocks are deterministic
#: for a fixed program — em_sort settles its spill store at the
#: pre-merge barrier, so residency (and therefore every prefetch
#: submission) is a pure function of the program. A silent fallback
#: from the columnar record format to the pickle spill path moves
#: records_blocks AND writeback_bytes, failing this contract instead
#: of hiding in wall-clock noise. (The em workload assumes the baked
#: toolchain: a compiler-less host runs the python block store, whose
#: eviction order differs.)
COUNTERS = (
    "device_dispatches", "device_uploads", "device_fetches",
    "fused_dispatches", "fused_ops",
    "exchanges", "exchanges_overlapped",
    "cap_cache_hits", "cap_cache_misses",
    "plan_builds", "items_moved",
    "spill_runs", "records_blocks", "prefetch_submits",
    "writeback_bytes",
    # elastic-mesh / service-plane row (ISSUE 16): a resize-free run
    # must report EXACTLY zero resizes and zero admission rejections —
    # the elastic machinery and the bounded submit queue cost nothing
    # when unused. resize_time_ms is derived from resize_time_s in
    # _run_workload; it is only contract-deterministic BECAUSE it must
    # be zero here (wall time appears the moment a resize does, which
    # is itself the violation being caught). jobs_submitted pins the
    # serve workload's job count; the batch workloads report 0.
    "jobs_submitted", "jobs_failed", "jobs_rejected",
    "resizes", "resize_time_ms",
    # remote object store + resumable runs (ISSUE 17): the batch and
    # em workloads above must report EXACTLY zero — the HTTP transport
    # and the run store cost nothing when unused. The em_remote
    # workload pins the transport's request economy (a lost Range
    # header or a dropped reader reopen moves remote_gets; a per-part
    # PUT regression moves remote_puts); em_resume pins the
    # merge-only-restart contract (every committed run reused, zero
    # new spills on the resume leg).
    "remote_gets", "remote_puts", "runs_reused",
    # network front door (ISSUE 18): the batch and serve workloads
    # must report EXACTLY zero on every fd_* counter — a Context that
    # never binds a FrontDoor pays nothing for the socket edge. The
    # front_door workload pins the admission + streaming economy of
    # one real loopback client: conns, submits, chunks, and the clean
    # zero row for sheds/slow-client drops on an unloaded lane.
    "fd_conns_accepted", "fd_conns_dropped", "fd_jobs_submitted",
    "fd_jobs_rejected", "fd_chunks_sent", "fd_slow_clients",
    "fd_deadline_expired",
    # supervised process elasticity (ISSUE 20): every workload here is
    # a fixed-W run that never moves processes, so the process-move
    # counter, the autoscaler's decision count and the orphan-run
    # adoption count must be EXACTLY zero — the drain/seal/relaunch
    # machinery, the scaling policy and the join-time run-store scan
    # cost nothing on a run that never resizes.
    "resizes_proc", "autoscale_decisions", "runs_adopted",
)

#: byte totals compared ratio-banded (pow2 capacity ratchets may move
#: padded volume without a real regression)
BYTE_FIELDS = ("bytes_on_wire", "bytes_on_wire_raw", "bytes_moved")

#: knobs that change the counters — recorded INFORMATIONALLY into the
#: contract (a human diffing a failure sees what the snapshot ran
#: under). Deliberately NOT a comparison guard: "someone ran with
#: THRILL_TPU_FUSE=0" is exactly the silent-regression class the
#: sentinel exists to catch, so a knob-skewed check must fail on the
#: COUNTERS, loudly, not be excused by an env note.
ENV_NOTE = (
    "THRILL_TPU_FUSE", "THRILL_TPU_OVERLAP", "THRILL_TPU_XCHG_CHUNKS",
    "THRILL_TPU_XCHG_CAP_CACHE", "THRILL_TPU_XCHG_NARROW",
    "THRILL_TPU_WIRE_COMPRESS", "THRILL_TPU_PLANNER",
    "THRILL_TPU_EXCHANGE",
    "THRILL_TPU_LOCATION_DETECT", "THRILL_TPU_DUP_DETECT",
    "THRILL_TPU_LOOP_REPLAY", "THRILL_TPU_FORI",
    "THRILL_TPU_NATIVE_RECORDS", "THRILL_TPU_PREFETCH",
    "THRILL_TPU_WRITEBACK",
    "THRILL_TPU_PALLAS", "THRILL_TPU_SORT_IMPL",
    "THRILL_TPU_XCHG_BYTES_EQ", "THRILL_TPU_XCHG_BYTES_EQ_CAL",
)

#: state that is NEVER legitimate during a sentinel measurement — a
#: warm plan store zeroes plan_builds by design and armed faults
#: change retry paths: both are scrubbed around the runs (and
#: restored), so the contract always measures the cold default
#: THRILL_TPU_SERVE_QUEUE is scrubbed too: admission rejections depend
#: on submit-vs-drain TIMING under a finite cap, so a capped serve run
#: can never honor an exact jobs_rejected contract — unlike FUSE-style
#: knobs, whose counter effects are deterministic and therefore
#: deliberately honored
_SCRUB = ("THRILL_TPU_PLAN_STORE", "THRILL_TPU_FAULTS",
          "THRILL_TPU_CKPT_DIR", "THRILL_TPU_RESUME",
          "THRILL_TPU_SERVE_QUEUE",
          # same timing-dependence argument for the edge knobs: rate
          # limits and tenant caps shed by wall clock, and a set
          # SERVE_PORT would auto-bind a front door into EVERY
          # workload's Context, polluting their all-zero fd_* rows
          "THRILL_TPU_SERVE_RATE", "THRILL_TPU_SERVE_TENANT_QUEUE",
          "THRILL_TPU_SERVE_PORT",
          # a set autoscale tick would thread a live policy into every
          # workload's Context; its decisions are wall-clock-timed, so
          # the all-zero autoscale_decisions row is only contract-
          # deterministic with the knob scrubbed
          "THRILL_TPU_AUTOSCALE_S")

VERSION = 1


def _band() -> float:
    try:
        v = float(os.environ.get("THRILL_TPU_SENTINEL_BAND", "0.25"))
    except ValueError:
        return 0.25
    return v if v > 0 else 0.25


# ----------------------------------------------------------------------
# workloads: small, fixed-seed, W=2 — each is a fresh Context so the
# counters depend only on the program, never on a previous workload's
# learned state
# ----------------------------------------------------------------------

def _wc_scale(x):
    return x * 3 + 1


def _wc_odd(x):
    return x % 2 == 1


def _wc_kv(x):
    return (x % 13, x)


def _wc_add(a, b):
    return a + b


def _wordcount(ctx):
    """ReduceByKey-shaped with an LOp stack on top: fusion (the stack
    collapses into the reduce's pre-phase — FUSE=0 moves
    device_dispatches, not just fused_*), hash exchange, preshuffle."""
    return sorted(
        (int(k), int(v)) for k, v in ctx.Distribute(
            np.arange(384, dtype=np.int64)).Map(_wc_scale).Filter(
                _wc_odd).Map(_wc_kv).ReducePair(_wc_add).AllGather())


def _sort(ctx):
    """Sample-sort shaped: splitter agreement + range exchange."""
    rng = np.random.default_rng(11)
    data = rng.integers(0, 1 << 30, size=512).astype(np.int64)
    return ctx.Distribute(data).Sort().AllGather()


def _kv_mod(x):
    return (x % 24, x)


def _kv_ident(x):
    return (x, x * 3)


def _key0(kv):
    return kv[0]


def _join_vals(left, right):
    return (left[1], right[1])


def _joinish(ctx):
    """Hash-join shaped: two shuffles + the pre-shuffle location
    filter's cost-model path — the wire-heaviest contract workload."""
    from ..api.dia import InnerJoin
    left = ctx.Distribute(np.arange(240, dtype=np.int64)).Map(_kv_mod)
    right = ctx.Distribute(np.arange(24, dtype=np.int64)).Map(
        _kv_ident)
    j = InnerJoin(left, right, _key0, _key0, _join_vals)
    return sorted((int(a), int(b)) for a, b in j.AllGather())


def _chain_inc(x):
    return x + 1


def _chain(ctx):
    """Fully-fusible row-local DOp chain: ONE stitched dispatch when
    fusion is healthy, one per DOp when it breaks —
    ``device_dispatches`` is the contract that catches it."""
    return ctx.Distribute(np.arange(256, dtype=np.int64)).PrefixSum() \
        .Map(_chain_inc).ZipWithIndex().AllGather()


def _radix_sort(ctx):
    """Radix-engine sort lane (ISSUE 19): the sample-sort shape forced
    through the LSD radix engine (Pallas stable-partition kernel on
    TPU, the lax.scan partition fallback here). The dispatch/exchange
    counters pin the engine's program economy — a silent fallback to
    another engine (or a dead-pass skip regression) moves them."""
    rng = np.random.default_rng(17)
    data = rng.integers(0, 1 << 30, size=512).astype(np.int64)
    got = [int(x) for x in ctx.Distribute(data).Sort().AllGather()]
    assert got == sorted(int(x) for x in data), "radix_sort diverged"


def _ss_key(t):
    return t["k"]


def _segsum(ctx):
    """Additive FieldReduce lane (ISSUE 19): an f32 'sum' fold, the
    shape the segment-sum kernel serves on TPU (scatter-add fallback
    here — counters are engine-independent). ReduceByKey's shuffle +
    fold economy is this workload's contract."""
    from ..api.functors import FieldReduce
    rng = np.random.default_rng(19)
    n = 768
    ks = rng.integers(0, 48, size=n).astype(np.int64)
    vs = (rng.random(n) * 4).astype(np.float32)
    out = ctx.Distribute({"k": ks, "v": vs}).ReduceByKey(
        _ss_key, FieldReduce({"k": "first", "v": "sum"})).AllGather()
    assert len(out) == len(set(int(k) for k in ks)), "segsum diverged"


def _em_sort(ctx):
    """Host EM sort (ISSUE 15): fixed-seed string items spilled as
    sorted runs through the native columnar record format in a pinned
    disk-resident regime, then k-way merged with readahead. The
    out-of-core counter row (spill_runs / records_blocks /
    prefetch_submits / writeback_bytes) is this workload's contract."""
    rng = np.random.default_rng(23)
    # ~170 KiB spilled: comfortably past the 64 KiB residency floor,
    # so the merge genuinely faults blocks from disk and its readahead
    # submissions are a nonzero, deterministic part of the contract
    items = [f"k-{int(v):09d}" for v in
             rng.integers(0, 1 << 30, size=4096)]
    node = ctx.Distribute(items, storage="host").Sort().node
    hs = node.materialize()
    assert sum(len(lst) for lst in hs.lists) == len(items)


def _em_remote(ctx):
    """Remote storage lane (ISSUE 17): ReadLines -> Sort ->
    WriteLinesOne entirely against the in-repo object server at ZERO
    latency and ZERO failure rate — retries and reopens would make the
    request counts timing-dependent, so the sentinel measures the
    fault-free request economy (the chaos sweep owns the faulted
    paths). remote_gets / remote_puts are this workload's contract: a
    transport that silently stops ranging, re-lists, or splits PUTs
    moves them."""
    from .object_server import ObjectServer
    rng = np.random.default_rng(29)
    lines = sorted(f"r-{int(v):09d}" for v in
                   rng.integers(0, 1 << 30, size=512))
    with ObjectServer() as srv:
        srv.put("b/in-00.txt",
                "\n".join(lines[0::2]).encode() + b"\n")
        srv.put("b/in-01.txt",
                "\n".join(lines[1::2]).encode() + b"\n")
        d = ctx.ReadLines(f"{srv.url}/b/in-*").Sort()
        d.WriteLinesOne(f"{srv.url}/b/out.txt")
        got = ctx.ReadLines(f"{srv.url}/b/out.txt").AllGather()
    assert got == lines, "em_remote: remote roundtrip diverged"


def _er_key(t):
    return t[0]


def _em_resume(ctx):
    """Resumable external runs (ISSUE 17): an EM sort with
    checkpointing on forms + commits its spilled runs, then the SAME
    program relaunches with resume — the second leg must reuse every
    committed run (runs_reused == the first leg's spill count) and
    form ZERO new ones. Both legs run as nested local mocks inside
    the sentinel's outer context: iostats is process-global and the
    outer context reports the delta, so the pair lands in one row."""
    import tempfile
    from ..api.context import RunLocalMock
    from ..common.config import Config
    n = 1600
    data = [(f"k{(i * 7919) % n:05d}", float(i)) for i in range(n)]

    def job(c):
        return c.Distribute(data, storage="host").Sort(
            key_fn=_er_key).AllGather()

    with tempfile.TemporaryDirectory() as td:
        ck = os.path.join(td, "ck")
        first = RunLocalMock(job, 2, config=Config(ckpt_dir=ck))
        again = RunLocalMock(job, 2,
                             config=Config(ckpt_dir=ck, resume=True))
    assert first == again == sorted(data, key=_er_key), \
        "em_resume: resumed sort diverged"


def _serve_wc(ctx):
    return sorted(
        (int(k), int(v)) for k, v in ctx.Distribute(
            np.arange(128, dtype=np.int64)).Map(_wc_kv).ReducePair(
                _wc_add).AllGather())


def _serve_chain(ctx):
    return [int(v) for v in ctx.Distribute(
        np.arange(96, dtype=np.int64)).Map(_chain_inc).PrefixSum()
        .AllGather()]


def _fd_stream(ctx, args):
    for i in range(int(args["k"])):
        yield i * i


def _fd_wc(ctx, args):
    return _serve_wc(ctx)


def _front_door(ctx):
    """Network-edge workload (ISSUE 18): ONE real loopback client
    through a FrontDoor bound to the Context — the full admission
    protocol (auth flag, hello/welcome, framing) plus both result
    modes. Sequential deterministic submits pin the edge's counter
    economy: 1 conn, 3 submits, 1 blob chunk per wc + 4 item chunks,
    zero sheds / slow-client drops / deadline expiries on an unloaded
    loopback lane. The FrontDoor is left attached so the stats capture
    (and the Prometheus surface it feeds) sees the live counters;
    Context.close tears it down like any serving process would."""
    from ..service.client import FrontDoorClient
    from ..service.front_door import FrontDoor
    fd = FrontDoor(ctx, port=0)
    fd.register("wc", _fd_wc)
    fd.register("stream", _fd_stream)
    with FrontDoorClient("127.0.0.1", fd.port, tenant="a") as cli:
        r1 = cli.submit("wc", None).result(120)
        r2 = cli.submit("wc", None).result(120)
        assert r1 == r2, "front_door: repeated job diverged"
        items = list(cli.submit("stream", {"k": 4}).chunks(timeout=120))
        assert items == [0, 1, 4, 9], "front_door: stream diverged"
    # the client's bye lands asynchronously: wait for the drop so
    # fd_conns_dropped is contract-deterministic, bounded not flaky
    deadline = time.monotonic() + 30.0
    while fd.conns_dropped < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fd.conns_dropped == 1, "front_door: bye never landed"


def _serve(ctx):
    """Resize-free serving lane (ISSUE 16): tenant-tagged jobs through
    ``ctx.submit`` on a W=2 mesh that never changes width. The elastic
    row (resizes / resize_time_ms) and the admission counter
    (jobs_rejected) must be EXACTLY zero — the elastic mesh and the
    bounded submit queue cost nothing when a Context never uses them —
    while jobs_submitted pins the lane's job count. Jobs serialize on
    the dispatcher, so the dispatch/exchange counters stay a pure
    function of the program just like the batch workloads."""
    futs = [ctx.submit(_serve_wc, tenant="a", name="wc0"),
            ctx.submit(_serve_chain, tenant="b", name="chain0"),
            ctx.submit(_serve_wc, tenant="a", name="wc1")]
    got = [f.result(timeout=120) for f in futs]
    assert got[0] == got[2], "serve lane: repeated job diverged"


WORKLOADS: Dict[str, Callable] = {
    "wordcount": _wordcount,
    "sort": _sort,
    "radix_sort": _radix_sort,
    "segsum": _segsum,
    "join": _joinish,
    "chain": _chain,
    "em_sort": _em_sort,
    "em_remote": _em_remote,
    "em_resume": _em_resume,
    "serve": _serve,
    "front_door": _front_door,
}

#: per-workload env pins (set around the run, restored after): the em
#: workload needs a deterministic spill regime — a forced run size and
#: a floor-pinned resident budget — regardless of the rig's RAM
ENV_PINS: Dict[str, Dict[str, str]] = {
    # the radix lane forces its engine; both new ISSUE-19 lanes pin
    # the bytes_eq calibration off so the dense/1-factor choice never
    # depends on this rig's measured launch overhead
    "radix_sort": {"THRILL_TPU_SORT_IMPL": "radix",
                   "THRILL_TPU_XCHG_BYTES_EQ_CAL": "0"},
    "segsum": {"THRILL_TPU_XCHG_BYTES_EQ_CAL": "0"},
    "em_sort": {"THRILL_TPU_HOST_SORT_RUN": "256",
                "THRILL_TPU_SPILL_RESIDENT": "64K"},
    # the resume pair needs the SAME forced run size on both legs so
    # run identities match; a fast retry base keeps the (fault-free)
    # remote lane from sleeping if the rig's loopback hiccups
    "em_resume": {"THRILL_TPU_HOST_SORT_RUN": "200",
                  "THRILL_TPU_SPILL_RESIDENT": "64K"},
    "em_remote": {"THRILL_TPU_RETRY_BASE_S": "0.01"},
}


def _run_workload(fn, workers: int = 2, pins=None) -> dict:
    from ..api.context import RunLocalMock
    stats_box = {}

    def job(ctx):
        fn(ctx)
        stats_box.update(ctx.overall_stats())

    saved = {k: os.environ.get(k) for k in (pins or {})}
    os.environ.update(pins or {})
    try:
        RunLocalMock(job, workers)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    out = {k: int(stats_box.get(k, 0)) for k in COUNTERS}
    # derived: resize wall time in whole ms — int() on the raw seconds
    # would truncate a 0.9 s resize to 0 and hide exactly the
    # machinery-engaged-when-unused violation this field exists for
    out["resize_time_ms"] = int(round(
        float(stats_box.get("resize_time_s", 0.0)) * 1000))
    out.update({k: int(stats_box.get(k, 0)) for k in BYTE_FIELDS})
    return out


def snapshot(workloads=None, workers: int = 2) -> dict:
    """Run each workload on a fresh W=``workers`` mesh and collect its
    counter contract."""
    # unknown names (a contract from a newer checkout) simply don't
    # run — diff() then reports them missing, loudly
    names = [n for n in (workloads or WORKLOADS) if n in WORKLOADS]
    saved = {k: os.environ.pop(k) for k in _SCRUB if k in os.environ}
    try:
        runs = {name: _run_workload(WORKLOADS[name], workers,
                                    pins=ENV_PINS.get(name))
                for name in names}
    finally:
        os.environ.update(saved)
    return {
        "version": VERSION,
        "workers": workers,
        "env": {k: os.environ.get(k) for k in ENV_NOTE
                if os.environ.get(k) is not None},
        "workloads": runs,
    }


def diff(contract: dict, fresh: dict) -> List[str]:
    """Violations of ``fresh`` against ``contract`` (empty = clean).
    The env note is NOT compared — a knob-skewed run must fail on the
    counters themselves (that is the regression class being hunted),
    with the recorded env available for the human reading the diff."""
    problems: List[str] = []
    if contract.get("version") != fresh.get("version"):
        problems.append(
            f"contract version {contract.get('version')} != "
            f"{fresh.get('version')} (re-snapshot)")
        return problems
    band = _band()
    for name, want in contract.get("workloads", {}).items():
        got = fresh.get("workloads", {}).get(name)
        if got is None:
            problems.append(f"{name}: workload missing from fresh run")
            continue
        for k in COUNTERS:
            if int(got.get(k, 0)) != int(want.get(k, 0)):
                problems.append(
                    f"{name}.{k}: {want.get(k, 0)} -> {got.get(k, 0)} "
                    f"(exact counter contract)")
        for k in BYTE_FIELDS:
            w, g = int(want.get(k, 0)), int(got.get(k, 0))
            if w == 0 and g == 0:
                continue
            lo, hi = w * (1 - band), w * (1 + band)
            if not (lo <= g <= hi):
                problems.append(
                    f"{name}.{k}: {w} -> {g} "
                    f"(outside the +/-{band:.0%} byte band)")
    for name in fresh.get("workloads", {}):
        if name not in contract.get("workloads", {}):
            problems.append(
                f"{name}: not in the contract (re-snapshot to adopt)")
    return problems


def default_path() -> str:
    """PERF_CONTRACT.json at the repo root (next to bench.py) when run
    from a checkout, else the current directory. The checkout test is
    the bench.py marker — the package grandparent always EXISTS (the
    module was imported from it), so a mere isdir check would route a
    pip-installed run's contract next to site-packages."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isfile(os.path.join(here, "bench.py")):
        return os.path.join(here, "PERF_CONTRACT.json")
    return os.path.abspath("PERF_CONTRACT.json")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    mode = None
    if argv and argv[0] in ("--snapshot", "--check"):
        mode = argv.pop(0)
    if mode is None:
        print("usage: perf_sentinel --snapshot|--check "
              "[PERF_CONTRACT.json]", file=sys.stderr)
        return 2
    path = argv.pop(0) if argv else default_path()
    # the virtual W=2 CPU mesh needs the device-count flag BEFORE jax
    # initializes (no-op when the harness already set it)
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    from ..common.platform import force_cpu_platform
    force_cpu_platform()
    if mode == "--snapshot":
        snap = snapshot()
        with open(path, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"perf_sentinel: contract written to {path} "
              f"({len(snap['workloads'])} workloads)")
        return 0
    try:
        with open(path) as f:
            contract = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perf_sentinel: cannot read contract {path}: {e}",
              file=sys.stderr)
        return 2
    fresh = snapshot(workloads=contract.get("workloads"))
    problems = diff(contract, fresh)
    if problems:
        print(f"perf_sentinel: {len(problems)} contract violation(s) "
              f"vs {path}:", file=sys.stderr)
        for p in problems:
            print(f"  REGRESSION {p}", file=sys.stderr)
        return 1
    print(f"perf_sentinel: clean — "
          f"{len(contract.get('workloads', {}))} workloads match "
          f"{path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
