"""In-repo S3-compatible object server: the CI stand-in for slow remote
storage.

A ThreadingHTTPServer speaking the subset of the S3 REST protocol that
``vfs/object_store.py`` uses — ListObjectsV2, ranged GET (206 +
Content-Range), single-shot PUT, the multipart protocol (initiate /
per-part PUT / complete / abort), HEAD, DELETE — with two injection
knobs that make it a *latency rig*, not just a correctness mock:

* ``latency_s``: every request sleeps this long before answering —
  the "each GET costs 20ms" regime the prefetch/write-behind overlap
  must beat (bench's em-remote lane, the tier-1 remote sweeps);
* ``fail_rate`` (seeded) / ``fail_next(n)``: requests answer 503, so
  the shared retry policy's transient classification and the
  reopen-at-offset recovery get exercised end-to-end over a real
  socket, not just via injected exceptions.

Objects live in a dict keyed ``bucket/key``; threads serve
concurrently (prefetch issues overlapping GETs). Usable in-process::

    with ObjectServer(latency_s=0.02) as srv:
        ctx.ReadLines(f"{srv.url}/bucket/input-*") ...

or standalone: ``python -m thrill_tpu.tools.object_server --latency-ms
20``. ``tests/vfs/object_server.py`` re-exports this module for the
test tree.
"""

from __future__ import annotations

import argparse
import random
import threading
import time
import urllib.parse
import uuid
from hashlib import md5
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from xml.sax.saxutils import escape


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "thrill-tpu-object-server/1"

    # -- plumbing -------------------------------------------------------
    def log_message(self, fmt, *args):  # pragma: no cover - quiet
        pass

    def _split(self) -> Tuple[str, Dict[str, str]]:
        u = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(u.query,
                                        keep_blank_values=True))
        return urllib.parse.unquote(u.path).lstrip("/"), q

    def _pre(self) -> bool:
        """Injection gate: per-request latency, then scripted/random
        failures. False = a 503 was sent, stop handling."""
        srv = self.server
        with srv.lock:
            srv.requests += 1
            lat = srv.latency_s
            fail = srv.fail_next > 0
            if fail:
                srv.fail_next -= 1
            elif srv.fail_rate > 0.0:
                fail = srv.rng.random() < srv.fail_rate
        if lat > 0.0:
            time.sleep(lat)
        if fail:
            self._reply(503, b"injected failure")
            return False
        return True

    def _reply(self, status: int, body: bytes = b"",
               headers: Optional[Dict[str, str]] = None,
               head_only: bool = False) -> None:
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body and not head_only:
            self.wfile.write(body)

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length", "0") or 0)
        return self.rfile.read(n) if n else b""

    # -- verbs ----------------------------------------------------------
    def do_GET(self) -> None:
        if not self._pre():
            return
        key, q = self._split()
        srv = self.server
        if "list-type" in q:
            with srv.lock:
                srv.lists += 1
            self._list(key.strip("/"), q.get("prefix", ""))
            return
        with srv.lock:
            srv.gets += 1
            data = srv.objects.get(key)
        if data is None:
            self._reply(404, b"<Error><Code>NoSuchKey</Code></Error>")
            return
        rng = self.headers.get("Range")
        if rng and srv.honor_range:
            try:
                spec = rng.split("=", 1)[1]
                lo_s, _, hi_s = spec.partition("-")
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else len(data) - 1
            except (IndexError, ValueError):
                self._reply(416, b"bad range")
                return
            if lo >= len(data):
                self._reply(416, b"range out of bounds")
                return
            hi = min(hi, len(data) - 1)
            part = data[lo:hi + 1]
            self._reply(206, part, {
                "Content-Range": f"bytes {lo}-{hi}/{len(data)}"})
            return
        self._reply(200, data)

    def do_HEAD(self) -> None:
        if not self._pre():
            return
        key, _ = self._split()
        with self.server.lock:
            data = self.server.objects.get(key)
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        # HEAD: size rides in Content-Length, no body follows
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_PUT(self) -> None:
        if not self._pre():
            return
        key, q = self._split()
        body = self._body()
        srv = self.server
        if "partNumber" in q and "uploadId" in q:
            uid = q["uploadId"]
            num = int(q["partNumber"])
            with srv.lock:
                srv.puts += 1
                up = srv.uploads.get(uid)
                if up is None or up[0] != key:
                    self._reply(404, b"<Error><Code>NoSuchUpload"
                                     b"</Code></Error>")
                    return
                up[1][num] = body
            etag = f'"{md5(body).hexdigest()}"'
            self._reply(200, b"", {"ETag": etag})
            return
        with srv.lock:
            srv.puts += 1
            srv.objects[key] = body
        self._reply(200, b"", {"ETag": f'"{md5(body).hexdigest()}"'})

    def do_POST(self) -> None:
        if not self._pre():
            return
        key, q = self._split()
        srv = self.server
        if "uploads" in q:
            uid = uuid.uuid4().hex
            with srv.lock:
                srv.uploads[uid] = (key, {})
            body = (f"<InitiateMultipartUploadResult>"
                    f"<Key>{escape(key)}</Key>"
                    f"<UploadId>{uid}</UploadId>"
                    f"</InitiateMultipartUploadResult>").encode()
            self._reply(200, body)
            return
        if "uploadId" in q:
            self._body()             # CompleteMultipartUpload XML
            uid = q["uploadId"]
            with srv.lock:
                up = srv.uploads.pop(uid, None)
                if up is None or up[0] != key:
                    self._reply(404, b"<Error><Code>NoSuchUpload"
                                     b"</Code></Error>")
                    return
                srv.objects[key] = b"".join(
                    up[1][n] for n in sorted(up[1]))
            body = (f"<CompleteMultipartUploadResult>"
                    f"<Key>{escape(key)}</Key>"
                    f"</CompleteMultipartUploadResult>").encode()
            self._reply(200, body)
            return
        self._reply(400, b"unsupported POST")

    def do_DELETE(self) -> None:
        if not self._pre():
            return
        key, q = self._split()
        srv = self.server
        if "uploadId" in q:
            with srv.lock:
                srv.uploads.pop(q["uploadId"], None)
            self._reply(204)
            return
        with srv.lock:
            srv.objects.pop(key, None)
        self._reply(204)

    # -- ListObjectsV2 --------------------------------------------------
    def _list(self, bucket: str, prefix: str) -> None:
        srv = self.server
        want = f"{bucket}/{prefix}"
        with srv.lock:
            hits = sorted((k, len(v)) for k, v in srv.objects.items()
                          if k.startswith(want))
        rows = "".join(
            f"<Contents><Key>{escape(k.split('/', 1)[1])}</Key>"
            f"<Size>{sz}</Size></Contents>"
            for k, sz in hits)
        body = (f"<ListBucketResult>"
                f"<Name>{escape(bucket)}</Name>"
                f"<Prefix>{escape(prefix)}</Prefix>"
                f"<KeyCount>{len(hits)}</KeyCount>"
                f"<IsTruncated>false</IsTruncated>"
                f"{rows}</ListBucketResult>").encode()
        self._reply(200, body)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        # keep-alive clients drop connections mid-wait constantly
        # (each transport request opens a fresh connection and closes
        # it after the response) — that is not an error worth a
        # traceback on the test's stderr
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, BrokenPipeError,
                            TimeoutError)):
            return
        super().handle_error(request, client_address)


class ObjectServer:
    """One in-process object store on 127.0.0.1:<ephemeral>.

    ``objects`` maps ``bucket/key`` → bytes and may be seeded/inspected
    directly. ``latency_s``/``fail_rate``/``fail_next()`` inject the
    slow-and-flaky regime; ``gets``/``puts``/``lists``/``requests``
    count what actually hit the wire. ``honor_range=False`` simulates a
    server that ignores Range (the client must then fail loudly rather
    than silently restart from byte 0)."""

    def __init__(self, latency_s: float = 0.0, fail_rate: float = 0.0,
                 seed: int = 0) -> None:
        self._httpd = _Server(("127.0.0.1", 0), _Handler)
        h = self._httpd
        h.lock = threading.Lock()
        h.objects = {}
        h.uploads = {}
        h.latency_s = float(latency_s)
        h.fail_rate = float(fail_rate)
        h.fail_next = 0
        h.rng = random.Random(seed)
        h.honor_range = True
        h.requests = h.gets = h.puts = h.lists = 0
        self._thread = threading.Thread(
            target=h.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name="thrill-tpu-object-server")
        self._thread.start()

    # -- addressing -----------------------------------------------------
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- state ----------------------------------------------------------
    @property
    def objects(self) -> Dict[str, bytes]:
        return self._httpd.objects

    def put(self, key: str, data: bytes) -> None:
        with self._httpd.lock:
            self._httpd.objects[key] = data

    def stats(self) -> Dict[str, int]:
        h = self._httpd
        with h.lock:
            return {"requests": h.requests, "gets": h.gets,
                    "puts": h.puts, "lists": h.lists}

    # -- injection ------------------------------------------------------
    def set_latency(self, latency_s: float) -> None:
        with self._httpd.lock:
            self._httpd.latency_s = float(latency_s)

    def set_fail_rate(self, rate: float, seed: int = 0) -> None:
        with self._httpd.lock:
            self._httpd.fail_rate = float(rate)
            self._httpd.rng = random.Random(seed)

    def fail_next(self, n: int) -> None:
        """The next ``n`` requests answer 503, deterministically."""
        with self._httpd.lock:
            self._httpd.fail_next += int(n)

    def set_honor_range(self, honor: bool) -> None:
        with self._httpd.lock:
            self._httpd.honor_range = bool(honor)

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)

    def __enter__(self) -> "ObjectServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def main(argv=None) -> int:          # pragma: no cover - manual tool
    ap = argparse.ArgumentParser(
        description="standalone S3-compatible mock object server")
    ap.add_argument("--latency-ms", type=float, default=0.0)
    ap.add_argument("--fail-rate", type=float, default=0.0)
    args = ap.parse_args(argv)
    srv = ObjectServer(latency_s=args.latency_ms / 1e3,
                       fail_rate=args.fail_rate)
    print(srv.url, flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.close()
    return 0


if __name__ == "__main__":           # pragma: no cover
    raise SystemExit(main())
