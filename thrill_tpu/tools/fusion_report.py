"""Fused-vs-unfused dispatch report over the example pipelines.

Runs WordCount (text -> packed words -> Map -> ReduceByKey) and
PageRank (the iterative join/reduce pipeline) twice each — program
stitching on (default) and THRILL_TPU_FUSE=0 — and prints the device
dispatch counts plus the delta. On a tunneled chip every dispatch is a
link round trip (140.7 ms measured, BASELINE.md r5), so the delta
column is wall-clock the fusion planner buys per run.

Usage::

    python -m thrill_tpu.tools.fusion_report [--pages N] [--edges M]
        [--iters K] [--words N]

(or ``run-scripts/fusion_report.sh``). Exercises the real pipelines,
so it doubles as an end-to-end parity check: both modes' results are
compared exactly before any number is printed.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile


def _run_wordcount(ctx, mex, path):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "..", "..", "examples"))
    import word_count as wc
    out = wc.word_count_text_device(ctx, path).AllGatherArrays()
    import jax
    import numpy as np
    cols = jax.tree.map(np.asarray, out)
    order = np.lexsort(tuple(cols["w"].T))
    return {k: v[order] for k, v in sorted(cols.items())}


def _run_pagerank(ctx, mex, edges, pages, iters):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "..", "..", "examples"))
    import page_rank as pr
    return pr.page_rank(ctx, edges, pages, iterations=iters)


def _measure(name, job):
    """Run ``job(fuse)`` for both modes (one warm-up run each so
    compile/caches don't pollute the counts) and return the row."""
    import numpy as np
    counts = {}
    results = {}
    prev = os.environ.get("THRILL_TPU_FUSE")
    try:
        for fuse in ("1", "0"):
            os.environ["THRILL_TPU_FUSE"] = fuse
            job()                                # warm: compile+cache
            d0 = _MEX.stats_dispatches
            results[fuse] = job()
            counts[fuse] = _MEX.stats_dispatches - d0
    finally:
        # restore the caller's setting — the report used to leave
        # THRILL_TPU_FUSE=0 behind, silently unfusing everything run
        # in the same process afterwards
        if prev is None:
            os.environ.pop("THRILL_TPU_FUSE", None)
        else:
            os.environ["THRILL_TPU_FUSE"] = prev
    assert np.allclose(np.asarray(results["1"], dtype=np.float64),
                       np.asarray(results["0"], dtype=np.float64)), \
        f"{name}: fused and unfused results diverge"
    return (name, counts["0"], counts["1"],
            counts["0"] - counts["1"],
            counts["0"] / max(counts["1"], 1))


_MEX = None


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--pages", type=int, default=256)
    ap.add_argument("--edges", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--words", type=int, default=4096)
    args = ap.parse_args()

    # the jitted engines are what fusion stitches; the CPU-native
    # fallbacks would sidestep the thing being measured
    os.environ.setdefault("THRILL_TPU_HOST_RADIX", "0")

    import numpy as np
    from thrill_tpu.api import Context
    from thrill_tpu.parallel.mesh import MeshExec

    global _MEX
    _MEX = mex = MeshExec()
    ctx = Context(mex)

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "..", "..", "examples"))
    import page_rank as pr

    rng = np.random.default_rng(0)
    vocab = ["w%03d" % i for i in range(97)]
    text = " ".join(rng.choice(vocab, size=args.words))
    edges = pr.zipf_graph(args.pages, args.edges)

    rows = []
    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as f:
        f.write(text + "\n")
        path = f.name
    try:
        def wc_leaves():
            cols = _run_wordcount(ctx, mex, path)
            return np.concatenate([np.asarray(v, np.float64).reshape(-1)
                                   for v in cols.values()])

        rows.append(_measure("WordCount", wc_leaves))
        rows.append(_measure(
            "PageRank",
            lambda: _run_pagerank(ctx, mex, edges, args.pages,
                                  args.iters)))
    finally:
        os.unlink(path)

    print(f"{'pipeline':<12} {'unfused':>8} {'fused':>8} "
          f"{'delta':>8} {'ratio':>7}")
    for name, unf, fus, delta, ratio in rows:
        print(f"{name:<12} {unf:>8} {fus:>8} {delta:>8} {ratio:>6.2f}x")
    stats = ctx.overall_stats()
    stages = stats.get("fused_stages") or {}
    if stages:
        print("\nfused stage compositions (this process):")
        for ops, n in sorted(stages.items(), key=lambda kv: -kv[1]):
            print(f"  {n:>5}x  {ops}")
    ctx.close()


if __name__ == "__main__":
    main()
