"""Benchmark: TeraSort record throughput on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The line is emitted UNCONDITIONALLY — on any backend failure the bench
falls back to a forced-CPU run, and on a fatal error it still prints
the line with an "error" field (reference guarantee analog: the mock
backend always works, /root/reference/thrill/net/mock/group.hpp:41).

The north-star workload (BASELINE.md) is TeraSort — 100-byte records
with 10-byte keys through the full DIA Sort pipeline. The reference
C++ framework cannot be built in this image (extlib submodules tlx/
foxxll are not checked out and there is no network), so ``vs_baseline``
compares against the strongest available host-side proxy measured in
the same run: numpy's lexsort-based TeraSort of the identical records
on the host CPU. vs_baseline = device_throughput / host_throughput.

Platform selection is hazard-aware for this image: the globally
exported ``JAX_PLATFORMS=axon`` plugin can HANG (not raise) at PJRT
client init when its tunnel is unhealthy, so accelerator health is
probed in a throwaway subprocess with a timeout before the parent
process commits to a backend.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

RESULT = {
    "metric": "terasort_throughput",
    "value": 0.0,
    "unit": "Mrecords/s",
    "vs_baseline": 0.0,
    "platform": "none",
}
_STATE_LOCK = threading.Lock()
_emitted = False


def _set(**kv):
    """Record result fields; safe against the watchdog thread."""
    with _STATE_LOCK:
        RESULT.update(kv)


def _emit(**extra):
    """Print the one JSON line exactly once."""
    global _emitted
    with _STATE_LOCK:
        if _emitted:
            return
        _emitted = True
        RESULT.update(extra)
        payload = json.dumps(RESULT)
    print(payload, flush=True)


def _watchdog(seconds: float):
    """Guarantee the JSON line even if the backend wedges mid-run."""

    def fire():
        try:
            _emit(error=f"watchdog: bench exceeded {seconds:.0f}s, "
                        f"emitting fallback line")
        finally:
            os._exit(0)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _probe_accelerator(timeout_s: float) -> str | None:
    """Ask a throwaway subprocess which backend jax picks. Returns the
    platform name, or None if init fails OR hangs past the timeout."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM=' + d[0].platform)")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print("bench: accelerator probe timed out; forcing CPU",
              file=sys.stderr)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORM="):
            plat = line.split("=", 1)[1].strip()
            if plat and plat != "cpu":
                return plat
    print(f"bench: accelerator probe failed (rc={out.returncode}); "
          f"forcing CPU", file=sys.stderr)
    return None


def _host_terasort(keys: np.ndarray, values: np.ndarray):
    """numpy proxy baseline: pack key words, lexsort, gather."""
    w0 = np.zeros(len(keys), dtype=np.uint64)
    w1 = np.zeros(len(keys), dtype=np.uint64)
    for i in range(8):
        w0 = (w0 << np.uint64(8)) | keys[:, i].astype(np.uint64)
    for i in range(8, 10):
        w1 = (w1 << np.uint64(8)) | keys[:, i].astype(np.uint64)
    w1 <<= np.uint64(48)
    perm = np.lexsort((w1, w0))
    return keys[perm], values[perm]


def _key_fn(r):
    """Module-level key extractor: stable identity -> the Sort executable
    compiles once and is reused across timed iterations."""
    return r["key"]


def _run_bench() -> None:
    want_cpu = os.environ.get("JAX_PLATFORMS") == "cpu"
    if not want_cpu:
        try:
            probe_timeout = float(
                os.environ.get("THRILL_TPU_BENCH_PROBE_TIMEOUT_S", "150"))
        except ValueError:
            probe_timeout = 150.0
        platform = _probe_accelerator(probe_timeout)
        want_cpu = platform is None

    import jax

    if want_cpu:
        from thrill_tpu.common.platform import force_cpu_platform
        force_cpu_platform()

    try:  # persistent compile cache: axon compiles cost ~40s/program
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/thrill_tpu_xla"))
    except Exception:
        pass

    import thrill_tpu  # noqa: F401  (enables x64)
    from thrill_tpu.api import Context
    from thrill_tpu.parallel.mesh import MeshExec

    platform = jax.default_backend()
    _set(platform=platform)
    default_n = 1 << 20 if platform != "cpu" else 1 << 18
    try:
        n = int(os.environ.get("THRILL_TPU_BENCH_N", "") or default_n)
    except ValueError:
        n = default_n
    if n < 1024:
        print(f"bench: clamping n={n} to 1024 (minimum)", file=sys.stderr)
        n = 1024
    _set(n=n)

    rng = np.random.default_rng(0)
    recs = {
        "key": rng.integers(0, 256, size=(n, 10)).astype(np.uint8),
        "value": rng.integers(0, 256, size=(n, 90)).astype(np.uint8),
    }

    mex = MeshExec()  # all local devices (1 real TPU chip under axon)
    ctx = Context(mex)

    # ingest once (reference TeraSort reads its input once, too); the
    # timed iterations measure the Sort pipeline itself, not the
    # host->device upload of the same 100 MB through the tunnel. The
    # upload cost is still reported (upload_s field).
    inp = ctx.Distribute(recs)
    t0 = time.perf_counter()
    jax.block_until_ready(jax.tree.leaves(
        inp.node.materialize(consume=False).tree))
    _set(upload_s=round(time.perf_counter() - t0, 3))

    def run_once():
        inp.Keep()
        out = inp.Sort(key_fn=_key_fn)
        shards = out.node.materialize()
        leaves = jax.tree.leaves(shards.tree)
        jax.block_until_ready(leaves)
        # few-byte readback: forces completion even if the experimental
        # backend's block_until_ready returns early (costs one RTT)
        np.asarray(leaves[0][0, :1])
        return shards

    run_once()                      # warmup + compile
    iters = 3
    t0 = time.perf_counter()
    for _ in range(iters):
        run_once()
    dt = (time.perf_counter() - t0) / iters

    # host proxy baseline on identical data
    t0 = time.perf_counter()
    _host_terasort(recs["key"], recs["value"])
    host_dt = time.perf_counter() - t0

    mrec_s = n / dt / 1e6
    host_mrec_s = n / host_dt / 1e6

    # secondary north-star metric (BASELINE.md): WordCount ReduceByKey
    # items/sec on the device path, vs a collections.Counter host proxy
    wc = _wordcount_metric(ctx, n)
    # tertiary: host-storage EM sort (spill + native k-way merge) vs
    # Python sorted() on the same strings — platform-independent, so it
    # reports the host engine even in a TPU window
    em = _em_sort_metric(ctx)

    _emit(value=round(mrec_s, 3),
          vs_baseline=round(mrec_s / host_mrec_s, 3), **wc, **em)
    ctx.close()


def _wc_key(t):
    return t["w"]


def _wordcount_metric(ctx, n: int) -> dict:
    """WordCount throughput: n packed words, zipf-ish key skew, full
    device ReduceByKey; proxy = collections.Counter over the strings.
    The reduce functor is the declarative FieldReduce — the idiomatic
    WordCount spelling here, matching the reference's std::plus functor
    (examples/word_count/word_count.hpp) which its templates likewise
    inline into the aggregation loop."""
    import collections
    from thrill_tpu.api import FieldReduce
    try:
        rng = np.random.default_rng(1)
        vocab_n = max(1024, n // 64)
        ids = np.minimum(rng.zipf(1.3, size=n) - 1, vocab_n - 1)
        words = np.zeros((n, 16), dtype=np.uint8)
        digits = np.char.zfill(ids.astype("U8"), 8)   # 8-char ids
        words[:, :8] = np.frombuffer(
            "".join(digits.tolist()).encode("ascii"),
            dtype=np.uint8).reshape(n, 8)
        import jax
        d = ctx.Distribute({"w": words,
                            "c": np.ones(n, dtype=np.int64)})
        d.Keep()

        red = FieldReduce({"w": "first", "c": "sum"})

        def once():
            d.Keep()
            out = d.ReduceByKey(_wc_key, red)
            sh = out.node.materialize()
            jax.block_until_ready(jax.tree.leaves(sh.tree))
            np.asarray(jax.tree.leaves(sh.tree)[0])[:1]

        once()
        t0 = time.perf_counter()
        once()
        dt = time.perf_counter() - t0
        strs = ["".join(map(chr, row)) for row in words]
        t0 = time.perf_counter()
        collections.Counter(strs)
        host_dt = time.perf_counter() - t0
        return {"wordcount_mitems_s": round(n / dt / 1e6, 3),
                "wordcount_vs_counter": round(host_dt / dt, 3)}
    except Exception as e:  # secondary metric never kills the line
        return {"wordcount_error": repr(e)[:200]}


def _em_sort_metric(ctx) -> dict:
    """Host EM sort throughput (forced spills, ~40 runs of 1M string
    items): native byte-key engine (core/order_key.py +
    native/mwmerge.cpp) A/B'd in-run against the generic
    Python-comparison engine on identical machinery. (The headline
    speedup vs the ROUND-3 code is 3.6x at 10M — BASELINE.md; an
    in-memory sorted() is not a meaningful baseline for an
    external-memory spill+merge pipeline.)"""
    try:
        n = 1 << 20
        rng = np.random.default_rng(3)
        items = [f"key-{v:014d}" for v in
                 rng.integers(0, 1 << 48, size=n).tolist()]
        prev = {k: os.environ.get(k) for k in
                ("THRILL_TPU_HOST_SORT_RUN", "THRILL_TPU_EM_MERGE")}
        os.environ["THRILL_TPU_HOST_SORT_RUN"] = str(n // 40)

        def run_once(data):
            d = ctx.Distribute(list(data), storage="host")
            t0 = time.perf_counter()
            hs = d.Sort().node.materialize()
            dt = time.perf_counter() - t0
            return dt, sum(len(l) for l in hs.lists)

        try:
            # warmup: a small EM sort pays the one-time native build /
            # ctypes load OUTSIDE the timed window (_wordcount_metric
            # warms up the same way). Must exceed run_size (n/40) or
            # the warmup takes the in-memory path and loads nothing.
            run_once(items[: 1 << 15])
            dt, got_n = run_once(items)
            os.environ["THRILL_TPU_EM_MERGE"] = "py"
            py_dt, _ = run_once(items)
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if got_n != n:
            return {"em_sort_error": f"lost items: {got_n}/{n}"}
        return {"em_sort_mitems_s": round(n / dt / 1e6, 3),
                "em_sort_vs_py_engine": round(py_dt / dt, 3)}
    except Exception as e:  # tertiary metric never kills the line
        return {"em_sort_error": repr(e)[:200]}


def main():
    try:
        watchdog_s = float(
            os.environ.get("THRILL_TPU_BENCH_WATCHDOG_S", "2700"))
    except ValueError:
        watchdog_s = 2700.0
    _watchdog(watchdog_s)
    try:
        _run_bench()
    except BaseException as e:  # noqa: BLE001 — the line must go out
        _emit(error=repr(e)[:500])
        raise SystemExit(0)


if __name__ == "__main__":
    main()
